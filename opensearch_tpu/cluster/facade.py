"""ClusterFacade: the TpuNode API surface served by a cluster.

The reference funnels every request through ONE RestController + NodeClient
in front of one action registry regardless of cluster size
(rest/RestController.java:285, action/ActionModule.java:527). This module
is that unification for the TPU build: rest/handlers.py's 128 routes run
unchanged against this object — its methods carry TpuNode's signatures but
execute with cluster semantics:

- metadata ops route to the elected leader and ride cluster-state
  publication;
- document ops route to primaries by murmur3(_routing) % shards and ack
  after full replication (TransportReplicationAction semantics);
- searches fan out ONE request per data node holding shards of the target
  index (search[node] returns a wire partial over all its local shards)
  and reduce on the coordinator (search/reduce.py:
  SearchPhaseController.mergeTopDocs + InternalAggregations.reduce);
- scroll/PIT pin per-node reader contexts; the cluster scroll id encodes
  {node -> ctx} so ANY node can continue a scroll.

Threading: facade methods are called from the HTTP executor thread and
bridge onto the transport event loop (call_soon_threadsafe + futures); the
loop thread never blocks in here.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
from concurrent.futures import Future
from typing import Any, Callable

from opensearch_tpu import __version__
from opensearch_tpu.common.errors import (
    DocumentMissingException,
    IllegalArgumentException,
    IndexNotFoundException,
    OpenSearchTpuException,
    ResourceAlreadyExistsException,
    SearchContextMissingException,
    VersionConflictException,
)
from opensearch_tpu.common.settings import (
    Settings,
    setting_str,
    settings_section as _settings_section,
)
from opensearch_tpu.index.mapper import MapperService

logger = logging.getLogger(__name__)

RPC_TIMEOUT_S = 30.0

# transport errors arrive as "ExceptionName: reason" strings; map the names
# back to typed exceptions so REST status codes survive the wire
_ERROR_TYPES = {}


def _register_error_types() -> None:
    import opensearch_tpu.common.errors as err_mod

    for name in dir(err_mod):
        obj = getattr(err_mod, name)
        if isinstance(obj, type) and issubclass(obj, OpenSearchTpuException):
            _ERROR_TYPES[name] = obj


_register_error_types()


def rehydrate_error(message) -> OpenSearchTpuException:
    # loopback sends deliver the exception object itself — keep its type
    if isinstance(message, OpenSearchTpuException):
        return message
    if isinstance(message, Exception):
        message = str(message)
    name, _, reason = str(message).partition(":")
    cls = _ERROR_TYPES.get(name.strip())
    if cls is not None:
        try:
            return cls(reason.strip())
        except TypeError:
            pass
    return OpenSearchTpuException(str(message))


class _IndexView:
    """Read-only IndexService stand-in built from cluster state."""

    def __init__(self, meta, mapper_service: MapperService):
        self.name = meta.name
        self.num_shards = meta.num_shards
        self.num_replicas = meta.num_replicas
        self.settings = dict(meta.settings or {})
        self.mapper_service = mapper_service
        self.aliases: dict[str, dict] = dict(
            (meta.settings or {}).get("_aliases", {})
        )
        self.shards: dict[int, Any] = {}


class ClusterFacade:
    def __init__(self, cluster_node, loop):
        self.node = cluster_node
        self.loop = loop
        self.node_name = cluster_node.node_id
        self._mapper_cache: dict[tuple[str, int], MapperService] = {}
        # node-local services (the reference's are node-local too)
        from opensearch_tpu.tasks.manager import TaskManager

        self.task_manager = TaskManager(cluster_node.node_id)
        # one telemetry per node process: facade (coordinator role) spans
        # and the data-plane handler spans share this node's ring, so
        # _nodes/stats and /_prometheus/metrics see both
        self.telemetry = cluster_node.telemetry
        from opensearch_tpu.index.request_cache import (
            CACHE_SIZE_SETTING,
            RequestCache,
        )

        self.request_cache = RequestCache()

        def _apply_cache_size(eff: dict) -> None:
            from opensearch_tpu.common.settings import Settings

            self.request_cache.set_max_bytes(
                CACHE_SIZE_SETTING.get(Settings.from_flat(eff)))

        cluster_node.settings_consumers.register(
            CACHE_SIZE_SETTING.key, _apply_cache_size)
        # the request cache is coordinator-side (it lives with the REST
        # surface, not the data plane): register it as a stats provider so
        # the cluster-wide _nodes/stats fan-out reports THIS node's cache
        # alongside the data-plane sections
        cluster_node.stats_providers["request_cache"] = \
            self.request_cache.stats
        # the kNN dispatch batcher is process-wide (one process == one
        # device); the facade shares it so cluster-mode stats see the same
        # coalescing the data plane performs
        from opensearch_tpu.search import batcher as _batcher_mod

        self.knn_batcher = _batcher_mod.default_batcher
        from opensearch_tpu.common.monitor import MonitorService

        self.monitor = MonitorService(cluster_node.data_path)
        # one wlm registry per node process: the facade (search admission)
        # and the cluster node (bulk admission) must see the same groups
        # and share the same slot budgets
        self.query_groups = cluster_node.query_groups
        # the facade keeps its OWN lane tracker for the HTTP boundary:
        # sharing the cluster node's cells would double-count every
        # coordinator-local request (once at REST submit, again when its
        # search[node]/msearch[node] leg lands on this node's search
        # pools) and halve the effective background_max_queue shed bound.
        # The node's `tail` stats section reports BOTH trackers (the
        # boundary one as `http_lanes` — that is where the bounded
        # background queue actually sheds).
        from opensearch_tpu.search import lanes as _lanes_mod

        self.lane_tracker = _lanes_mod.LaneTracker()
        cluster_node.http_lane_tracker = self.lane_tracker
        self.tail_stats = cluster_node.tail_stats
        from opensearch_tpu.persistent import PersistentTasksService

        self.persistent_tasks = PersistentTasksService(
            cluster_node.data_path / "persistent_tasks.json"
        )

    # ------------------------------------------------------------------ #
    # loop bridging
    # ------------------------------------------------------------------ #

    def _on_loop(self, fn: Callable[[Callable[[dict], None]], None]) -> dict:
        """Run callback-style `fn(callback)` on the transport loop; block
        this (executor) thread for the response."""
        fut: Future = Future()

        def run() -> None:
            try:
                fn(lambda resp: fut.done() or fut.set_result(resp))
            except Exception as e:  # noqa: BLE001
                if not fut.done():
                    fut.set_exception(e)

        self.loop.call_soon_threadsafe(run)
        resp = fut.result(timeout=RPC_TIMEOUT_S)
        if isinstance(resp, dict) and "error" in resp and set(resp) <= {
            "error", "status"
        }:
            raise rehydrate_error(resp["error"])
        return resp

    def _rpc(self, target: str, action: str, payload: dict) -> dict:
        """One transport round-trip from the executor thread."""
        def fn(callback):
            self.node.transport.send(
                self.node.node_id, target, action, payload,
                on_response=callback,
                on_failure=lambda e: callback(
                    {"error": e if isinstance(e, OpenSearchTpuException)
                     else str(e), "status": 500}
                ),
            )
        return self._on_loop(fn)

    def _rpc_many(self, calls: list[tuple[str, str, dict]]) -> list[dict]:
        """Concurrent fan-out; preserves call order in the result list."""
        fut: Future = Future()
        results: list = [None] * len(calls)
        remaining = [len(calls)]

        def run() -> None:
            def one(i: int):
                def ok(resp) -> None:
                    results[i] = resp
                    remaining[0] -= 1
                    if remaining[0] == 0 and not fut.done():
                        fut.set_result(results)

                def fail(e: Exception) -> None:
                    ok({"error": e if isinstance(e, OpenSearchTpuException)
                        else str(e), "status": 500})

                return ok, fail

            for i, (target, action, payload) in enumerate(calls):
                ok, fail = one(i)
                self.node.transport.send(
                    self.node.node_id, target, action, payload,
                    on_response=ok, on_failure=fail,
                )

        if not calls:
            return []
        self.loop.call_soon_threadsafe(run)
        return fut.result(timeout=RPC_TIMEOUT_S)

    # ------------------------------------------------------------------ #
    # state views
    # ------------------------------------------------------------------ #

    @property
    def state(self):
        return self.node.applied_state

    def _meta(self, index: str):
        meta = self.state.indices.get(index)
        if meta is None:
            raise IndexNotFoundException(index)
        return meta

    def _mapper_for(self, index: str) -> MapperService:
        meta = self._meta(index)
        key = (index, meta.version)
        ms = self._mapper_cache.get(key)
        if ms is None:
            ms = MapperService(meta.mappings or None)
            self._mapper_cache[key] = ms
            for k in [k for k in self._mapper_cache
                      if k[0] == index and k[1] != meta.version]:
                del self._mapper_cache[k]
        return ms

    @property
    def indices(self) -> dict[str, _IndexView]:
        return {
            name: _IndexView(meta, self._mapper_for(name))
            for name, meta in self.state.indices.items()
        }

    def resolve_indices(self, expr: str) -> list[str]:
        import fnmatch as _fn

        names = sorted(self.state.indices)
        if expr in ("_all", "*", "", None):
            return names
        out: list[str] = []
        for part in str(expr).split(","):
            part = part.strip()
            if not part:
                continue
            if "*" in part or "?" in part:
                out.extend(n for n in names
                           if _fn.fnmatch(n, part) and n not in out)
            else:
                if part not in self.state.indices:
                    raise IndexNotFoundException(part)
                if part not in out:
                    out.append(part)
        return out

    # ------------------------------------------------------------------ #
    # index lifecycle (leader-routed)
    # ------------------------------------------------------------------ #

    def create_index(self, name: str, body: dict | None = None) -> dict:
        if name in self.state.indices:
            raise ResourceAlreadyExistsException(
                f"index [{name}] already exists"
            )
        leader = self._leader()
        resp = self._rpc(leader, "cluster:admin/create_index",
                         {"name": name, "body": body or {}})
        self._wait_active_primaries(name)
        return resp

    def delete_index(self, name: str) -> dict:
        for n in self.resolve_indices(name):
            self._rpc(self._leader(), "cluster:admin/delete_index",
                      {"name": n})
        return {"acknowledged": True}

    def put_mapping(self, index: str, body: dict) -> dict:
        return self._rpc(self._leader(), "cluster:admin/put_mapping",
                         {"name": index, "mappings": body or {}})

    def get_mapping(self, index: str, *, ignore_unavailable: bool = False,
                    allow_no_indices: bool = True,
                    expand_wildcards: str = "open") -> dict:
        names = self.resolve_indices(index)
        return {
            name: {"mappings": self._mapper_for(name).to_dict()}
            for name in names
        }

    def get_settings(self, index: str, *, name: str | None = None,
                     flat: bool = False, include_defaults: bool = False,
                     expand_wildcards: str = "all") -> dict:
        """Same contract as TpuNode.get_settings (name filter, flat vs
        nested shape, defaults section) over the replicated metadata,
        via the shared index_settings_entry shaping."""
        from opensearch_tpu.node import index_settings_entry

        out = {}
        for idx_name in self.resolve_indices(index):
            meta = self._meta(idx_name)
            raw = {k: v for k, v in (meta.settings or {}).items()
                   if not k.startswith("_")}
            out[idx_name] = index_settings_entry(
                raw, num_shards=meta.num_shards,
                num_replicas=meta.num_replicas,
                name=name, flat=flat, include_defaults=include_defaults,
            )
        return out

    def _leader(self) -> str:
        leader = self.node.coordinator.leader_id
        if leader is None:
            raise OpenSearchTpuException("no elected cluster manager")
        return leader

    def _wait_active_primaries(self, index: str, timeout_s: float = 10.0) -> None:
        # real-thread poll against live TCP nodes; the facade never runs
        # under the virtual-time sim, and the deadline must track REAL
        # time here — reading the injectable clock would freeze this loop
        # if another component installs a VirtualClock process-wide
        import time as _t

        deadline = _t.monotonic() + timeout_s  # tpulint: disable=TPU004
        while _t.monotonic() < deadline:  # tpulint: disable=TPU004
            entries = [r for r in self.state.routing
                       if r.index == index and r.primary]
            if entries and all(r.state == "STARTED" for r in entries):
                return
            _t.sleep(0.05)  # tpulint: disable=TPU004

    # ------------------------------------------------------------------ #
    # documents
    # ------------------------------------------------------------------ #

    def _auto_id(self) -> str:
        """Auto document ids draw from the node's scheduler RNG — the
        injectable entropy source (seeded under the deterministic sim,
        time-seeded by LoopScheduler in production). uuid4/os.urandom
        would defeat sim replayability (tpulint TPU006)."""
        return "%020x" % self.node.scheduler.random.getrandbits(80)

    def index_doc(self, index: str, doc_id: str | None, source: dict,
                  routing: str | None = None, if_seq_no: int | None = None,
                  refresh: bool = False, op_type: str | None = None,
                  pipeline: str | None = None, version: int | None = None,
                  version_type: str = "internal",
                  if_primary_term: int | None = None) -> dict:
        if if_primary_term is not None and int(if_primary_term) != 1:
            raise VersionConflictException(
                f"[{doc_id}]: version conflict, required primaryTerm "
                f"[{if_primary_term}], current primaryTerm [1]"
            )
        if pipeline is not None:
            self._unsupported("ingest pipelines")
        if version is not None:
            self._unsupported("explicit document versions in cluster mode")
        if doc_id is None:
            doc_id = self._auto_id()
        resp = self._on_loop(lambda cb: self.node.index_doc(
            index, doc_id, source, cb, routing=routing,
            if_seq_no=if_seq_no, op_type=op_type,
        ))
        if refresh:
            self.refresh(index)
        return resp

    def get_doc(self, index: str, doc_id: str,
                routing: str | None = None, realtime: bool = True,
                version: int | None = None, refresh: bool = False) -> dict:
        got = self._on_loop(lambda cb: self.node.get_doc(
            index, doc_id, cb, routing=routing
        ))
        if version is not None and got.get("found") \
                and got.get("_version") != version:
            from opensearch_tpu.common.errors import VersionConflictException

            raise VersionConflictException(
                f"[{doc_id}]: version conflict, current version "
                f"[{got.get('_version')}] is different than the one "
                f"provided [{version}]"
            )
        return got

    def delete_doc(self, index: str, doc_id: str, routing: str | None = None,
                   refresh: bool = False, if_seq_no: int | None = None,
                   version: int | None = None,
                   version_type: str = "internal") -> dict:
        if version is not None or if_seq_no is not None:
            self._unsupported("versioned deletes in cluster mode")
        resp = self._on_loop(lambda cb: self.node.delete_doc(
            index, doc_id, cb, routing=routing
        ))
        if refresh:
            self.refresh(index)
        return resp

    def update_doc(self, index: str, doc_id: str, body: dict,
                   routing: str | None = None, refresh: bool = False,
                   if_seq_no: int | None = None,
                   require_alias: bool = False) -> dict:
        """Coordinator-side read-modify-write with optimistic concurrency
        (UpdateHelper semantics over the cluster write path)."""
        current = self.get_doc(index, doc_id, routing=routing)
        if if_seq_no is not None:
            current_seq = current.get("_seq_no") if current.get("found") else -1
            if current_seq != if_seq_no:
                from opensearch_tpu.common.errors import (
                    VersionConflictException,
                )

                raise VersionConflictException(
                    f"[{doc_id}]: version conflict, required seqNo "
                    f"[{if_seq_no}], current document has seqNo "
                    f"[{current_seq}]"
                )
        exists = current.get("found")
        if "script" in body:
            from opensearch_tpu.script import default_script_service

            if not exists:
                if "upsert" in body:
                    return self.index_doc(index, doc_id, body["upsert"],
                                          routing=routing, refresh=refresh)
                raise DocumentMissingException(f"[{doc_id}]: document missing")
            ctx = {"_source": dict(current["_source"]), "op": "index",
                   "_index": index, "_id": doc_id}
            ast, params = default_script_service.compile(body["script"])
            default_script_service.execute_update(ast, params, ctx)
            if ctx.get("op") in ("none", "noop"):
                return {"_index": index, "_id": doc_id, "result": "noop",
                        "_shards": {"total": 0, "successful": 0, "failed": 0}}
            if ctx.get("op") == "delete":
                return self.delete_doc(index, doc_id, routing=routing,
                                       refresh=refresh)
            out = self.index_doc(index, doc_id, ctx["_source"],
                                 routing=routing, refresh=refresh,
                                 if_seq_no=current.get("_seq_no"))
            out["result"] = "updated"
            return out
        if "doc" in body:
            if not exists:
                if body.get("doc_as_upsert"):
                    return self.index_doc(index, doc_id, body["doc"],
                                          routing=routing, refresh=refresh)
                raise DocumentMissingException(f"[{doc_id}]: document missing")
            merged = _deep_merge(dict(current["_source"]), body["doc"])
            out = self.index_doc(index, doc_id, merged, routing=routing,
                                 refresh=refresh,
                                 if_seq_no=current.get("_seq_no"))
            out["result"] = "updated"
            return out
        if "upsert" in body and not exists:
            return self.index_doc(index, doc_id, body["upsert"],
                                  routing=routing, refresh=refresh)
        raise IllegalArgumentException("update requires [doc] or [upsert]")

    def bulk(self, operations, refresh: bool = False,
             pipeline: str | None = None,
             payload_bytes: int | None = None,
             query_group: str | None = None) -> dict:
        if pipeline is not None:
            self._unsupported("ingest pipelines")
        ops = []
        for action, meta, source in operations:
            meta = dict(meta)
            if action in ("index", "create") and not meta.get("_id"):
                meta["_id"] = self._auto_id()
            ops.append((action, meta, source))
        resp = self._on_loop(
            lambda cb: self.node.bulk(ops, cb, query_group=query_group))
        if refresh:
            touched = {m.get("_index") for _a, m, _s in ops if m.get("_index")}
            for idx in touched:
                try:
                    self.refresh(idx)
                except OpenSearchTpuException:
                    pass
        return resp

    def mget(self, index: str | None, body: dict,
             realtime: bool = True, refresh: bool = False,
             stored_fields: list | None = None) -> dict:
        docs_spec = body.get("docs")
        if docs_spec is None and "ids" in body:
            docs_spec = [{"_id": i} for i in body["ids"]]
        if docs_spec is None:
            raise IllegalArgumentException("mget requires docs or ids")
        docs = []
        for spec in docs_spec:
            idx = spec.get("_index", index)
            try:
                docs.append(self.get_doc(idx, spec["_id"],
                                         routing=spec.get("routing")))
            except OpenSearchTpuException as e:
                docs.append({"_index": idx, "_id": spec.get("_id"),
                             "error": e.to_dict()})
        return {"docs": docs}

    # ------------------------------------------------------------------ #
    # search (per-node fan-out + coordinator reduce)
    # ------------------------------------------------------------------ #

    def _node_assignments(
        self, names: list[str], body: dict | None = None,
    ) -> list[tuple[str, str, list[int]]]:
        """[(node_id, index, [shard_nums])] — one entry per (node, index).
        Bare-kNN bodies route RESIDENCY-AWARE (cluster/residency.py): each
        shard's launch lands on the copy whose mesh bundle / IVF-PQ slab
        the board knows to be HBM-resident, round-robin when no copy is
        warm; everything else keeps the prefer-primary selection."""
        from opensearch_tpu.cluster import residency as residency_mod

        state = self.state
        field = residency_mod.knn_query_field(body) if body else None
        out: dict[tuple[str, str], list[int]] = {}
        for name in names:
            meta = self._meta(name)
            candidates: dict[int, list] = {}
            for r in state.shards_for_index(name):
                # RELOCATING sources still serve until the routing swap
                if r.state not in ("STARTED", "RELOCATING") or r.node_id is None:
                    continue
                candidates.setdefault(r.shard, []).append(r)
            if len(candidates) < meta.num_shards:
                raise OpenSearchTpuException(
                    f"not all shards of [{name}] are available"
                )
            if field is not None:
                targets, _warm = residency_mod.choose_copies(
                    self.node.residency_board, name, field, candidates,
                    next(self.node._route_rr))
            else:
                targets = {
                    num: next((r for r in cands if r.primary), cands[0])
                    for num, cands in candidates.items()
                }
            for num, r in targets.items():
                out.setdefault((r.node_id, name), []).append(num)
        return [(nid, idx, sorted(nums)) for (nid, idx), nums in
                sorted(out.items())]

    def search(self, index: str | None = None, body: dict | None = None,
               scroll: str | None = None,
               search_pipeline: str | None = None,
               ignore_unavailable: bool = False,
               request_cache: bool | None = None,
               query_group: str | None = None,
               allow_partial_search_results: bool = True) -> dict:
        from opensearch_tpu.search.reduce import (
            check_cluster_aggs_supported,
            reduce_search_responses,
        )

        body = dict(body or {})
        if "pit" in body:
            return self._pit_search(body)
        if search_pipeline is not None:
            raise IllegalArgumentException(
                "search pipelines are not yet supported in cluster mode"
            )
        if "suggest" in body:
            raise IllegalArgumentException(
                "suggest is not yet supported in cluster mode"
            )
        query = body.get("query") or {}
        if "hybrid" in query:
            raise IllegalArgumentException(
                "hybrid queries are not yet supported in cluster mode"
            )
        aggs_body = body.get("aggs") or body.get("aggregations")
        check_cluster_aggs_supported(aggs_body)

        names = self.resolve_indices(index if index is not None else "_all")
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        track_total = body.get("track_total_hits", True)
        keep = scroll is not None
        if keep and from_ > 0:
            raise IllegalArgumentException(
                "[from] is not allowed in a scroll context"
            )
        keep_alive_ms = (
            _parse_keep_alive_ms(scroll) if keep else None
        )

        node_body = dict(body)
        node_body["from"] = 0
        node_body["size"] = from_ + size
        node_body["track_total_hits"] = True  # coordinator applies the cap
        # wlm search admission BEFORE the fan-out (the bulk twin): an
        # enforced group past its slot share sheds a typed 429 here —
        # RejectedExecutionException surfaces through the REST envelope —
        # and burns no transport or device work
        release_admission = self.query_groups.admit_search(query_group)
        try:
            return self._search_fanned(
                names, body, node_body, size, from_, track_total, keep,
                keep_alive_ms, index, allow_partial_search_results)
        finally:
            release_admission()

    def _search_fanned(self, names, body, node_body, size, from_,
                       track_total, keep, keep_alive_ms, index,
                       allow_partial_search_results) -> dict:
        from opensearch_tpu.search.reduce import reduce_search_responses

        from opensearch_tpu.search import lanes as lanes_mod

        assignments = self._node_assignments(names, body)
        lane = lanes_mod.active_lane()
        from opensearch_tpu.telemetry import tracing

        tracer = self.telemetry.tracer
        with tracing.activate(tracer), tracer.start_span(
            "search.coordinator",
            {"indices": ",".join(names), "node": self.node_name,
             "fanout": len(assignments)},
        ):
            # the per-node RPCs capture this span's context
            # (call_soon_threadsafe copies the executor thread's context)
            partials = self._rpc_many([
                (nid, "indices:data/read/search[node]",
                 {"index": idx, "shards": nums, "body": node_body,
                  "keep_context": keep, "keep_alive_ms": keep_alive_ms,
                  "lane": lane})
                for nid, idx, nums in assignments
            ])
            # residency stamps teach the coordinator's board which copies
            # are warm BEFORE the next fan-out routes (pop so the stamp
            # never reaches the reduce)
            for (nid, idx, _nums), p in zip(assignments, partials):
                if isinstance(p, dict):
                    res = p.pop("_residency", None)
                    if isinstance(res, dict) and res.get("field"):
                        self.node.residency_board.observe(
                            nid, idx, res["field"], bool(res.get("warm")))
            # a scroll must pin a context on EVERY node, so partial
            # tolerance only applies to plain searches
            if keep or not allow_partial_search_results:
                self._raise_partial_errors(partials)
            ok, failures = self._split_partials(assignments, partials)
            if failures and not ok:
                self._raise_partial_errors(partials)
            with tracer.start_span("search.reduce", {
                "node": self.node_name, "partials": len(ok),
            }):
                resp = reduce_search_responses(
                    body, [p for _a, p in ok], size=size, from_=from_,
                    track_total=track_total
                )
            if failures:
                # degrade, don't wedge: unreachable nodes' shards count as
                # failed (allow_partial_search_results=true semantics) and
                # the per-shard failure reasons ride along
                failed_shards = sum(len(nums) for (_n, _i, nums), _e
                                    in failures)
                resp["_shards"]["total"] += failed_shards
                resp["_shards"]["failed"] += failed_shards
                # one failures entry PER SHARD (the reference's shape),
                # so the list length matches the failed count
                resp["_shards"]["failures"] = [
                    {"node": nid, "index": idx, "shard": num,
                     "reason": {"reason": str(err)}}
                    for (nid, idx, nums), err in failures
                    for num in (nums or [-1])
                ]
            # same request metrics the single-node path records, so
            # /_prometheus/metrics is useful in cluster mode too; INSIDE
            # the coordinator span so the histogram exemplar captures this
            # trace id (a p99 bucket links straight to the trace)
            self.telemetry.metrics.counter("search.total").add(1)
            self.telemetry.metrics.histogram("search.took_ms").record(
                resp.get("took", 0))
            # per-index series under the same constant name (labels, not
            # names — TPU013; the registry bounds label cardinality)
            if index and "*" not in str(index) and "," not in str(index):
                self.telemetry.metrics.histogram(
                    "search.took_ms", labels={"index": str(index)},
                ).record(resp.get("took", 0))
            # per-LANE series (ISSUE 11): interactive vs background tails
            # separate under the same constant family name
            self.telemetry.metrics.histogram(
                "search.took_ms", labels={"lane": lane},
            ).record(resp.get("took", 0))
        if keep:
            contexts = {
                f"{nid}|{idx}": p["_ctx_id"]
                for (nid, idx, _nums), p in zip(assignments, partials)
            }
            seen = len(resp["hits"]["hits"])
            resp["_scroll_id"] = _encode_scroll_id({
                "ctx": contexts, "seen": seen, "size": size,
                "sort": body.get("sort"),
            })
        return resp

    @staticmethod
    def _raise_partial_errors(partials: list[dict]) -> None:
        for p in partials:
            if isinstance(p, dict) and "error" in p and "hits" not in p:
                raise rehydrate_error(p["error"])

    @staticmethod
    def _split_partials(
        assignments: list[tuple], partials: list[dict],
    ) -> tuple[list[tuple], list[tuple]]:
        """Partition per-node partials into (ok, failures): ok entries are
        (assignment, partial), failures are (assignment, error). Query-shape
        errors (parse failures — every node rejects identically) are raised
        immediately: degrading them to partial results would mask a client
        bug as a transient outage."""
        ok: list[tuple] = []
        failures: list[tuple] = []
        for a, p in zip(assignments, partials):
            if isinstance(p, dict) and "error" in p and "hits" not in p:
                err = rehydrate_error(p["error"])
                if isinstance(err, (IllegalArgumentException,)) or \
                        "ParsingException" in str(p["error"]):
                    raise err
                failures.append((a, err))
            else:
                ok.append((a, p))
        return ok, failures

    def scroll(self, scroll_id: str, scroll: str | None = None) -> dict:
        from opensearch_tpu.search.reduce import reduce_hits

        state = _decode_scroll_id(scroll_id)
        seen, size = state["seen"], state["size"]
        calls = []
        for key, ctx_id in state["ctx"].items():
            nid, _, idx = key.partition("|")
            calls.append((nid, "indices:data/read/search[ctx]",
                          {"ctx_id": ctx_id, "from": 0,
                           "size": seen + size}))
        partials = self._rpc_many(calls)
        self._raise_partial_errors(partials)
        sort = state.get("sort")
        if isinstance(sort, (str, dict)):
            sort = [sort]
        hits_obj = reduce_hits(partials, size=size, from_=seen, sort=sort,
                               track_total=True)
        state["seen"] = seen + len(hits_obj["hits"])
        shards_total = sum(
            (p.get("_shards") or {}).get("total", 0) for p in partials
        )
        return {
            "took": 0, "timed_out": False,
            "_shards": {"total": shards_total, "successful": shards_total,
                        "skipped": 0, "failed": 0},
            "hits": hits_obj,
            "_scroll_id": _encode_scroll_id(state),
        }

    def clear_scroll(self, scroll_ids: list[str] | None) -> dict:
        freed = 0
        for sid in scroll_ids or []:
            try:
                state = _decode_scroll_id(sid)
            except Exception as e:  # noqa: BLE001 - malformed id: skip
                logger.debug("clear_scroll: malformed scroll id: %s", e)
                continue
            by_node: dict[str, list[str]] = {}
            for key, ctx_id in state["ctx"].items():
                nid = key.partition("|")[0]
                by_node.setdefault(nid, []).append(ctx_id)
            results = self._rpc_many([
                (nid, "indices:data/read/ctx_close", {"ctx_ids": ids})
                for nid, ids in by_node.items()
            ])
            freed += sum(r.get("freed", 0) for r in results
                         if isinstance(r, dict))
        return {"succeeded": True, "num_freed": freed}

    def open_pit(self, index: str, keep_alive: str) -> dict:
        names = self.resolve_indices(index)
        assignments = self._node_assignments(names)
        partials = self._rpc_many([
            (nid, "indices:data/read/search[node]",
             {"index": idx, "shards": nums,
              "body": {"query": {"match_all": {}}, "size": 0},
              "keep_context": True,
              "keep_alive_ms": _parse_keep_alive_ms(keep_alive)})
            for nid, idx, nums in assignments
        ])
        self._raise_partial_errors(partials)
        contexts = {
            f"{nid}|{idx}": p["_ctx_id"]
            for (nid, idx, _nums), p in zip(assignments, partials)
        }
        total = sum((p.get("_shards") or {}).get("total", 0)
                    for p in partials)
        pit_id = "cpit_" + _encode_scroll_id({"ctx": contexts})
        from opensearch_tpu.common.timeutil import epoch_millis

        return {"pit_id": pit_id,
                "_shards": {"total": total, "successful": total,
                            "skipped": 0, "failed": 0},
                "creation_time": epoch_millis()}

    def close_pit(self, pit_ids: list[str] | None) -> dict:
        pits = []
        for pid in pit_ids or []:
            ok = True
            try:
                state = _decode_scroll_id(pid.removeprefix("cpit_"))
                by_node: dict[str, list[str]] = {}
                for key, ctx_id in state["ctx"].items():
                    by_node.setdefault(key.partition("|")[0], []).append(ctx_id)
                self._rpc_many([
                    (nid, "indices:data/read/ctx_close", {"ctx_ids": ids})
                    for nid, ids in by_node.items()
                ])
            except Exception as e:  # noqa: BLE001
                logger.debug("delete_pit: context close failed: %s", e)
                ok = False
            pits.append({"pit_id": pid, "successful": ok})
        return {"pits": pits}

    def _pit_search(self, body: dict) -> dict:
        from opensearch_tpu.search.reduce import reduce_search_responses

        pit = body.pop("pit")
        pit_id = pit["id"] if isinstance(pit, dict) else pit
        state = _decode_scroll_id(str(pit_id).removeprefix("cpit_"))
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        track_total = body.get("track_total_hits", True)
        node_body = dict(body)
        node_body["from"] = 0
        node_body["size"] = from_ + size
        node_body["track_total_hits"] = True
        calls = [
            (key.partition("|")[0], "indices:data/read/search[ctx]",
             {"ctx_id": ctx_id, "body": node_body})
            for key, ctx_id in state["ctx"].items()
        ]
        partials = self._rpc_many(calls)
        self._raise_partial_errors(partials)
        resp = reduce_search_responses(
            body, partials, size=size, from_=from_, track_total=track_total
        )
        resp["pit_id"] = pit_id
        return resp

    def msearch(self, searches: list[tuple[dict, dict]]) -> dict:
        """Runs of consecutive bare-knn sub-searches against the SAME index
        ship to each data node as ONE msearch[node] RPC, whose query phase
        is a single batched device dispatch (B query vectors in one program
        launch); everything else fans out per sub-search like the
        reference's TransportMultiSearchAction."""
        from opensearch_tpu.search.service import msearch_groups

        responses: list[dict | None] = [None] * len(searches)
        for group in msearch_groups(searches):
            index = searches[group[0]][0].get("index")
            grouped = None
            if len(group) > 1:
                grouped = self._msearch_knn_group(
                    index, [searches[g][1] for g in group]
                )
            if grouped is not None:
                for g, resp in zip(group, grouped):
                    responses[g] = resp
                continue
            # whole group serial (each member still eligible for the
            # single-query device path on its data node)
            for g in group:
                try:
                    responses[g] = self.search(
                        searches[g][0].get("index"), searches[g][1])
                except OpenSearchTpuException as e:
                    responses[g] = {"error": e.to_dict(), "status": e.status}
        return {"took": 0, "responses": responses}

    def _msearch_knn_group(
        self, index: str, bodies: list[dict]
    ) -> list[dict] | None:
        """One msearch[node] RPC per data node for a batchable knn group;
        reduce each body's partials exactly like search(). Returns None to
        send the group down the serial path (e.g. resolution errors)."""
        from opensearch_tpu.search.reduce import reduce_search_responses

        try:
            names = self.resolve_indices(index)
            # residency routing sees the first body (the group shares one
            # knn field); msearch fan-outs are background-lane work
            assignments = self._node_assignments(names, bodies[0])
            node_bodies = []
            for body in bodies:
                nb = dict(body)
                nb["from"] = 0
                nb["size"] = int(body.get("from", 0)) + int(body.get("size", 10))
                nb["track_total_hits"] = True
                node_bodies.append(nb)
            from opensearch_tpu.search import lanes as lanes_mod

            partials_per_node = self._rpc_many([
                (nid, "indices:data/read/msearch[node]",
                 {"index": idx, "shards": nums, "bodies": node_bodies,
                  "lane": lanes_mod.BACKGROUND})
                for nid, idx, nums in assignments
            ])
        except OpenSearchTpuException:
            return None
        out = []
        for bi, body in enumerate(bodies):
            body_partials = []
            for node_resp in partials_per_node:
                if not isinstance(node_resp, dict) or "responses" not in node_resp:
                    body_partials.append(node_resp)  # transport-level error
                else:
                    body_partials.append(node_resp["responses"][bi])
            try:
                self._raise_partial_errors(body_partials)
                out.append(reduce_search_responses(
                    body, body_partials,
                    size=int(body.get("size", 10)),
                    from_=int(body.get("from", 0)),
                    track_total=body.get("track_total_hits", True),
                ))
            except OpenSearchTpuException as e:
                out.append({"error": e.to_dict(), "status": e.status})
        return out

    def count(self, index: str, body: dict | None = None) -> dict:
        body = dict(body or {})
        body["size"] = 0
        resp = self.search(index, body)
        return {"count": resp["hits"]["total"]["value"],
                "_shards": resp["_shards"]}

    # ------------------------------------------------------------------ #
    # maintenance
    # ------------------------------------------------------------------ #

    def refresh(self, index: str = "_all") -> dict:
        total = {"total": 0, "successful": 0, "failed": 0}
        for name in self.resolve_indices(index):
            resp = self._on_loop(lambda cb, n=name: self.node.refresh(n, cb))
            for k in total:
                total[k] += resp.get("_shards", {}).get(k, 0)
        return {"_shards": total}

    def flush(self, index: str = "_all") -> dict:
        names = self.resolve_indices(index)  # raises on missing indices
        nodes = sorted(self.state.nodes)
        results = self._rpc_many([
            (nid, "indices:admin/flush[node]", {"indices": names})
            for nid in nodes
        ])
        ok = sum(1 for r in results
                 if isinstance(r, dict) and r.get("ack"))
        return {"_shards": {"total": len(nodes), "successful": ok,
                            "failed": len(nodes) - ok}}

    def force_merge(self, index: str = "_all", max_num_segments: int = 1,
                    only_expunge_deletes: bool = False,
                    flush: bool = True) -> dict:
        names = self.resolve_indices(index)
        nodes = sorted(self.state.nodes)
        results = self._rpc_many([
            (nid, "indices:admin/forcemerge[node]",
             {"indices": names, "max_num_segments": max_num_segments})
            for nid in nodes
        ])
        ok = sum(1 for r in results
                 if isinstance(r, dict) and r.get("ack"))
        return {"_shards": {"total": len(nodes), "successful": ok,
                            "failed": len(nodes) - ok}}

    # ------------------------------------------------------------------ #
    # cluster / stats
    # ------------------------------------------------------------------ #

    def cluster_health(self, index: str | None = None,
                       level: str = "cluster",
                       expand_wildcards: str = "all") -> dict:
        return self.node.cluster_health()

    def put_cluster_settings(self, body: dict, *, flat: bool = False) -> dict:
        from opensearch_tpu.cluster.cluster_settings import flatten, merge

        resp = self._rpc(self._leader(), "cluster:admin/settings/update",
                         body or {})
        # echo the EFFECTIVE sections in the same shape as the single-node
        # path (the leader ack carries only the update maps; the merged
        # result is current state + this update)
        state = self.state
        persistent = merge(state.settings,
                           flatten((body or {}).get("persistent") or {}))
        transient = merge(state.transient_settings,
                          flatten((body or {}).get("transient") or {}))
        return {
            "acknowledged": bool(resp.get("acknowledged", True)),
            "persistent": _settings_section(persistent, flat),
            "transient": _settings_section(transient, flat),
        }

    def cluster_state(self, metrics: list[str] | None = None,
                      index: str | None = None,
                      expand_wildcards: str = "all",
                      ignore_unavailable: bool = False,
                      allow_no_indices: bool = True) -> dict:
        """GET /_cluster/state rendered from the REAL replicated cluster
        state (nodes, routing table, index metadata) instead of the
        single-node projection."""
        want = set(metrics or ["_all"])
        everything = "_all" in want

        def on(metric: str) -> bool:
            return everything or metric in want

        state = self.state
        names = (self.resolve_indices(index) if index
                 else sorted(state.indices))
        leader = state.leader_id or self.node.coordinator.leader_id
        out: dict[str, Any] = {
            "cluster_name": "opensearch-tpu",
            "cluster_uuid": state.cluster_uuid,
            "state_uuid": f"state-{state.term}-{state.version}",
        }
        if on("version"):
            out["version"] = state.version
        if on("master_node"):
            out["master_node"] = leader
        if on("cluster_manager_node"):
            out["cluster_manager_node"] = leader
        if on("nodes"):
            out["nodes"] = {
                nid: {"name": n.name or nid,
                      "transport_address": n.address,
                      "attributes": dict(n.attrs)}
                for nid, n in state.nodes.items()
            }
        if on("blocks"):
            out["blocks"] = {}
        if on("metadata"):
            out["metadata"] = {
                "cluster_coordination": {
                    "term": state.term,
                    "last_committed_config":
                        sorted(state.last_committed_config.node_ids),
                    "last_accepted_config":
                        sorted(state.last_accepted_config.node_ids),
                    "voting_config_exclusions":
                        list(getattr(self, "_voting_exclusions", [])),
                },
                "indices": {
                    name: {
                        "state": "open",
                        "settings": {"index": dict(
                            state.indices[name].settings or {})},
                        "mappings": state.indices[name].mappings or {},
                    }
                    for name in names
                },
            }
        if on("routing_table"):
            table: dict[str, Any] = {}
            for name in names:
                shards: dict[str, list] = {}
                for r in state.routing_for_index(name):
                    shards.setdefault(str(r.shard), []).append({
                        "state": r.state, "primary": r.primary,
                        "node": r.node_id,
                        "relocating_node": r.relocating_node,
                        "shard": r.shard, "index": r.index,
                    })
                table[name] = {"shards": shards}
            out["routing_table"] = {"indices": table}
        if on("routing_nodes"):
            assigned: dict[str, list] = {nid: [] for nid in state.nodes}
            unassigned = []
            for r in state.routing:
                entry = {"state": r.state, "primary": r.primary,
                         "node": r.node_id,
                         "relocating_node": r.relocating_node,
                         "shard": r.shard, "index": r.index}
                if r.node_id is None:
                    unassigned.append(entry)
                else:
                    assigned.setdefault(r.node_id, []).append(entry)
            out["routing_nodes"] = {"unassigned": unassigned,
                                    "nodes": assigned}
        return out

    def pending_cluster_tasks(self) -> dict:
        return {"tasks": []}

    def add_voting_config_exclusions(self, node_ids: str | None = None,
                                     node_names: str | None = None) -> dict:
        provided = [p for p in (node_ids, node_names) if p]
        if len(provided) != 1:
            raise IllegalArgumentException(
                "Please set node identifiers correctly. One and only one "
                "of [node_name], [node_names] and [node_ids] has to be set"
            )
        if not hasattr(self, "_voting_exclusions"):
            self._voting_exclusions = []
        if node_ids:
            entries = [{"node_id": nid.strip(), "node_name": "_absent_"}
                       for nid in str(node_ids).split(",") if nid.strip()]
        else:
            entries = [{"node_id": "_absent_", "node_name": nm.strip()}
                       for nm in str(node_names).split(",") if nm.strip()]
        for e in entries:
            if e not in self._voting_exclusions:
                self._voting_exclusions.append(e)
        return {}

    def clear_voting_config_exclusions(self) -> dict:
        self._voting_exclusions = []
        return {}

    def cluster_reroute(self, body: dict | None, *, explain: bool = False,
                        dry_run: bool = False,
                        metrics: list[str] | None = None) -> dict:
        default_metrics = ["version", "master_node", "cluster_manager_node",
                           "nodes", "routing_table", "routing_nodes",
                           "blocks"]
        state = self.cluster_state(metrics=metrics or default_metrics)
        state.pop("cluster_name", None)
        out: dict[str, Any] = {"acknowledged": True, "state": state}
        if explain or (body or {}).get("commands") is not None:
            out["explanations"] = []
        return out

    def allocation_explain(self, body: dict | None,
                           include_disk_info: bool = False) -> dict:
        body = body or {}
        state = self.state
        index = body.get("index")
        if index is not None:
            shard = int(body.get("shard", 0))
            primary = bool(body.get("primary", False))
            entry = next(
                (r for r in state.routing_for_index(index)
                 if r.shard == shard and r.primary == primary), None)
        else:
            entry = next((r for r in state.routing if r.node_id is None),
                         None)
            if entry is None:
                raise IllegalArgumentException(
                    "unable to find any unassigned shards to explain "
                    "[ClusterAllocationExplainRequest["
                    "useAnyUnassignedShard=true]"
                )
        if entry is None:
            raise IllegalArgumentException(
                f"cannot find shard [{body.get('index')}][{body.get('shard')}]"
            )
        out: dict[str, Any] = {
            "index": entry.index,
            "shard": entry.shard,
            "primary": entry.primary,
            "current_state": entry.state.lower(),
        }
        if entry.node_id is not None:
            n = state.nodes.get(entry.node_id)
            out["current_node"] = {
                "id": entry.node_id,
                "name": (n.name or entry.node_id) if n else entry.node_id,
            }
            out["can_remain_on_current_node"] = "yes"
            out["can_rebalance_cluster"] = "yes"
            out["can_rebalance_to_other_node"] = "no"
            out["rebalance_explanation"] = (
                "cannot rebalance as no target node exists that can both "
                "allocate this shard and improve the cluster balance")
        else:
            out["can_allocate"] = "no"
            out["allocate_explanation"] = (
                "cannot allocate because allocation is not permitted to "
                "any of the nodes")
        return out

    def list_all_pits(self) -> dict:
        # cluster PIT ids are stateless {node -> ctx} encodings; there is
        # no central registry to enumerate (reader contexts live on the
        # data nodes and expire there)
        return {"pits": []}

    def get_cluster_settings(self, *, flat: bool = False,
                             include_defaults: bool = False) -> dict:
        from opensearch_tpu.node import TpuNode

        state = self.state

        def view(flat_map: dict) -> dict:
            out = {k: TpuNode._setting_str(v) for k, v in flat_map.items()}
            return out if flat else Settings.from_flat(out).as_nested()

        out = {"persistent": view(state.settings),
               "transient": view(state.transient_settings)}
        if include_defaults:
            out["defaults"] = view({
                k: v for k, v in TpuNode._CLUSTER_SETTING_DEFAULTS.items()
                if k not in state.settings
                and k not in state.transient_settings})
        return out

    def cluster_nodes_stats(self, metrics: list[str] | None = None) -> dict:
        """Cluster-wide `_nodes/stats`: ONE fan-out RPC per node
        (TransportNodesStatsAction), merging every node's telemetry ring,
        exporter accounting, kNN-batch stats, shard-mesh stats and
        registered extras (request cache) into one response. A node that
        fails to answer counts in `_nodes.failed` instead of failing the
        whole call — stats must work mid-chaos. A metric filter narrows
        the RPC payload via the same `sections` mechanism the federated
        Prometheus scrape uses — `_nodes/stats/knn_batch` must not ship
        every node's span ring over the transport just to discard it."""
        payload: dict[str, Any] = {"full": True}
        if metrics and "_all" not in metrics:
            section_of = {"telemetry": "spans", "knn_batch": "knn_batch",
                          "indices": "providers", "device": "device",
                          "tail": "tail", "roofline": "roofline",
                          "heat": "heat"}
            payload["sections"] = sorted(
                {section_of[m] for m in metrics if m in section_of})
        nodes = sorted(self.state.nodes)
        results = self._rpc_many([
            (nid, "indices:monitor/stats[node]", dict(payload))
            for nid in nodes
        ])
        entries: dict[str, dict] = {}
        failed = 0
        for nid, r in zip(nodes, results):
            if not isinstance(r, dict) or set(r) <= {"error", "status"}:
                failed += 1
                continue
            # piggybacked residency advertisement (ISSUE 15): every stats
            # fan-out refreshes the coordinator's warm-copy board for free
            self.node._observe_residency(nid, r)
            entries[nid] = {
                "name": r.get("name", nid),
                "roles": ["cluster_manager", "data"],
                "telemetry": r.get("telemetry", {}),
                "knn_batch": r.get("knn_batch", {}),
                "shard_mesh": r.get("shard_mesh", {}),
                "device": r.get("device", {}),
                "tail": r.get("tail", {}),
                "roofline": r.get("roofline", {}),
                "heat": r.get("heat", {}),
                "indices": {
                    "request_cache": r.get("request_cache", {}),
                },
                "shards": r.get("shards", {}),
            }
        return {
            "_nodes": {"total": len(nodes), "successful": len(entries),
                       "failed": failed},
            "cluster_name": "opensearch-tpu",
            "nodes": entries,
        }

    def cluster_metrics(self) -> dict[str, dict]:
        """Per-node metrics registries (counters + exemplar-carrying
        histograms) for the federated `/_prometheus/metrics?cluster=true`
        view: node id -> MetricsRegistry.stats() shape. Scrapes recur
        every few seconds, so the fan-out asks each node for its metrics
        SECTION only — no span ring, exporter ledger, batcher or provider
        payloads ride the transport just to be discarded here."""
        nodes = sorted(self.state.nodes)
        results = self._rpc_many([
            (nid, "indices:monitor/stats[node]",
             {"full": True,
              "sections": ["metrics", "device_totals", "roofline",
                           "heat"]})
            for nid in nodes
        ])
        out: dict[str, dict] = {}
        for nid, r in zip(nodes, results):
            if not isinstance(r, dict) or set(r) <= {"error", "status"}:
                continue
            tel = r.get("telemetry", {})
            out[nid] = {"counters": tel.get("counters", {}),
                        "histograms": tel.get("histograms", {}),
                        # per-device resident-byte totals: the federated
                        # exposition renders them as labeled gauges
                        "device": r.get("device_totals", {}),
                        # per-family roofline fractions/FLOP/s, rendered
                        # as {family=,node=}-labeled gauges
                        "roofline": r.get("roofline", {}),
                        # per-structure heat classes, rendered as
                        # {kind=,index=,node=}-labeled gauges
                        "heat": r.get("heat", {})}
        return out

    def cluster_otel_flush(self) -> dict:
        """`POST /_otel/flush`: force every node's span exporter to decide
        and drain, and collect each node's exporter ledger + device-memory
        snapshot. Nodes that fail to answer count in `_nodes.failed` —
        the flush must work mid-chaos, like stats."""
        nodes = sorted(self.state.nodes)
        results = self._rpc_many([
            (nid, "cluster:admin/otel/flush[node]", {}) for nid in nodes
        ])
        entries: dict[str, dict] = {}
        failed = 0
        for nid, r in zip(nodes, results):
            if not isinstance(r, dict) or set(r) <= {"error", "status"}:
                failed += 1
                continue
            entries[nid] = r
        return {
            "_nodes": {"total": len(nodes), "successful": len(entries),
                       "failed": failed},
            "cluster_name": "opensearch-tpu",
            "nodes": entries,
        }

    def _all_shard_stats(self) -> dict[str, dict]:
        nodes = sorted(self.state.nodes)
        results = self._rpc_many([
            (nid, "indices:monitor/stats[node]", {}) for nid in nodes
        ])
        out: dict[str, dict] = {}
        for nid, r in zip(nodes, results):
            if isinstance(r, dict):
                # piggybacked residency advertisement (ISSUE 15)
                self.node._observe_residency(nid, r)
                for key, s in (r.get("shards") or {}).items():
                    if s.get("primary") or key not in out:
                        out[key] = s
        return out

    def index_stats(self, index: str = "_all", **_kw) -> dict:
        # the cluster facade reports the docs core; metric filtering and
        # per-section detail are the single-node TpuNode.index_stats's
        names = self.resolve_indices(index)
        shard_stats = self._all_shard_stats()
        per_index: dict[str, int] = {}
        for s in shard_stats.values():
            if s.get("primary"):
                per_index[s["index"]] = per_index.get(s["index"], 0) + s["docs"]
        total = sum(per_index.get(n, 0) for n in names)
        out = {
            "_all": {"primaries": {"docs": {"count": total}},
                     "total": {"docs": {"count": total}}},
            "indices": {
                n: {"primaries": {"docs": {"count": per_index.get(n, 0)}}}
                for n in names
            },
        }
        return out

    def field_caps(self, index: str | None, fields: str,
                   include_unmapped: bool = False,
                   index_filter: dict | None = None) -> dict:
        """Cluster field_caps over the replicated index metadata (the
        shared merge in node.build_field_caps — mappings are in the
        cluster state, so no per-node fan-out is needed; index_filter
        falls back to a cluster count per index)."""
        from opensearch_tpu.node import build_field_caps

        names = self.resolve_indices(index if index is not None else "_all")
        patterns = [p.strip() for p in str(fields or "").split(",")
                    if p.strip()]
        if not patterns:
            raise IllegalArgumentException("[field_caps] requires [fields]")
        if index_filter:
            names = [
                name for name in names
                if self.count(name, {"query": index_filter}).get("count", 0)
            ]
        return build_field_caps(names, self._mapper_for, patterns,
                                include_unmapped=include_unmapped)

    def recovery_records(self, index: str | None = None) -> list[dict]:
        """Cluster-wide recovery progress (RecoveryState collection behind
        GET [/{index}]/_recovery and _cat/recovery): every node reports its
        target-side records; peer recoveries, relocation transfers and
        local store bootstraps all appear with live stage/bytes/ops."""
        names = self.resolve_indices(index) if index else None
        nodes = sorted(self.state.nodes)
        results = self._rpc_many([
            (nid, "indices:monitor/recovery[node]", {"indices": names})
            for nid in nodes
        ])
        out: list[dict] = []
        for r in results:
            if isinstance(r, dict):
                out.extend(r.get("recoveries") or [])
        return sorted(
            out, key=lambda p: (p["index"], p["shard"],
                                str(p.get("target_node")))
        )

    # unsupported-surface markers (clear 400s beat silent wrong answers)

    _UNSUPPORTED_SERVICES = {
        "ingest", "snapshots", "search_pipelines", "script",
        "indexing_pressure", "search_backpressure", "search_slowlog",
        "indexing_slowlog", "reindex",
    }

    # -- node-local stored scripts + search templates ------------------- #

    def _scripts_file(self):
        return self.node.data_path / "stored_scripts.json"

    def _load_scripts(self) -> dict:
        if self._scripts_file().exists():
            return json.loads(self._scripts_file().read_text())
        return {}

    def put_stored_script(self, script_id: str, body: dict) -> dict:
        script = (body or {}).get("script")
        if not isinstance(script, dict) or "source" not in script:
            raise IllegalArgumentException(
                "stored script requires [script] with [source]"
            )
        data = self._load_scripts()
        data[script_id] = {"lang": script.get("lang", "painless"),
                           "source": script["source"]}
        self._scripts_file().parent.mkdir(parents=True, exist_ok=True)
        self._scripts_file().write_text(json.dumps(data))
        return {"acknowledged": True}

    def get_stored_script(self, script_id: str) -> dict:
        data = self._load_scripts()
        if script_id not in data:
            return {"_id": script_id, "found": False}
        return {"_id": script_id, "found": True, "script": data[script_id]}

    def delete_stored_script(self, script_id: str) -> dict:
        from opensearch_tpu.common.errors import ResourceNotFoundException

        data = self._load_scripts()
        if script_id not in data:
            raise ResourceNotFoundException(
                f"stored script [{script_id}] does not exist"
            )
        del data[script_id]
        self._scripts_file().write_text(json.dumps(data))
        return {"acknowledged": True}

    def render_search_template(self, body: dict,
                               template_id: str | None = None) -> dict:
        from opensearch_tpu.common.errors import ResourceNotFoundException
        from opensearch_tpu.script.mustache import render_search_template

        body = body or {}
        source = body.get("source")
        sid = template_id or body.get("id")
        if source is None and sid is not None:
            stored = self.get_stored_script(str(sid))
            if not stored.get("found"):
                raise ResourceNotFoundException(
                    f"search template [{sid}] does not exist"
                )
            source = stored["script"]["source"]
        if source is None:
            raise IllegalArgumentException(
                "search template requires [source] or [id]"
            )
        return render_search_template(source, body.get("params"))

    def search_template(self, index: str | None, body: dict,
                        template_id: str | None = None, **kwargs) -> dict:
        rendered = self.render_search_template(body, template_id)
        return self.search(index, rendered, **kwargs)

    def _unsupported(self, what: str):
        raise IllegalArgumentException(
            f"{what} is not yet supported in cluster mode"
        )

    def __getattr__(self, name: str):
        if name in self._UNSUPPORTED_SERVICES:
            # handlers dereference these services directly; a clear 400
            # beats an opaque AttributeError 500
            self._unsupported(f"[{name}]")
        # attributes probed via getattr(..., default) (breakers, telemetry)
        # must keep AttributeError semantics
        raise AttributeError(name)


def _encode_scroll_id(state: dict) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(state, separators=(",", ":")).encode()
    ).decode()


def _decode_scroll_id(scroll_id: str) -> dict:
    try:
        return json.loads(base64.urlsafe_b64decode(scroll_id.encode()))
    except Exception as e:  # noqa: BLE001
        raise SearchContextMissingException(
            f"malformed scroll id [{scroll_id[:32]}...]"
        ) from e


def _parse_keep_alive_ms(value: str | None) -> int:
    from opensearch_tpu.common.settings import parse_time_millis

    if value is None:
        return 60_000
    return int(parse_time_millis(value))


def _deep_merge(base: dict, overlay: dict) -> dict:
    out = dict(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out
