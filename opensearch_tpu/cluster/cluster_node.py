"""ClusterNode: a full node — coordinator + data shards + action handlers.

Ties the control plane to the data plane the way the reference wires
Node.java: the Coordinator publishes cluster states; every node's
IndicesClusterStateService analog (`_apply_cluster_state`) creates/removes
local IndexShards to match the routing table and runs replica recovery;
write operations route to the primary and fan out to started replicas
(TransportReplicationAction / ReplicationOperation.java:77 semantics);
search scatter-gathers over one copy of each shard (SURVEY.md §3.2).

Transport actions (names mirror the reference's):
    cluster:admin/create_index, cluster:admin/delete_index   (leader)
    internal:cluster/shard_started                           (leader)
    indices:data/write[p]  indices:data/write[r]             (data)
    indices:data/read/get, indices:data/read/search[shard]   (data)
    internal:index/shard/recovery/start                      (data: source)

Recovery model (v1, ops-based): the replica pulls a full live-doc dump +
seq_nos from the primary (the retention-lease ops path of
RecoverySourceHandler.recoverToTarget:171 reduced to its logical core),
then reports shard-started to the leader. Segment(-file) replication is the
planned physical path (indices/replication/ analog) once transport carries
binary payloads.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Any, Callable

from opensearch_tpu.common.errors import (
    IndexNotFoundException,
    OpenSearchTpuException,
    RejectedExecutionException,
    ShardNotFoundException,
)
from opensearch_tpu.common.hashing import shard_id_for_routing
from opensearch_tpu.cluster import residency as residency_mod
from opensearch_tpu.cluster.allocation import (
    mark_shard_started,
    reroute,
)
from opensearch_tpu.cluster.coordinator import Coordinator, Mode
from opensearch_tpu.cluster.state import (
    ClusterState,
    DiscoveryNode,
    IndexMeta,
    ShardRoutingEntry,
)
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.index.shard import IndexShard, ShardId
from opensearch_tpu.search import query_dsl
from opensearch_tpu.telemetry import tracing


def _wall_ms() -> int:
    """Epoch wall-clock ms for retention-lease timestamps — deliberately
    NOT ClusterNode._now_ms (monotonic): lease timestamps persist in the
    commit point and must stay comparable across restarts."""
    from opensearch_tpu.common.timeutil import epoch_millis

    return epoch_millis()
from opensearch_tpu.search.executor import execute_query_phase
from opensearch_tpu.search.service import _source_filter


def _release_then(release: Callable[[], None],
                  callback: Callable[[dict], None]) -> Callable[[dict], None]:
    """Wrap a response callback so an admission slot releases exactly once,
    right before the caller sees the response."""
    def wrapped(resp: dict) -> None:
        release()
        callback(resp)
    return wrapped


class ClusterNode:
    def __init__(
        self,
        node_id: str,
        data_path: str | Path,
        transport,
        scheduler,
        peers: list[str],
        roles: tuple[str, ...] = ("cluster_manager", "data"),
        persisted=None,
    ):
        self.node_id = node_id
        self.data_path = Path(data_path)
        self.transport = transport
        self.scheduler = scheduler
        self.node = DiscoveryNode(node_id=node_id, name=node_id, roles=roles)
        # per-node telemetry: spans land in THIS node's ring (the tracer
        # name prefixes span ids so traces stitched across sim nodes stay
        # unambiguous); trace ids ride transport headers between nodes
        from opensearch_tpu.telemetry.tracing import Telemetry

        self.telemetry = Telemetry(name=node_id)
        # fs stats feeding the disk-threshold decider; tests override
        # disk_usage_pct directly (the FsHealthService probe analog)
        self.disk_usage_pct: float | None = None
        self._node_disk: dict[str, float] = {}
        # fault-injection hooks (testing/soak.py FaultScheduler): a clock
        # skew offsets THIS node's monotonic reads (the timeutil clock is
        # process-global under the sim, so skew must be per-node here);
        # a worker delay stalls the serial data executor's jobs
        self.clock_skew_ms: int = 0
        self.data_worker_delay_ms: int = 0
        # leader-side watermark classification per node (low/high) — a
        # crossing triggers a reroute publication (DiskThresholdMonitor)
        self._disk_classes: dict[str, tuple[bool, bool]] = {}
        from opensearch_tpu.cluster.allocation import AllocationSettings

        def transform(state: ClusterState) -> ClusterState:
            disk = dict(self._node_disk)
            own = self._disk_usage()
            if own is not None:
                disk[node_id] = own
            return reroute(state, AllocationSettings.from_cluster(state, disk))

        self.coordinator = Coordinator(
            self.node, peers, transport, scheduler,
            persisted=persisted,
            on_state_applied=self._apply_cluster_state,
            # every publication passes through allocation: node joins/leaves
            # re-assign shards, promote replicas, fill replica slots;
            # allocation settings resolve from the DYNAMIC cluster settings
            # in the state being published
            state_transform=transform,
        )
        self.coordinator.tracer = self.telemetry.tracer
        self.coordinator.check_extras = lambda: {
            "disk_used_pct": self._disk_usage()
        }

        def on_extras(peer: str, extras: dict) -> None:
            pct = extras.get("disk_used_pct")
            if pct is not None:
                self._node_disk[peer] = float(pct)
                self._maybe_reroute_on_disk(peer, float(pct))

        self.coordinator.on_follower_extras = on_extras
        # addSettingsUpdateConsumer registry, notified at state application
        from opensearch_tpu.cluster.cluster_settings import (
            SettingsUpdateConsumers,
        )

        self.settings_consumers = SettingsUpdateConsumers()
        # kNN dispatch batcher: process-wide scheduler (one process == one
        # device); this node wires its metrics sink and subscribes its
        # settings keys to the cluster-state settings consumer, so dynamic
        # updates reach the data plane in cluster mode too
        from opensearch_tpu.search import batcher as _batcher_mod

        self.knn_batcher = _batcher_mod.default_batcher
        self.knn_batcher.metrics = self.telemetry.metrics
        self.settings_consumers.register(
            "search.knn.batch.", self.knn_batcher.apply_settings
        )
        # roofline recorder: process-wide like the batcher; this node
        # becomes its fallback metrics sink (active_metrics() still wins
        # per request, so in-process sims attribute per executing node).
        # Peaks calibrate at boot (cached per platform; a sim's stub
        # wins) — never lazily inside a stats poll.
        from opensearch_tpu.telemetry import roofline as _roofline_mod

        _roofline_mod.default_recorder.metrics = self.telemetry.metrics
        _roofline_mod.ensure_peaks()
        # kNN serving knobs (search/ann.py): process-wide like the batcher,
        # applied live the same way. The prefix is "search.knn." (not
        # ".ann.") because the exact-path policy keys — search.knn.kernel
        # and search.knn.score_precision — sit directly under it;
        # apply_settings re-derives every field from the effective map, so
        # firing on an unrelated search.knn.batch.* change is a no-op
        from opensearch_tpu.search import ann as _ann_mod

        self.settings_consumers.register(
            "search.knn.", _ann_mod.default_config.apply_settings
        )
        # shard-mesh HBM byte budget (cluster/shard_mesh.py): dynamic
        # search.mesh.hbm_budget_bytes reaches the registry at state
        # application, so a PUT retunes residency pressure cluster-wide
        from opensearch_tpu.cluster.shard_mesh import default_registry

        self.settings_consumers.register(
            "search.mesh.", default_registry.apply_settings
        )
        # span exporter: per-node (its ring is per-node); dynamic
        # telemetry.tracing.* updates rebuild/retune it at state application
        from opensearch_tpu.telemetry.export import apply_tracing_settings

        self.settings_consumers.register(
            "telemetry.tracing.",
            lambda eff: apply_tracing_settings(
                self.telemetry, eff, self.data_path, service_name=node_id),
        )
        # priority lanes (search/lanes.py): process-wide policy like the
        # batcher; dynamic search.lanes.* retunes the pool split + the
        # background queue bound at state application
        from opensearch_tpu.search import lanes as _lanes_mod

        self.settings_consumers.register(
            "search.lanes.", _lanes_mod.default_config.apply_settings
        )
        self.lane_tracker = _lanes_mod.LaneTracker()
        # residency-aware replica routing (cluster/residency.py): this
        # node's COORDINATOR-side board of warm copies, fed by the
        # _residency stamps kNN partials carry back; the dynamic toggle
        # rides the settings consumer like the lanes
        self.settings_consumers.register(
            "search.routing.", residency_mod.default_config.apply_settings
        )
        self.residency_board = residency_mod.ResidencyBoard()
        # heat/touch accounting (telemetry/device_ledger.py): the ledger
        # is process-wide like the batcher; dynamic telemetry.heat.*
        # (enabled, advisor ring size) reaches it at state application
        from opensearch_tpu.telemetry.device_ledger import (
            default_ledger as _heat_ledger,
        )

        self.settings_consumers.register(
            "telemetry.heat.", _heat_ledger.apply_heat_settings
        )
        # cross-node residency advertisement (ISSUE 15): a fresh
        # coordinator seeds its board from the data nodes' warm sets
        # piggybacked on the light stats RPC — fired once, at the first
        # state application that shows other nodes (join traffic), so
        # cold-start routing stops round-robining onto warm copies
        self._residency_seeded = False
        # last advertisement seen per node, so a pair that DROPS OUT of a
        # node's warm set (bundle evicted under budget pressure) is
        # observed cold — an advertise-only board would latch stale
        # warmth forever; pruned with the board at state application
        self._advertised_residency: dict[str, set] = {}
        self._advertised_lock = threading.Lock()
        # round-robin sequence for cold routing decisions (no warm copy
        # known yet): one draw per fan-out keeps the shard set on one
        # replica rank instead of scattering the first build
        import itertools as _it

        self._route_rr = _it.count(0)
        # extra per-node stats sections for the cluster-wide _nodes/stats
        # fan-out: coordinator-side services (the facade's request cache)
        # register a provider here so the node RPC can report them
        self.stats_providers: dict[str, Callable[[], dict]] = {}
        # workload-management groups: one registry per node, shared with the
        # REST facade; bulk admission (wlm.admit_bulk) sheds tagged bulk
        # traffic past its group's slot share with 429 BEFORE fan-out
        from opensearch_tpu.wlm import QueryGroupService

        self.query_groups = QueryGroupService(
            self.data_path / "query_groups.json"
        )
        self.local_shards: dict[tuple[str, int], IndexShard] = {}
        self._mapper_services: dict[str, MapperService] = {}
        self._index_versions: dict[str, int] = {}
        # primary-side recovery tracking (ReplicationTracker.initiateTracking
        # analog): targets that requested recovery receive concurrent writes
        # even before the routing table shows them STARTED — otherwise ops
        # arriving between the recovery dump and shard-started are lost
        self._tracked_targets: dict[tuple[str, int], set[str]] = {}
        # recovery-source mode counters (tests assert ops-based recovery
        # ships zero segment bytes when a retention lease holds)
        self.recovery_stats = {"ops_based": 0, "segment_based": 0,
                               "dump_based": 0}
        # recovery subsystem (indices/recovery/ analog): source-side chunk
        # sessions + target-side progress records (RecoveryState), exposed
        # via indices:monitor/recovery[node] for _cat/recovery
        from opensearch_tpu.index.recovery import RecoverySourceSessions

        self._recovery_sources = RecoverySourceSessions()
        self._recovery_drivers: dict[tuple[str, int], Any] = {}
        self.recoveries: dict[tuple[str, int], Any] = {}
        # last routing state THIS node observed for its own copies: a
        # STARTED -> INITIALIZING transition on the same key means the
        # leader reset the copy while we were dark (see
        # _apply_cluster_state's assignment-epoch check)
        self._last_routing_state: dict[tuple[str, int], str] = {}

        reg = transport.register
        reg(node_id, "cluster:admin/create_index", self._on_create_index)
        reg(node_id, "cluster:admin/settings/update", self._on_update_settings)
        reg(node_id, "cluster:admin/delete_index", self._on_delete_index)
        reg(node_id, "cluster:admin/put_mapping", self._on_put_mapping)
        reg(node_id, "internal:cluster/shard_started", self._on_shard_started)
        reg(node_id, "internal:cluster/shard_failed", self._on_shard_failed)
        reg(node_id, "indices:data/write[p]", self._on_primary_write)
        reg(node_id, "indices:data/write[r]", self._on_replica_write)
        reg(node_id, "indices:data/write[p][bulk]", self._on_primary_bulk)
        reg(node_id, "indices:data/write[r][bulk]", self._on_replica_bulk)
        reg(node_id, "indices:data/read/get", self._on_get)
        reg(node_id, "indices:data/read/search[shard]", self._on_shard_search)
        reg(node_id, "indices:data/read/search[node]", self._on_node_search)
        reg(node_id, "indices:data/read/msearch[node]", self._on_node_msearch)
        reg(node_id, "indices:data/read/search[ctx]", self._on_ctx_search)
        reg(node_id, "indices:data/read/ctx_close", self._on_ctx_close)
        reg(node_id, "indices:admin/refresh[shard]", self._on_shard_refresh)
        reg(node_id, "indices:admin/flush[node]", self._on_node_flush)
        reg(node_id, "indices:admin/forcemerge[node]", self._on_node_forcemerge)
        reg(node_id, "indices:monitor/stats[node]", self._on_node_stats)
        reg(node_id, "cluster:admin/otel/flush[node]", self._on_otel_flush)
        reg(node_id, "indices:replication/checkpoint", self._on_replication_checkpoint)
        reg(node_id, "indices:replication/get_segments", self._on_get_segments)
        reg(node_id, "internal:index/shard/recovery/start", self._on_start_recovery)
        reg(node_id, "internal:index/shard/recovery/file_chunk",
            self._on_recovery_file_chunk)
        reg(node_id, "internal:index/shard/recovery/ops_chunk",
            self._on_recovery_ops_chunk)
        reg(node_id, "internal:index/shard/recovery/finalize",
            self._on_recovery_finalize)
        reg(node_id, "indices:monitor/recovery[node]", self._on_node_recovery)
        reg(node_id, "internal:snapshot/shard_dump", self._on_snapshot_shard_dump)
        reg(node_id, "internal:snapshot/restore_dump",
            self._on_snapshot_restore_dump)
        # per-node reader contexts (scroll/PIT pin snapshots node-side; the
        # coordinator's scroll id maps node -> local ctx — ReaderContext
        # .java:64 semantics distributed)
        self._reader_contexts: dict[str, dict] = {}
        # heavy query phases run OFF the transport loop so a slow search
        # cannot stall heartbeats/elections (VERDICT r2 weak #9); one worker
        # keeps the engine's single-writer discipline for WRITE/engine work
        self._data_executor = None
        # read-only searches get a PARALLEL pool (the reference's `search`
        # threadpool; same split rest/http.py uses): they execute against
        # immutable acquired snapshots, so they need no single-writer
        # discipline — and serializing them behind the data worker meant
        # concurrent search[node] requests could never reach the kNN
        # dispatch batcher together, so cross-request coalescing (and the
        # shard-mesh launch amortization) never engaged in cluster mode.
        # Background-lane work (msearch[node] fan-outs and anything the
        # coordinator marked background) runs its OWN smaller pool so a
        # flood of it can never occupy the interactive workers (ISSUE 11).
        self._search_executor = None
        self._bg_search_executor = None
        # ctx ids mint on the parallel pool: itertools.count is atomic
        # under the GIL where `self._ctx_seq += 1` is read-modify-write
        # (_it imported above for the routing round-robin)
        self._ctx_counter = _it.count(1)
        # device-resident shard bundles for the mesh kNN path, keyed by
        # reader generation (cluster/shard_mesh.py); process-wide like the
        # batcher — invalidated when this node's shards leave
        from opensearch_tpu.cluster.shard_mesh import default_registry

        self.shard_mesh = default_registry
        # mesh launch walls land in this node's histograms (exemplar-linked
        # like the batcher's queue-wait: a p99 launch links to its trace)
        self.shard_mesh.metrics = self.telemetry.metrics

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        # recovered durable state: recreate local shards BEFORE elections so
        # a restarted node serves its recovered data (GatewayService state
        # recovery; shard data itself replays from translog/commits in the
        # Engine constructor)
        if self.applied_state.indices:
            self._apply_cluster_state(self.applied_state)
        self.coordinator.start()
        self._schedule_shard_state_tick()

    # ShardStateAction resend loop: a shard-started message can be LOST
    # (leader change, half-open link) and with no further publication the
    # copy would sit INITIALIZING forever. Periodically re-report local
    # copies that finished recovering until the routing table shows them
    # STARTED (the reference resends via ShardStateAction retries).
    _SHARD_STATE_TICK_MS = 2_000

    def _schedule_shard_state_tick(self) -> None:
        if getattr(self, "_closed", False):
            return
        self._shard_tick_timer = self.scheduler.schedule(
            self._SHARD_STATE_TICK_MS, self._shard_state_tick
        )

    def _maybe_reroute_on_disk(self, nid: str, pct: float | None) -> None:
        """DiskThresholdMonitor analog: disk stats arrive on heartbeat
        acks, but reroute only runs INSIDE a publication — without a
        trigger, a node filling past the high watermark would sit full
        until some unrelated state change. A watermark-classification
        crossing (below/above low, below/above high, either direction)
        on any node submits an identity task so the publication
        transform's reroute evaluates the new disk picture."""
        if not self.is_leader:
            return
        from opensearch_tpu.cluster.allocation import AllocationSettings

        s = AllocationSettings.from_cluster(self.applied_state)
        cls = (False, False) if pct is None else (
            pct >= s.disk_low_watermark_pct,
            pct >= s.disk_high_watermark_pct,
        )
        if self._disk_classes.get(nid, (False, False)) == cls:
            return
        self._disk_classes[nid] = cls
        from opensearch_tpu.cluster.coordination import CoordinationError

        try:
            self.coordinator.submit_state_update(lambda st: st)
        except CoordinationError:
            pass

    def _allocator_pending(self) -> bool:
        """Would the publication transform's reroute change the applied
        routing table? Uses the same disk picture the transform uses, so
        a True here means the next publication makes progress."""
        from opensearch_tpu.cluster.allocation import (
            AllocationSettings,
            reroute,
        )

        state = self.applied_state
        disk = dict(self._node_disk)
        own = self._disk_usage()
        if own is not None:
            disk[self.node_id] = own
        out = reroute(state, AllocationSettings.from_cluster(state, disk))
        return set(out.routing) != set(state.routing)

    def _shard_state_tick(self) -> None:
        if getattr(self, "_closed", False):
            return
        # expired reader contexts reap on a TICK, not only on the next
        # search[node] arrival: a node whose copies stop being query
        # targets (all-replica holder, post-relocation) would otherwise
        # pin expired scroll/PIT snapshots forever (the reference runs a
        # dedicated keep-alive reaper thread for the same reason)
        self._reap_reader_contexts()
        # the leader's OWN disk crossing a watermark must trigger a
        # reroute too (no heartbeat carries it back to itself)
        if self.is_leader:
            self._maybe_reroute_on_disk(self.node_id, self._disk_usage())
            # RoutingService analog: multi-step reshapes (rebalance chains,
            # primary-role swaps, evacuations) apply ONE change per
            # publication and rely on a follow-up to continue — but the
            # last change of a chain has no natural follow-up event. If
            # the allocator still wants changes against the applied state,
            # nudge a publication so the chain converges instead of
            # stalling one step short.
            if self._allocator_pending():
                from opensearch_tpu.cluster.coordination import (
                    CoordinationError,
                )

                try:
                    self.coordinator.submit_state_update(lambda st: st)
                except CoordinationError:
                    pass
        for r in self.applied_state.shards_for_node(self.node_id):
            if r.state != "INITIALIZING":
                continue
            shard = self.local_shards.get((r.index, r.shard))
            if shard is not None and (
                r.primary or getattr(shard, "recovery_done", False)
            ):
                self._report_shard_started(r.index, r.shard)
        self._schedule_shard_state_tick()

    def bootstrap(self, voting_ids: list[str]) -> None:
        self.coordinator.bootstrap(voting_ids)

    @property
    def applied_state(self) -> ClusterState:
        return self.coordinator.applied_state

    @property
    def is_leader(self) -> bool:
        return self.coordinator.mode == Mode.LEADER

    # ------------------------------------------------------------------ #
    # cluster state application (IndicesClusterStateService analog)
    # ------------------------------------------------------------------ #

    def _mapper_for(self, index: str, state: ClusterState) -> MapperService:
        meta = state.indices[index]
        ms = self._mapper_services.get(index)
        if ms is None or self._index_versions.get(index, -1) < meta.version:
            ms = MapperService(meta.mappings or None)
            self._mapper_services[index] = ms
            self._index_versions[index] = meta.version
        return ms

    def _apply_cluster_state(self, state: ClusterState) -> None:
        from opensearch_tpu.cluster.cluster_settings import effective

        self.settings_consumers.apply(
            effective(state.settings, state.transient_settings)
        )
        # disk stats ride follower-check acks keyed by node id; departed
        # nodes must not accrete entries forever (TPU009: every long-lived
        # map on the sim/serving path needs eviction)
        self._node_disk = {
            nid: pct for nid, pct in self._node_disk.items()
            if nid in state.nodes
        }
        self._disk_classes = {
            nid: cls for nid, cls in self._disk_classes.items()
            if nid in state.nodes
        }
        # residency-routing board: a departed node or deleted index must
        # never look warm to the replica router (candidates re-filter by
        # routing state anyway — this is the memory bound + staleness cut)
        self.residency_board.prune(
            live_nodes=set(state.nodes),
            live_indices=set(state.indices),
        )
        with self._advertised_lock:
            for nid in [n for n in self._advertised_residency
                        if n not in state.nodes]:
                del self._advertised_residency[nid]
        my_shards = {
            (r.index, r.shard): r for r in state.shards_for_node(self.node_id)
        }
        # remove shards no longer assigned here (or whose index is deleted)
        for key in list(self.local_shards):
            if key not in my_shards or key[0] not in state.indices:
                shard = self.local_shards.pop(key)
                # a departing shard invalidates the index's device-resident
                # mesh bundles: the residency key pins engine instance ids,
                # so a stale bundle could never serve wrong data — this
                # just releases HBM promptly instead of waiting on LRU
                self.shard_mesh.invalidate_index(key[0])
                self._tracked_targets.pop(key, None)
                driver = self._recovery_drivers.pop(key, None)
                if driver is not None:
                    driver.cancel()
                # the recovery record leaves the node with its shard, like
                # the reference's per-shard RecoveryState
                self.recoveries.pop(key, None)
                shard.close()
                # a copy that MOVED AWAY (relocation swap completed, or the
                # allocator rebalanced it) deletes its local files when the
                # cluster holds another live copy — IndicesStore
                # .deleteShardIfExistElseWhere. A plain node-left keeps the
                # files: a returning node recovers far cheaper from them
                # (ops-based path off the local checkpoint).
                if key[0] in state.indices and any(
                    r.node_id not in (None, self.node_id)
                    and r.state in ("STARTED", "RELOCATING")
                    for r in state.routing if (r.index, r.shard) == key
                ):
                    import shutil

                    shutil.rmtree(
                        self.data_path / "indices" / key[0] / str(key[1]),
                        ignore_errors=True,
                    )
        # recovery progress records and source sessions die with their index
        for key in [k for k in self.recoveries if k[0] not in state.indices]:
            del self.recoveries[key]
        # drop tracked recovery targets that are no longer assigned copies,
        # and release their retention leases — a departed copy must not pin
        # translog history forever (ReplicationTracker removes peer leases
        # when the routing table drops the copy)
        for key, targets in list(self._tracked_targets.items()):
            assigned = {
                r.node_id for r in state.routing
                if (r.index, r.shard) == key and r.node_id is not None
            }
            gone = targets - assigned
            local = self.local_shards.get(key)
            if gone and local is not None and local.primary:
                for nid in gone:
                    local.engine.retention_leases.remove(
                        f"peer_recovery/{nid}")
            for nid in gone:
                # a departed target's chunk session stops pinning blobs
                self._recovery_sources.drop_target(key[0], key[1], nid)
            targets &= assigned
            if not targets:
                self._tracked_targets.pop(key, None)
        for index_name in list(self._mapper_services):
            if index_name not in state.indices:
                self._mapper_services.pop(index_name, None)
                self._index_versions.pop(index_name, None)
        # create newly assigned shards
        for (index_name, shard_num), entry in my_shards.items():
            if index_name not in state.indices:
                continue
            if (index_name, shard_num) not in self.local_shards:
                ms = self._mapper_for(index_name, state)
                path = self.data_path / "indices" / index_name / str(shard_num)
                from opensearch_tpu.index.shard import (
                    replication_type,
                    translog_durability,
                )

                shard = IndexShard(
                    ShardId(index_name, shard_num), path, ms,
                    durability=translog_durability(
                        state.indices[index_name].settings
                    ),
                    replication=replication_type(
                        state.indices[index_name].settings
                    ),
                )
                shard.primary = entry.primary
                self.local_shards[(index_name, shard_num)] = shard
                if entry.state == "INITIALIZING":
                    if entry.primary:
                        # local (possibly empty) store is authoritative
                        from opensearch_tpu.index.recovery import (
                            RecoveryProgress,
                        )

                        p = RecoveryProgress(
                            index_name, shard_num, self.node_id,
                            recovery_type=(
                                "EXISTING_STORE" if shard.num_docs
                                else "EMPTY_STORE"),
                        )
                        p.done()
                        self.recoveries[(index_name, shard_num)] = p
                        self._report_shard_started(index_name, shard_num)
                    else:
                        self._start_replica_recovery(index_name, shard_num, state)
                elif not entry.primary:
                    # entry says STARTED but we just CREATED this shard
                    # object (e.g. a wiped node rejoined under its old id
                    # while never evicted): local content is unknown —
                    # re-sync from the primary before trusting it
                    self._start_replica_recovery(index_name, shard_num, state)
            else:
                shard = self.local_shards[(index_name, shard_num)]
                was_primary = shard.primary
                shard.primary = entry.primary
                prev_state = self._last_routing_state.get(
                    (index_name, shard_num))
                if (entry.state == "INITIALIZING" and not entry.primary
                        and prev_state in ("STARTED", "RELOCATING")
                        and getattr(shard, "recovery_done", False)):
                    # the leader RESET this copy: we last saw ourselves
                    # STARTED, now we are INITIALIZING again — we were
                    # evicted while dark (kill/partition) and re-assigned
                    # the same slot. recovery_done belongs to the previous
                    # assignment epoch; trusting it would report a copy
                    # that MISSED acked writes as started (permanent
                    # divergence — the chaos soak's copy-agreement
                    # invariant caught this). Re-sync from the primary.
                    shard.recovery_done = False
                    shard.recovery_inflight = False
                if (entry.primary and not was_primary
                        and shard.replication == "SEGMENT"):
                    # promotion of a segrep replica: translog ops not yet
                    # covered by replicated segments must become searchable
                    # (the reference's NRT replica -> InternalEngine swap)
                    def promote(s=shard):
                        s.engine.replay_translog_tail()
                        s.refresh()

                    self._offload(promote)
                if entry.state == "INITIALIZING":
                    # re-report on every publication until the leader records
                    # STARTED — a lost shard-started message (timeout, old
                    # leader died) must not leave the copy INITIALIZING
                    # forever (ShardStateAction resend semantics)
                    if entry.primary or getattr(shard, "recovery_done", False):
                        self._report_shard_started(index_name, shard_num)
                    elif not getattr(shard, "recovery_inflight", False):
                        # a pre-existing local copy (e.g. recreated from
                        # persisted state after a restart) assigned
                        # INITIALIZING must still re-sync from the primary —
                        # its local data may be arbitrarily stale
                        shard.recovery_inflight = True
                        self._start_replica_recovery(
                            index_name, shard_num, state
                        )
        self._last_routing_state = {
            key: entry.state for key, entry in my_shards.items()
        }
        # cross-node residency advertisement (ISSUE 15): a coordinator
        # seeing other nodes for the first time (its own join, or theirs)
        # seeds its ResidencyBoard from their advertised warm sets
        self._maybe_seed_residency_board()

    # -- shard started / recovery ------------------------------------------

    def _report_shard_started(self, index: str, shard: int) -> None:
        leader = self.applied_state.leader_id or self.coordinator.leader_id
        if leader is None:
            return
        self.transport.send(
            self.node_id, leader, "internal:cluster/shard_started",
            {"index": index, "shard": shard, "node_id": self.node_id},
            on_response=None, on_failure=lambda e: None,
        )

    def _on_shard_started(self, sender: str, payload: dict) -> dict:
        if not self.is_leader:
            raise OpenSearchTpuException("not the leader")
        self.coordinator.submit_state_update(
            lambda s: mark_shard_started(
                s, payload["index"], payload["shard"], payload["node_id"]
            )
        )
        return {"ack": True}

    def _after_offload(self, fn, cb) -> None:
        """Run `fn` on the data worker; `cb(ok: bool)` fires back on the
        transport execution context (synchronously under the sim)."""
        out = self._offload(fn)
        from opensearch_tpu.transport.base import DeferredResponse

        if isinstance(out, DeferredResponse):
            out.on_done(lambda d: cb(d.error is None and bool(d.result)))
        else:
            cb(bool(out))

    def _start_replica_recovery(self, index: str, shard: int, state: ClusterState) -> None:
        """Target-side peer recovery (RecoveryTarget analog): request a
        manifest from the primary, stream what it names in bounded chunks
        (per-chunk timeout + exponential-backoff retry), catch up live
        writes via the seqno handoff, then report shard-started."""
        local = self.local_shards.get((index, shard))
        if local is not None:
            local.recovery_inflight = True
        primary = state.primary(index, shard)
        if primary is None or primary.node_id is None or primary.state != "STARTED":
            # retry later — the primary may still be initializing
            self.scheduler.schedule(
                500, lambda: self._retry_recovery(index, shard)
            )
            return
        from opensearch_tpu.index.recovery import (
            RecoveryProgress,
            RecoveryTargetDriver,
        )

        entry = next(
            (r for r in state.shards_for_node(self.node_id)
             if r.index == index and r.shard == shard), None
        )
        progress = RecoveryProgress(
            index, shard, self.node_id, primary.node_id,
            recovery_type=(
                "RELOCATION" if entry is not None and entry.relocating_node
                else "PEER"
            ),
        )
        self.recoveries[(index, shard)] = progress
        old = self._recovery_drivers.pop((index, shard), None)
        if old is not None:
            old.cancel()
        # target-side root span for this recovery attempt: every chunk,
        # retry and finalize request joins its trace (a retried attempt is
        # a FRESH span/trace — each attempt's tree stays self-consistent)
        rec_span = self.telemetry.tracer.begin_span(
            "recovery.target",
            {"index": index, "shard": shard, "node": self.node_id,
             "source": primary.node_id, "type": progress.recovery_type},
        )
        rec_trace = {"trace_id": rec_span.trace_id,
                     "span_id": rec_span.span_id}
        span_open = [True]

        def finish_span(outcome: str) -> None:
            if span_open[0]:
                span_open[0] = False
                rec_span.set_attribute("outcome", outcome)
                self.telemetry.tracer.end_span(rec_span)

        driver = RecoveryTargetDriver(
            self.transport, self.scheduler, self.node_id, primary.node_id,
            index, shard, progress, trace=rec_trace, root_span=rec_span,
        )
        self._recovery_drivers[(index, shard)] = driver

        def fail_and_retry(_e: Exception | None = None) -> None:
            if driver.cancelled:
                finish_span("cancelled")
                return
            progress.failed()
            finish_span("failed")
            if self._recovery_drivers.get((index, shard)) is driver:
                self._recovery_drivers.pop((index, shard), None)
            self.scheduler.schedule(
                1000, lambda: self._retry_recovery(index, shard)
            )

        def succeed() -> None:
            if driver.cancelled:
                # superseded mid-install (shard evicted/recreated): the
                # fresh driver owns the shard's fate — marking recovery_done
                # here would report a possibly-empty copy as STARTED
                finish_span("cancelled")
                return
            lcl = self.local_shards.get((index, shard))
            if lcl is not None:
                lcl.recovery_done = True
                lcl.recovery_inflight = False
            progress.done()
            finish_span("done")
            if self._recovery_drivers.get((index, shard)) is driver:
                self._recovery_drivers.pop((index, shard), None)
            self._report_shard_started(index, shard)

        def finalize_then(done_fn) -> None:
            lcl = self.local_shards.get((index, shard))
            if lcl is None:
                fail_and_retry()
                return
            driver.finalize(
                lambda: lcl.engine.local_checkpoint,
                lambda ok: done_fn() if ok else fail_and_retry(),
            )

        def on_manifest(resp) -> None:
            if driver.cancelled or not isinstance(resp, dict):
                fail_and_retry()
                return
            mode = resp.get("mode")
            if mode == "ops":
                self._recover_from_ops(index, shard, resp, progress,
                                       succeed, fail_and_retry)
            elif mode == "segment":
                self._recover_from_segments(
                    index, shard, resp, driver, progress,
                    lambda: finalize_then(succeed), fail_and_retry,
                )
            elif mode == "dump":
                self._recover_from_dump(
                    index, shard, resp, driver, progress,
                    lambda: finalize_then(succeed), fail_and_retry,
                )
            else:
                fail_and_retry()

        with tracing.restore_trace_context(rec_trace):
            self.transport.send(
                self.node_id, primary.node_id,
                "internal:index/shard/recovery/start",
                {"index": index, "shard": shard, "target": self.node_id,
                 # the target's recovered-from-disk progress: with a valid
                 # retention lease the source answers with an OPS-ONLY replay
                 # from here instead of a segment copy
                 "local_checkpoint": (
                     local.engine.local_checkpoint if local is not None else -1
                 )},
                on_response=on_manifest,
                on_failure=fail_and_retry,
                # the manifest itself is small; the bulk ships as chunks
                timeout_ms=60_000,
            )

    def _recover_from_ops(self, index: str, shard: int, resp: dict,
                          progress, succeed, fail) -> None:
        """Ops-only replay (retention-lease fast path): small by
        construction, applied in one offloaded step."""
        ops = resp.get("ops") or []
        progress.stage = "TRANSLOG"
        progress.ops_total = len(ops)

        def apply() -> bool:
            local = self.local_shards.get((index, shard))
            if local is None:
                return False
            for op in ops:
                if op["op"] == "index":
                    local.apply_index_on_replica(
                        op["id"], op["source"], op["seq_no"],
                        op.get("routing"),
                    )
                else:
                    local.apply_delete_on_replica(op["id"], op["seq_no"])
            # replayed history must survive a crash of this node
            local.engine.translog.sync()
            local.refresh()
            progress.ops_recovered = len(ops)
            return True

        self._after_offload(apply, lambda ok: succeed() if ok else fail())

    def _recover_from_segments(self, index: str, shard: int, resp: dict,
                               driver, progress, succeed, fail) -> None:
        """File-based recovery target: stream the primary's changed
        segments in byte-range chunks, install them verbatim (no
        re-analysis), append the translog tail, then FLUSH — the recovered
        state must survive a crash of this node (segments + commit +
        translog on disk)."""
        local = self.local_shards.get((index, shard))
        if local is None:
            fail()
            return
        have = local.engine.segment_sigs()
        want_sigs = resp.get("sigs") or {}
        order = list(resp["order"])
        need = [n for n in order if have.get(n) != want_sigs.get(n)]
        tail_ops = resp.get("ops") or []

        def after_files(ok: bool, blobs: dict) -> None:
            if not ok:
                fail()
                return

            def install() -> bool:
                from opensearch_tpu.index.segment import unpack_segment

                lcl = self.local_shards.get((index, shard))
                if lcl is None:
                    return False
                hosts = [unpack_segment(blobs[n]) for n in need if n in blobs]
                lcl.engine.install_replicated_segments(hosts, order)
                for op in tail_ops:
                    entry = lcl.engine.version_map.get(op["id"])
                    if entry is not None and entry.seq_no >= op["seq_no"]:
                        continue  # covered by an installed segment
                    lcl.engine.append_translog_op(op)
                # segments + tail form a point-in-time copy at max_seq_no;
                # superseded ops' seq-no holes must not pin the checkpoint
                # below the handoff (same contract as the dump path)
                lcl.engine.tracker.fast_forward_processed(
                    int(resp.get("max_seq_no", -1)))
                # durability: the recovered copy must survive a crash
                # BEFORE its first local flush (installed segments existed
                # only in memory until here)
                lcl.engine.flush()
                progress.ops_recovered = len(tail_ops)
                return True

            self._after_offload(install,
                                lambda ok2: succeed() if ok2 else fail())

        progress.ops_total = len(tail_ops)
        driver.fetch_files(need, resp.get("sizes") or {}, after_files)

    def _recover_from_dump(self, index: str, shard: int, resp: dict,
                           driver, progress, succeed, fail) -> None:
        """Logical live-doc dump, pulled in bounded batches and applied as
        each lands (document-replication fresh target)."""
        total = int(resp.get("total_ops", 0))

        def apply_batch(batch: list, cont) -> None:
            def run() -> bool:
                lcl = self.local_shards.get((index, shard))
                if lcl is None:
                    return False
                for op in batch:
                    if op["op"] == "index":
                        lcl.apply_index_on_replica(
                            op["id"], op["source"], op["seq_no"],
                            op.get("routing"),
                        )
                    else:
                        lcl.apply_delete_on_replica(op["id"], op["seq_no"])
                return True

            self._after_offload(run, cont)

        def after_ops(ok: bool) -> None:
            if not ok:
                fail()
                return

            def finish() -> bool:
                lcl = self.local_shards.get((index, shard))
                if lcl is None:
                    return False
                # the dump is a point-in-time snapshot at max_seq_no: ops
                # superseded before the snapshot (overwritten/deleted docs)
                # left seq-no holes no future op can fill — jump the local
                # checkpoint over them or the FINALIZE handoff wedges
                lcl.engine.tracker.fast_forward_processed(
                    int(resp.get("max_seq_no", -1)))
                lcl.engine.translog.sync()
                lcl.refresh()
                return True

            self._after_offload(finish,
                                lambda ok2: succeed() if ok2 else fail())

        driver.fetch_ops(total, apply_batch, after_ops)

    def _retry_recovery(self, index: str, shard: int) -> None:
        if (index, shard) in self.local_shards and not self.local_shards[(index, shard)].primary:
            entry = next(
                (r for r in self.applied_state.shards_for_node(self.node_id)
                 if r.index == index and r.shard == shard), None
            )
            if entry is not None and entry.state == "INITIALIZING":
                self._start_replica_recovery(index, shard, self.applied_state)

    def _on_start_recovery(self, sender: str, payload: dict):
        def run() -> dict:
            with tracing.activate(self.telemetry.tracer), \
                    self.telemetry.tracer.start_span("recovery.source_start", {
                        "index": payload["index"],
                        "shard": payload["shard"],
                        "target": payload.get("target"),
                        "node": self.node_id}):
                return self._start_recovery_local(payload)

        return self._offload(run)

    def _start_recovery_local(self, payload: dict) -> dict:
        """Primary-side recovery source. OPS-BASED fast path first
        (RecoverySourceHandler.recoverToTarget:171: when a peer-recovery
        retention lease retains history from the target's checkpoint,
        phase1 file copy is SKIPPED entirely and phase2 replays the ops);
        otherwise SEGMENT replication ships the sealed segment files +
        translog tail, and DOCUMENT replication the logical live-doc dump."""
        shard = self._local_shard(payload["index"], payload["shard"])
        target = payload["target"]
        target_ckpt = int(payload.get("local_checkpoint", -1))
        # a target that died mid-transfer without being evicted must not
        # pin packed blobs forever
        self._recovery_sources.reap()
        # ops-based recovery serves DOCUMENT replication; a segrep replica's
        # searchable state is the primary's segment set, so its recovery
        # stays the sig-diff file sync (only changed segments transfer)
        if target_ckpt >= 0 and shard.replication != "SEGMENT":
            # track BEFORE snapshotting history (same invariant as the
            # full-dump path below): a write landing in between must reach
            # the target through the fan-out
            self._tracked_targets.setdefault(
                (payload["index"], payload["shard"]), set()
            ).add(target)
            ops = shard.engine.history_ops_from(target_ckpt + 1)
            if ops is not None:
                shard.engine.retention_leases.add_or_renew(
                    f"peer_recovery/{target}", target_ckpt + 1,
                    _wall_ms(),
                )
                self.recovery_stats["ops_based"] += 1
                return {"mode": "ops", "ops": ops,
                        "max_seq_no": shard.engine.max_seq_no}
        if shard.replication == "SEGMENT":
            self._tracked_targets.setdefault(
                (payload["index"], payload["shard"]), set()
            ).add(payload["target"])
            self.recovery_stats["segment_based"] += 1
            # phase1 manifest only — the target pulls each needed segment
            # as byte-range chunks from the session opened here (bounded
            # frame sizes); phase2 = the translog tail in the manifest
            session = self._recovery_sources.open(
                payload["index"], payload["shard"], target,
                mode="segment",
                max_seq_no=shard.engine.max_seq_no,
            )
            # immutable host refs captured NOW; chunks pack lazily from them
            session["hosts"] = {
                h.name: h for h, _dev in shard.engine._segments
            }
            return {
                "mode": "segment",
                "order": shard.engine.segment_names(),
                "sigs": shard.engine.segment_sigs(),
                "ops": shard.engine.translog_tail_ops(),
                "max_seq_no": shard.engine.max_seq_no,
            }
        # track the target BEFORE snapshotting: every write from here on is
        # fanned out to it, and the seq_no stale-op check on the target makes
        # the dump/fan-out overlap idempotent in either arrival order
        self._tracked_targets.setdefault(
            (payload["index"], payload["shard"]), set()
        ).add(payload["target"])
        # establish the peer lease NOW: a flush landing between this dump
        # and the copy's first write-ack must not trim the history its next
        # ops-based recovery would need
        shard.engine.retention_leases.add_or_renew(
            f"peer_recovery/{target}", shard.engine.max_seq_no + 1,
            _wall_ms(),
        )
        engine = shard.engine
        ops: list[dict] = []
        snapshot = engine.acquire_searcher()
        # buffered (not yet refreshed) docs
        seen: set[str] = set()
        for entry in engine._buffer:
            if entry is None:
                continue
            parsed, seq = entry
            ops.append({"op": "index", "id": parsed.doc_id, "source": parsed.source,
                        "seq_no": seq, "routing": parsed.routing})
            seen.add(parsed.doc_id)
        for host, _dev in snapshot.segments:
            for d in range(host.n_docs):
                if not host.live[d]:
                    continue
                doc_id = host.doc_ids[d]
                if doc_id in seen:
                    continue
                entry2 = engine.version_map.get(doc_id)
                ops.append({
                    "op": "index", "id": doc_id,
                    "source": json.loads(host.sources[d]),
                    "seq_no": entry2.seq_no if entry2 else 0,
                    "routing": None,
                })
        # tombstones make the dump a COMPLETE logical point-in-time copy:
        # a STALE target (an old replica re-recovering after a fault) may
        # still hold docs deleted here while it was away — live docs alone
        # can't tell it, and the checkpoint fast-forward at the end of the
        # dump apply would jump the delete's seq_no without ever applying
        # it (a lost delete: the doc resurrects on the replica). Shipping
        # each retained tombstone at its TRUE seq_no lets the target apply
        # the miss; the per-doc stale check keeps replays idempotent.
        ops.extend(sorted(
            ({"op": "delete", "id": doc_id, "seq_no": entry3.seq_no}
             for doc_id, entry3 in engine.version_map.items()
             if entry3.deleted),
            key=lambda o: o["seq_no"],
        ))
        # the dump stays on the source as a SESSION; the target pulls it in
        # bounded batches (chunked phase2 instead of one giant frame)
        self.recovery_stats["dump_based"] += 1
        self._recovery_sources.open(
            payload["index"], payload["shard"], target,
            mode="dump", ops=ops, max_seq_no=engine.max_seq_no,
        )
        return {"mode": "dump", "total_ops": len(ops),
                "max_seq_no": engine.max_seq_no}

    # -- recovery chunk serving (source side) -------------------------------

    def _on_recovery_file_chunk(self, sender: str, payload: dict):
        def run() -> dict:
            with tracing.activate(self.telemetry.tracer), \
                    self.telemetry.tracer.start_span("recovery.file_chunk", {
                        "index": payload["index"],
                        "shard": payload["shard"],
                        "name": payload.get("name"),
                        "offset": payload.get("offset", 0),
                        "node": self.node_id}):
                return self._file_chunk_local(payload)

        return self._offload(run)

    def _file_chunk_local(self, payload: dict) -> dict:
        key = (payload["index"], payload["shard"], payload["target"])
        session = self._recovery_sources.get(*key)
        if session is None:
            raise OpenSearchTpuException(
                f"no recovery session for [{payload['index']}]"
                f"[{payload['shard']}] -> {payload['target']}"
            )
        name = payload["name"]
        if name not in session["blobs"]:
            host = (session.get("hosts") or {}).get(name)
            if host is None:
                raise OpenSearchTpuException(
                    f"segment [{name}] not in recovery session"
                )
            from opensearch_tpu.index.segment import pack_segment

            # pack lazily, once; retried chunks re-read the same bytes
            session["blobs"][name] = pack_segment(host)
        from opensearch_tpu.index.recovery import DEFAULT_CHUNK_BYTES

        return self._recovery_sources.file_chunk(
            payload["index"], payload["shard"], payload["target"],
            name, int(payload.get("offset", 0)),
            int(payload.get("length") or 0) or DEFAULT_CHUNK_BYTES,
        )

    def _on_recovery_ops_chunk(self, sender: str, payload: dict) -> dict:
        with tracing.activate(self.telemetry.tracer), \
                self.telemetry.tracer.start_span("recovery.ops_chunk", {
                    "index": payload["index"], "shard": payload["shard"],
                    "from": payload.get("from", 0), "node": self.node_id}):
            try:
                return self._recovery_sources.ops_batch(
                    payload["index"], payload["shard"], payload["target"],
                    int(payload.get("from", 0)),
                    int(payload.get("size", 0) or 500),
                )
            except KeyError as e:
                raise OpenSearchTpuException(str(e)) from e

    def _on_recovery_finalize(self, sender: str, payload: dict) -> dict:
        """Seqno handoff: report the primary's max_seq_no so the target can
        verify it caught up before the routing swap; the chunk session is
        done (fan-out to the tracked target carries everything newer)."""
        with self.telemetry.tracer.start_span("recovery.finalize", {
                "index": payload["index"], "shard": payload["shard"],
                "target": payload.get("target"), "node": self.node_id}):
            shard = self._local_shard(payload["index"], payload["shard"])
            self._recovery_sources.close(
                payload["index"], payload["shard"], payload["target"]
            )
            return {"max_seq_no": shard.engine.max_seq_no}

    def _on_node_recovery(self, sender: str, payload: dict) -> dict:
        """Per-node recovery progress records (RecoveryState collection
        backing GET [/{index}]/_recovery and _cat/recovery)."""
        want = payload.get("indices")
        return {"recoveries": [
            p.to_dict() for (index, _shard), p in sorted(
                self.recoveries.items())
            if want is None or index in want
        ]}

    # -- cluster snapshots (ClusterSnapshotsService orchestrates) -----------

    def _on_snapshot_shard_dump(self, sender: str, payload: dict):
        """Logical point-in-time live-doc set of a local shard copy: the
        unrefreshed buffer (later write wins), segment live docs, minus
        anything the version map says is deleted. Runs on the data worker
        so the engine's single-writer discipline holds while we walk the
        buffer."""

        def run() -> dict:
            shard = self._local_shard(payload["index"], payload["shard"])
            engine = shard.engine
            by_id: dict[str, Any] = {}
            for entry in engine._buffer:
                if entry is None:
                    continue
                parsed, _seq = entry
                by_id[parsed.doc_id] = parsed.source
            snapshot = engine.acquire_searcher()
            for host, _dev in snapshot.segments:
                for d in range(host.n_docs):
                    if not host.live[d]:
                        continue
                    doc_id = host.doc_ids[d]
                    if doc_id not in by_id:
                        by_id[doc_id] = json.loads(host.sources[d])
            for doc_id, vme in engine.version_map.items():
                if vme.deleted:
                    by_id.pop(doc_id, None)
            return {
                "docs": [{"id": i, "source": by_id[i]} for i in sorted(by_id)],
                "max_seq_no": engine.max_seq_no,
            }

        return self._offload(run)

    def _on_snapshot_restore_dump(self, sender: str, payload: dict):
        """Install a snapshot shard's doc set into a freshly created
        primary (restore targets are replicas=0, so primary-only install
        is the complete copy)."""

        def run() -> dict:
            shard = self._local_shard(payload["index"], payload["shard"])
            if not shard.primary:
                raise OpenSearchTpuException(
                    f"restore target [{payload['index']}][{payload['shard']}]"
                    f" on [{self.node_id}] is not the primary"
                )
            for op in payload["docs"]:
                shard.apply_index_on_primary(op["id"], op["source"])
            shard.engine.translog.sync()
            shard.refresh()
            return {"restored": len(payload["docs"])}

        return self._offload(run)

    # ------------------------------------------------------------------ #
    # metadata APIs (routed to the leader)
    # ------------------------------------------------------------------ #

    def _leader_or_raise(self) -> str:
        leader = self.coordinator.leader_id
        if leader is None:
            raise OpenSearchTpuException("no elected cluster manager")
        return leader

    def create_index(self, name: str, body: dict | None,
                     callback: Callable[[dict], None]) -> None:
        self.transport.send(
            self.node_id, self._leader_or_raise(), "cluster:admin/create_index",
            {"name": name, "body": body or {}},
            on_response=callback,
            on_failure=lambda e: callback({"error": str(e)}),
        )

    def delete_index(self, name: str, callback: Callable[[dict], None]) -> None:
        self.transport.send(
            self.node_id, self._leader_or_raise(), "cluster:admin/delete_index",
            {"name": name},
            on_response=callback,
            on_failure=lambda e: callback({"error": str(e)}),
        )

    def put_mapping(self, name: str, mappings: dict,
                    callback: Callable[[dict], None]) -> None:
        self.transport.send(
            self.node_id, self._leader_or_raise(), "cluster:admin/put_mapping",
            {"name": name, "mappings": mappings},
            on_response=callback,
            on_failure=lambda e: callback({"error": str(e)}),
        )

    def _disk_usage(self) -> float | None:
        if self.disk_usage_pct is not None:
            return self.disk_usage_pct
        try:
            import shutil

            du = shutil.disk_usage(self.data_path)
            return 100.0 * (du.total - du.free) / du.total
        except OSError:
            return None

    def _on_update_settings(self, sender: str, payload: dict) -> dict:
        """PUT /_cluster/settings routed to the leader: validate, then a
        cluster-state task merges persistent/transient (null deletes) —
        the two-phase apply of ClusterSettings.java:205."""
        if not self.is_leader:
            raise OpenSearchTpuException("not the leader")
        from opensearch_tpu.cluster.cluster_settings import (
            flatten,
            merge,
            validate_settings,
        )

        persistent = flatten(payload.get("persistent") or {})
        transient = flatten(payload.get("transient") or {})
        validate_settings(persistent)
        validate_settings(transient)

        def task(state: ClusterState) -> ClusterState:
            return state.with_(
                settings=merge(state.settings, persistent),
                transient_settings=merge(state.transient_settings, transient),
            )

        self.coordinator.submit_state_update(task)
        return {
            "acknowledged": True,
            "persistent": persistent,
            "transient": transient,
        }

    def _on_create_index(self, sender: str, payload: dict) -> dict:
        if not self.is_leader:
            raise OpenSearchTpuException("not the leader")
        name = payload["name"]
        body = payload["body"]
        settings = body.get("settings") or {}
        index_settings = settings.get("index", settings)

        def task(state: ClusterState) -> ClusterState:
            if name in state.indices:
                return state
            meta = IndexMeta(
                name=name,
                num_shards=int(index_settings.get("number_of_shards", 1)),
                num_replicas=int(index_settings.get("number_of_replicas", 1)),
                settings=index_settings,
                mappings=body.get("mappings") or {},
            )
            return reroute(state.with_(indices={**state.indices, name: meta}))

        self.coordinator.submit_state_update(task)
        return {"acknowledged": True, "index": name}

    def _on_delete_index(self, sender: str, payload: dict) -> dict:
        if not self.is_leader:
            raise OpenSearchTpuException("not the leader")
        name = payload["name"]

        def task(state: ClusterState) -> ClusterState:
            if name not in state.indices:
                return state
            indices = {k: v for k, v in state.indices.items() if k != name}
            routing = tuple(r for r in state.routing if r.index != name)
            return state.with_(indices=indices, routing=routing)

        self.coordinator.submit_state_update(task)
        return {"acknowledged": True}

    def _on_put_mapping(self, sender: str, payload: dict) -> dict:
        if not self.is_leader:
            raise OpenSearchTpuException("not the leader")
        name, mappings = payload["name"], payload["mappings"]

        def task(state: ClusterState) -> ClusterState:
            meta = state.indices.get(name)
            if meta is None:
                return state
            # validate by merging into a scratch mapper service
            ms = MapperService(meta.mappings or None)
            ms.merge(mappings)
            new_meta = IndexMeta(
                meta.name, meta.num_shards, meta.num_replicas, meta.settings,
                ms.to_dict(), meta.version + 1,
            )
            return state.with_(indices={**state.indices, name: new_meta})

        self.coordinator.submit_state_update(task)
        return {"acknowledged": True}

    # ------------------------------------------------------------------ #
    # write path (TransportReplicationAction analog)
    # ------------------------------------------------------------------ #

    def _routing_for_doc(self, index: str, doc_id: str, routing: str | None):
        state = self.applied_state
        meta = state.indices.get(index)
        if meta is None:
            raise IndexNotFoundException(index)
        shard_num = shard_id_for_routing(routing or doc_id, meta.num_shards)
        primary = state.primary(index, shard_num)
        if primary is None or primary.node_id is None:
            raise ShardNotFoundException(f"no primary for [{index}][{shard_num}]")
        return shard_num, primary

    # transient write-routing retry: a relocation swap or primary failover
    # can make the routed primary reject the write with
    # ShardNotFoundException ("not on node ..." — the copy moved away) or
    # leave the routing table momentarily without a primary. Both heal
    # within one or two cluster-state publications, so the coordinator
    # retries with RE-RESOLVED routing under exponential backoff instead of
    # surfacing a 5xx for a perfectly healthy cluster. Only routing-shaped
    # failures retry — the write provably never applied, so the retry
    # cannot double-apply.
    WRITE_RETRY_ATTEMPTS = 5
    WRITE_RETRY_BASE_MS = 100

    @staticmethod
    def _is_transient_routing_error(err) -> bool:
        text = str(err)
        return ("ShardNotFoundException" in type(err).__name__
                or "not on node" in text or "no primary for" in text)

    def _write_with_retry(self, build_payload, callback, attempt: int = 0):
        """`build_payload()` re-resolves routing and returns (primary_node,
        payload); raises ShardNotFoundException while routing is in flux."""
        def retry_or_fail(err) -> None:
            if (attempt + 1 < self.WRITE_RETRY_ATTEMPTS
                    and self._is_transient_routing_error(err)
                    and not getattr(self, "_closed", False)):
                self.scheduler.schedule(
                    self.WRITE_RETRY_BASE_MS * (2 ** attempt),
                    lambda: self._write_with_retry(
                        build_payload, callback, attempt + 1),
                )
            else:
                callback({"error": str(err)})

        try:
            primary_node, payload = build_payload()
        except OpenSearchTpuException as e:
            retry_or_fail(e)
            return

        def on_response(resp: dict) -> None:
            # the primary answers routing staleness as an error response
            # (handler raises travel back through on_failure; loopback
            # handlers may surface them as {"error"} dicts)
            if (isinstance(resp, dict) and "error" in resp
                    and self._is_transient_routing_error(
                        RuntimeError(resp["error"]))):
                retry_or_fail(RuntimeError(resp["error"]))
            else:
                callback(resp)

        self.transport.send(
            self.node_id, primary_node, "indices:data/write[p]", payload,
            on_response=on_response, on_failure=retry_or_fail,
        )

    def index_doc(self, index: str, doc_id: str, source: dict,
                  callback: Callable[[dict], None], routing: str | None = None,
                  if_seq_no: int | None = None,
                  op_type: str | None = None) -> None:
        def build():
            shard_num, primary = self._routing_for_doc(index, doc_id, routing)
            return primary.node_id, {
                "index": index, "shard": shard_num, "op": "index",
                "id": doc_id, "source": source, "routing": routing,
                "if_seq_no": if_seq_no, "op_type": op_type}

        self._write_with_retry(build, callback)

    def delete_doc(self, index: str, doc_id: str,
                   callback: Callable[[dict], None], routing: str | None = None) -> None:
        def build():
            shard_num, primary = self._routing_for_doc(index, doc_id, routing)
            return primary.node_id, {
                "index": index, "shard": shard_num, "op": "delete",
                "id": doc_id, "routing": routing}

        self._write_with_retry(build, callback)

    def bulk(self, operations: list[tuple[str, dict, dict | None]],
             callback: Callable[[dict], None],
             query_group: str | None = None) -> None:
        """TransportBulkAction analog: group items by owning SHARD and send
        ONE shard-bulk RPC per (shard, primary) — TransportShardBulkAction's
        batching (one replication round per shard, not per document). Item
        order is preserved in the response regardless of completion order.

        `query_group` tags the request for wlm admission: an enforced group
        past its bulk slot share sheds the WHOLE request with a 429-shaped
        error before any fan-out (no queue slots, no pending callbacks)."""
        from opensearch_tpu.common.timeutil import monotonic_millis

        from opensearch_tpu.common.errors import RejectedExecutionException

        try:
            release_admission = self.query_groups.admit_bulk(query_group)
        except RejectedExecutionException as e:
            # typed-name prefix so facade._on_loop rehydrates the 429
            callback({"error": f"RejectedExecutionException: {e}",
                      "status": 429})
            return
        callback = _release_then(release_admission, callback)

        t0 = monotonic_millis()
        n = len(operations)
        if n == 0:
            callback({"took": 0, "errors": False, "items": []})
            return
        items: list[dict | None] = [None] * n
        state = {"errors": False}

        # group by (index, shard): [(item_idx, action, op_payload)]
        groups: dict[tuple[str, int], list] = {}
        group_primary: dict[tuple[str, int], str] = {}
        for i, (action, meta, source) in enumerate(operations):
            index = meta.get("_index")
            doc_id = meta.get("_id")
            routing = meta.get("routing") or meta.get("_routing")
            try:
                if action not in ("index", "create", "delete"):
                    raise OpenSearchTpuException(
                        f"unsupported bulk action [{action}]"
                    )
                shard_num, primary = self._routing_for_doc(
                    index, doc_id, routing
                )
            except OpenSearchTpuException as e:
                state["errors"] = True
                items[i] = {action: {"error": str(e), "status": 500}}
                continue
            key = (index, shard_num)
            group_primary[key] = primary.node_id
            op = {"op": "index" if action in ("index", "create") else "delete",
                  "id": doc_id, "routing": routing}
            if action in ("index", "create"):
                op["source"] = source
                if action == "create":
                    op["op_type"] = "create"
            groups.setdefault(key, []).append((i, action, op))

        pending = {"n": len(groups)}

        def done_if_last() -> None:
            pending["n"] -= 1
            if pending["n"] == 0:
                callback({
                    "took": monotonic_millis() - t0,
                    "errors": state["errors"],
                    "items": items,
                })

        if not groups:
            callback({"took": monotonic_millis() - t0,
                      "errors": state["errors"], "items": items})
            return

        for key, group in groups.items():
            index, shard_num = key

            def on_response(g=group):
                def handle(resp: dict) -> None:
                    results = (resp or {}).get("items", [])
                    for (i, action, _op), r in zip(g, results):
                        if "error" in r:
                            state["errors"] = True
                            items[i] = {action: {"error": r["error"],
                                                 "status": r.get("status", 500)}}
                        else:
                            status = (201 if r.get("result") == "created"
                                      else 200)
                            items[i] = {action: {**r, "status": status}}
                    done_if_last()
                return handle

            def on_failure(g=group):
                def handle(e: Exception) -> None:
                    state["errors"] = True
                    for (i, action, _op) in g:
                        items[i] = {action: {"error": str(e), "status": 500}}
                    done_if_last()
                return handle

            self.transport.send(
                self.node_id, group_primary[key], "indices:data/write[p][bulk]",
                {"index": index, "shard": shard_num,
                 "ops": [op for _i, _a, op in group]},
                on_response=on_response(), on_failure=on_failure(),
            )

    def cluster_health(self) -> dict:
        """Computed from the applied state on ANY node (ClusterStateHealth
        analog) — no leader round-trip needed for a health read."""
        state = self.applied_state
        total = len(state.routing)
        # a RELOCATING copy is a fully started copy that happens to be
        # moving — it serves reads and counts active (ClusterStateHealth)
        active = sum(1 for r in state.routing
                     if r.state in ("STARTED", "RELOCATING"))
        active_primaries = sum(
            1 for r in state.routing
            if r.primary and r.state in ("STARTED", "RELOCATING")
        )
        unassigned = sum(1 for r in state.routing if r.state == "UNASSIGNED")
        relocating = sum(1 for r in state.routing if r.state == "RELOCATING")
        initializing = sum(
            1 for r in state.routing
            if r.state == "INITIALIZING" and not r.is_relocation_target
        )
        primaries_down = any(
            r.primary and r.state not in ("STARTED", "RELOCATING")
            for r in state.routing
        )
        status = ("red" if primaries_down
                  else "yellow" if unassigned or initializing else "green")
        return {
            "cluster_name": "opensearch-tpu",
            "status": status,
            "number_of_nodes": len(state.nodes),
            "number_of_data_nodes": sum(
                1 for nd in state.nodes.values() if nd.is_data
            ),
            "active_primary_shards": active_primaries,
            "active_shards": active,
            "relocating_shards": relocating,
            "initializing_shards": initializing,
            "unassigned_shards": unassigned,
            "cluster_manager_node": state.leader_id,
            "active_shards_percent_as_number": (
                100.0 * active / total if total else 100.0
            ),
        }

    def _local_shard(self, index: str, shard: int) -> IndexShard:
        local = self.local_shards.get((index, shard))
        if local is None:
            raise ShardNotFoundException(f"[{index}][{shard}] not on node {self.node_id}")
        return local

    def _on_primary_write(self, sender: str, payload: dict):
        """Primary write: apply + fsync locally (on the data worker, off
        the transport loop), fan out to every assigned replica copy, and —
        crucially — ACK ONLY AFTER EVERY COPY ANSWERED
        (ReplicationOperation.java:77: the response waits for all in-sync
        copies; a replica that fails is evicted via a shard-failed leader
        task before the ack, so an acknowledged write can never be lost by
        promoting that stale copy)."""
        applied = self._offload(lambda: self._apply_primary_local(payload))
        from opensearch_tpu.transport.base import DeferredResponse

        if not isinstance(applied, DeferredResponse):  # sim: synchronous
            return self._continue_primary_write(payload, applied)
        final = DeferredResponse()

        def after(d: DeferredResponse) -> None:
            if d.error is not None:
                final.set_exception(d.error)
                return
            try:
                cont = self._continue_primary_write(payload, d.result)
            except Exception as e:  # noqa: BLE001 - must fail the listener
                # a raise here runs on the transport loop's completion
                # callback: nobody above us would resolve `final`, and the
                # client's write would wedge until (sim: forever) timeout
                final.set_exception(e)
                return
            if isinstance(cont, DeferredResponse):
                cont.on_done(lambda c: (
                    final.set_exception(c.error) if c.error is not None
                    else final.set_result(c.result)
                ))
            else:
                final.set_result(cont)

        applied.on_done(after)
        return final

    def _apply_primary_local(self, payload: dict):
        shard = self._local_shard(payload["index"], payload["shard"])
        if payload["op"] == "index":
            if payload.get("op_type") == "create":
                existing = shard.get(payload["id"])
                if existing is not None:
                    from opensearch_tpu.common.errors import (
                        VersionConflictException,
                    )

                    raise VersionConflictException(
                        f"[{payload['id']}]: version conflict, document "
                        f"already exists"
                    )
            result = shard.apply_index_on_primary(
                payload["id"], payload["source"], payload.get("routing"),
                if_seq_no=payload.get("if_seq_no"),
            )
        else:
            result = shard.apply_delete_on_primary(
                payload["id"], if_seq_no=payload.get("if_seq_no")
            )
        shard.maybe_sync_translog()
        return result

    def _continue_primary_write(self, payload: dict, result):
        index, shard_num = payload["index"], payload["shard"]
        # fan out to every assigned replica copy — STARTED, RELOCATING and
        # recovering alike (performOnReplicas sends to all in-sync + tracked
        # copies; a recovering replica dedups via seq_no)
        state = self.applied_state
        target_nodes = {
            r.node_id for r in state.shards_for_index(index)
            if r.shard == shard_num and not r.primary
            and r.state in ("STARTED", "INITIALIZING", "RELOCATING")
            and r.node_id is not None
        }
        target_nodes |= self._tracked_targets.get((index, shard_num), set())
        target_nodes.discard(self.node_id)

        def response(failed: int) -> dict:
            return {
                "_index": index, "_id": payload["id"],
                "_version": result.version, "_seq_no": result.seq_no,
                "result": result.result,
                "_shards": {"total": 1 + len(target_nodes),
                            "successful": 1 + len(target_nodes) - failed,
                            "failed": failed},
            }

        if not target_nodes:
            return response(0)

        from opensearch_tpu.transport.base import DeferredResponse

        deferred = DeferredResponse()
        pending = {"n": len(target_nodes), "failed": 0}
        replica_payload = dict(payload, seq_no=result.seq_no, version=result.version)

        def one_done() -> None:
            pending["n"] -= 1
            if pending["n"] == 0:
                deferred.set_result(response(pending["failed"]))

        def make_on_ack(nid: str):
            def on_ack(resp: Any) -> None:
                self._renew_peer_lease(index, shard_num, nid, resp)
                one_done()
            return on_ack

        def make_on_fail(nid: str):
            def on_fail(_e: Exception) -> None:
                # evict the unreachable copy BEFORE acking (ShardStateAction
                # shard-failed; the leader reroutes and the copy must
                # re-recover). If the leader is unreachable too the ack
                # still proceeds — the election path removes dead nodes.
                pending["failed"] += 1
                self._report_shard_failed(index, shard_num, nid, one_done)
            return on_fail

        for nid in sorted(target_nodes):
            self.transport.send(
                self.node_id, nid, "indices:data/write[r]", replica_payload,
                on_response=make_on_ack(nid), on_failure=make_on_fail(nid),
            )
        return deferred

    def _renew_peer_lease(self, index: str, shard_num: int, nid: str,
                          resp: Any) -> None:
        """Advance the replica's peer-recovery retention lease to its acked
        local checkpoint + 1: everything at or below the checkpoint is
        durable on that copy, so history above it is all a future ops-based
        recovery would need (ReplicationTracker.renewRetentionLease)."""
        if not isinstance(resp, dict) or "local_checkpoint" not in resp:
            return
        local = self.local_shards.get((index, shard_num))
        if local is None or not local.primary:
            return
        local.engine.retention_leases.add_or_renew(
            f"peer_recovery/{nid}", int(resp["local_checkpoint"]) + 1,
            _wall_ms(),
        )

    # -- shard-level bulk (TransportShardBulkAction.performOnPrimary) -------

    def _on_primary_bulk(self, sender: str, payload: dict):
        """Apply a batch of ops on the primary, then ONE batched replica
        round per copy; ack after every copy answered."""
        applied = self._offload(lambda: self._apply_primary_bulk_local(payload))
        from opensearch_tpu.transport.base import DeferredResponse

        if not isinstance(applied, DeferredResponse):
            return self._continue_primary_bulk(payload, applied)
        final = DeferredResponse()

        def after(d: DeferredResponse) -> None:
            if d.error is not None:
                final.set_exception(d.error)
                return
            try:
                cont = self._continue_primary_bulk(payload, d.result)
            except Exception as e:  # noqa: BLE001 - must fail the listener
                # same leak class as the single-doc path: an unresolved
                # `final` never ships a response frame
                final.set_exception(e)
                return
            if isinstance(cont, DeferredResponse):
                cont.on_done(lambda c: (
                    final.set_exception(c.error) if c.error is not None
                    else final.set_result(c.result)
                ))
            else:
                final.set_result(cont)

        applied.on_done(after)
        return final

    def _apply_primary_bulk_local(self, payload: dict) -> list[dict]:
        shard = self._local_shard(payload["index"], payload["shard"])
        results: list[dict] = []
        for op in payload["ops"]:
            try:
                r = self._apply_primary_local(
                    {"index": payload["index"], "shard": payload["shard"],
                     **op}
                )
                results.append({
                    "_index": payload["index"], "_id": op["id"],
                    "_version": r.version, "_seq_no": r.seq_no,
                    "result": r.result, "seq_no": r.seq_no,
                    "version": r.version,
                })
            except OpenSearchTpuException as e:
                results.append({"error": str(e), "_id": op["id"],
                                "status": getattr(e, "status", 500)})
        shard.maybe_sync_translog()
        return results

    def _continue_primary_bulk(self, payload: dict, results: list[dict]):
        index, shard_num = payload["index"], payload["shard"]
        state = self.applied_state
        target_nodes = {
            r.node_id for r in state.shards_for_index(index)
            if r.shard == shard_num and not r.primary
            and r.state in ("STARTED", "INITIALIZING", "RELOCATING")
            and r.node_id is not None
        }
        target_nodes |= self._tracked_targets.get((index, shard_num), set())
        target_nodes.discard(self.node_id)

        def response(failed: int) -> dict:
            n_copies = 1 + len(target_nodes)
            items = []
            for r in results:
                if "error" in r:
                    items.append(r)
                else:
                    items.append({
                        "_index": r["_index"], "_id": r["_id"],
                        "_version": r["_version"], "_seq_no": r["_seq_no"],
                        "result": r["result"],
                        "_shards": {"total": n_copies,
                                    "successful": n_copies - failed,
                                    "failed": failed},
                    })
            return {"items": items}

        if not target_nodes:
            return response(0)
        from opensearch_tpu.transport.base import DeferredResponse

        deferred = DeferredResponse()
        pending = {"n": len(target_nodes), "failed": 0}
        # replicate only the ops that applied (with their seq_nos)
        rep_ops = [
            {**op, "seq_no": r["seq_no"], "version": r["version"]}
            for op, r in zip(payload["ops"], results) if "error" not in r
        ]
        rep_payload = {"index": index, "shard": shard_num, "ops": rep_ops}

        def one_done() -> None:
            pending["n"] -= 1
            if pending["n"] == 0:
                deferred.set_result(response(pending["failed"]))

        def make_on_fail(nid: str):
            def on_fail(_e: Exception) -> None:
                pending["failed"] += 1
                self._report_shard_failed(index, shard_num, nid, one_done)
            return on_fail

        def make_on_ack(nid: str):
            def on_ack(resp: Any) -> None:
                self._renew_peer_lease(index, shard_num, nid, resp)
                one_done()
            return on_ack

        for nid in sorted(target_nodes):
            self.transport.send(
                self.node_id, nid, "indices:data/write[r][bulk]", rep_payload,
                on_response=make_on_ack(nid),
                on_failure=make_on_fail(nid),
            )
        return deferred

    def _on_replica_bulk(self, sender: str, payload: dict):
        def run() -> dict:
            shard = self._local_shard(payload["index"], payload["shard"])
            for op in payload["ops"]:
                if shard.replication == "SEGMENT":
                    top = {"op": op["op"], "id": op["id"],
                           "seq_no": op["seq_no"],
                           "version": op.get("version", 1)}
                    if op["op"] == "index":
                        top["source"] = op["source"]
                        top["routing"] = op.get("routing")
                    shard.engine.append_translog_op(top)
                elif op["op"] == "index":
                    shard.apply_index_on_replica(
                        op["id"], op["source"], op["seq_no"],
                        op.get("routing"),
                    )
                else:
                    shard.apply_delete_on_replica(op["id"], op["seq_no"])
            shard.maybe_sync_translog()
            return {"ack": True,
                    "local_checkpoint": shard.engine.local_checkpoint}

        return self._offload(run)

    # a lost shard-failed report must be RETRIED: the failing copy missed
    # a write, and if no leader ever learns, it stays STARTED with stale
    # data forever — permanent copy divergence (the chaos soak's
    # copy-agreement invariant caught exactly this under one-way drops
    # that also severed the primary -> leader path)
    _SHARD_FAILED_RETRY_MS = 1_000
    _SHARD_FAILED_MAX_RETRIES = 30

    def _report_shard_failed(self, index: str, shard: int, node_id: str,
                             done: Callable[[], None],
                             _attempt: int = 0) -> None:
        leader = self.coordinator.leader_id

        def settle_and_retry(_e: Exception | None = None) -> None:
            done()
            self._retry_shard_failed(index, shard, node_id, _attempt)

        if leader is None:
            settle_and_retry()
            return
        self.transport.send(
            self.node_id, leader, "internal:cluster/shard_failed",
            {"index": index, "shard": shard, "node_id": node_id},
            on_response=lambda _r: done(),
            on_failure=settle_and_retry,
        )

    def _retry_shard_failed(self, index: str, shard: int, node_id: str,
                            attempt: int) -> None:
        if getattr(self, "_closed", False) or \
                attempt >= self._SHARD_FAILED_MAX_RETRIES:
            return

        def tick() -> None:
            if getattr(self, "_closed", False):
                return
            entry = next(
                (r for r in self.applied_state.shards_for_index(index)
                 if r.shard == shard and r.node_id == node_id
                 and r.state in ("STARTED", "RELOCATING")), None)
            if entry is None:
                return  # the leader evicted/moved the copy — resolved
            self._report_shard_failed(index, shard, node_id,
                                      lambda: None, attempt + 1)

        self.scheduler.schedule(self._SHARD_FAILED_RETRY_MS, tick)

    def _on_shard_failed(self, sender: str, payload: dict) -> dict:
        if not self.is_leader:
            raise OpenSearchTpuException("not the leader")
        from opensearch_tpu.cluster.allocation import mark_shard_failed

        self.coordinator.submit_state_update(
            lambda s: mark_shard_failed(
                s, payload["index"], payload["shard"], payload["node_id"]
            )
        )
        return {"ack": True}

    def _on_replica_write(self, sender: str, payload: dict):
        def run() -> dict:
            shard = self._local_shard(payload["index"], payload["shard"])
            if shard.replication == "SEGMENT":
                # segrep replica: durability only — the op reaches the
                # searchable set via the primary's segment checkpoints
                op = {"op": payload["op"], "id": payload["id"],
                      "seq_no": payload["seq_no"],
                      "version": payload.get("version", 1)}
                if payload["op"] == "index":
                    op["source"] = payload["source"]
                    op["routing"] = payload.get("routing")
                shard.engine.append_translog_op(op)
            elif payload["op"] == "index":
                shard.apply_index_on_replica(
                    payload["id"], payload["source"], payload["seq_no"],
                    payload.get("routing"),
                )
            else:
                shard.apply_delete_on_replica(payload["id"], payload["seq_no"])
            # replica acks are durability promises too (the primary counts
            # this copy in-sync based on them): fsync before responding
            shard.maybe_sync_translog()
            # the ack carries the replica's local checkpoint so the primary
            # can advance this copy's retention lease (the reference
            # piggybacks it on every ReplicationResponse)
            return {"ack": True,
                    "local_checkpoint": shard.engine.local_checkpoint}

        return self._offload(run)

    # ------------------------------------------------------------------ #
    # read path
    # ------------------------------------------------------------------ #

    def get_doc(self, index: str, doc_id: str,
                callback: Callable[[dict], None], routing: str | None = None) -> None:
        shard_num, primary = self._routing_for_doc(index, doc_id, routing)
        self.transport.send(
            self.node_id, primary.node_id, "indices:data/read/get",
            {"index": index, "shard": shard_num, "id": doc_id},
            on_response=callback,
            on_failure=lambda e: callback({"error": str(e)}),
        )

    def _on_get(self, sender: str, payload: dict):
        def run() -> dict:
            shard = self._local_shard(payload["index"], payload["shard"])
            got = shard.get(payload["id"])
            if got is None:
                return {"_index": payload["index"], "_id": payload["id"],
                        "found": False}
            return {"_index": payload["index"], "_id": payload["id"],
                    "found": True, "_source": got["_source"],
                    "_seq_no": got["_seq_no"], "_version": got["_version"]}

        return self._offload(run)

    def refresh(self, index: str, callback: Callable[[dict], None]) -> None:
        """Broadcast refresh to every shard copy (BroadcastReplicationAction)."""
        state = self.applied_state
        targets = [
            r for r in state.shards_for_index(index)
            if r.node_id is not None and r.state in ("STARTED", "RELOCATING")
        ]
        if not targets:
            callback({"_shards": {"total": 0, "successful": 0, "failed": 0}})
            return
        remaining = [len(targets)]

        def one_done(_resp: Any) -> None:
            remaining[0] -= 1
            if remaining[0] == 0:
                callback({"_shards": {"total": len(targets),
                                      "successful": len(targets), "failed": 0}})

        for r in targets:
            self.transport.send(
                self.node_id, r.node_id, "indices:admin/refresh[shard]",
                {"index": index, "shard": r.shard},
                on_response=one_done, on_failure=one_done,
            )

    def _on_shard_refresh(self, sender: str, payload: dict):
        shard = self._local_shard(payload["index"], payload["shard"])
        deferred = self._offload(lambda: (shard.refresh(), {"ack": True})[1])
        if shard.primary and shard.replication == "SEGMENT":
            from opensearch_tpu.transport.base import DeferredResponse

            if isinstance(deferred, DeferredResponse):
                deferred.on_done(lambda d: (
                    self._publish_checkpoint(payload["index"], payload["shard"])
                    if d.error is None else None
                ))
            else:
                self._publish_checkpoint(payload["index"], payload["shard"])
        return deferred

    # -- segment replication (indices/replication/ analog) ------------------

    def _publish_checkpoint(self, index: str, shard_num: int) -> None:
        """Primary: after refresh, tell every replica copy which segments
        now exist (checkpoint/PublishCheckpointAction)."""
        shard = self.local_shards.get((index, shard_num))
        if shard is None:
            return
        checkpoint = {
            "index": index, "shard": shard_num,
            "segments": shard.engine.segment_names(),
            "sigs": shard.engine.segment_sigs(),
            "generation": shard.engine._refresh_generation,
            "max_seq_no": shard.engine.max_seq_no,
            "primary": self.node_id,
        }
        state = self.applied_state
        for r in state.shards_for_index(index):
            if (r.shard == shard_num and not r.primary
                    and r.node_id not in (None, self.node_id)
                    and r.state in ("STARTED", "RELOCATING")):
                self.transport.send(
                    self.node_id, r.node_id,
                    "indices:replication/checkpoint", checkpoint,
                    on_response=None, on_failure=lambda e: None,
                )

    def _on_replication_checkpoint(self, sender: str, payload: dict) -> dict:
        """Replica: diff the checkpoint against local segments, fetch the
        missing ones (SegmentReplicationTargetService.onNewCheckpoint:298)."""
        shard = self.local_shards.get((payload["index"], payload["shard"]))
        if shard is None or shard.primary:
            return {"ack": False}
        have = shard.engine.segment_sigs()
        want = list(payload["segments"])
        want_sigs = payload.get("sigs") or {}
        # a same-name segment with a different signature is stale (e.g. a
        # crash-restarted replica's locally rebuilt bootstrap segment)
        missing = [n for n in want
                   if have.get(n) != want_sigs.get(n)]
        if not missing and set(want) == set(have):
            return {"ack": True, "fetched": 0}
        self._fetch_and_install(
            payload["index"], payload["shard"], payload["primary"],
            want, missing, done=None,
        )
        return {"ack": True, "fetched": len(missing)}

    def _fetch_and_install(self, index: str, shard_num: int,
                           primary_id: str, order: list[str],
                           names: list[str], done) -> None:
        """Fetch the named segments from the primary ONE per request (the
        MultiChunkTransfer idea at segment granularity — a whole-shard
        bundle could exceed the transport's frame cap), then install the
        set on the data worker. `done(ok: bool)` fires on the loop."""
        blobs: list[bytes] = []

        def finish_install() -> None:
            def run() -> bool:
                from opensearch_tpu.index.segment import unpack_segment

                hosts = [unpack_segment(b) for b in blobs]
                shard = self.local_shards.get((index, shard_num))
                if shard is None:
                    return False
                shard.engine.install_replicated_segments(hosts, order)
                return True

            deferred = self._offload(run)
            from opensearch_tpu.transport.base import DeferredResponse

            if done is None:
                return
            if isinstance(deferred, DeferredResponse):
                deferred.on_done(lambda d: done(
                    d.error is None and bool(d.result)
                ))
            else:
                done(bool(deferred))

        def fetch(i: int) -> None:
            if i >= len(names):
                finish_install()
                return
            self.transport.send(
                self.node_id, primary_id,
                "indices:replication/get_segments",
                {"index": index, "shard": shard_num, "names": [names[i]]},
                on_response=lambda resp: (
                    blobs.append(resp["_binary"]), fetch(i + 1)
                ) if isinstance(resp, dict) and resp.get("_binary")
                else (done(False) if done else None),
                on_failure=lambda e: done(False) if done else None,
                # large bundles take longer than control messages
                # (RecoverySettings' dedicated recovery timeouts)
                timeout_ms=180_000,
            )

        fetch(0)

    def _on_get_segments(self, sender: str, payload: dict):
        """Primary: serve sealed segment bundles as binary blobs
        (RecoverySourceHandler phase1's file chunks over binary frames;
        callers request one segment per round to stay under MAX_FRAME)."""
        shard = self._local_shard(payload["index"], payload["shard"])

        def run() -> dict:
            from opensearch_tpu.index.segment import pack_segment

            names = set(payload["names"])
            blobs: list[tuple[str, bytes]] = []
            for host, _dev in shard.engine._segments:
                if host.name in names:
                    blobs.append((host.name, pack_segment(host)))
            manifest = [[n, len(b)] for n, b in blobs]
            return {"manifest": manifest,
                    "segments": shard.engine.segment_names(),
                    "_binary": b"".join(b for _n, b in blobs)}

        return self._offload(run)

    # -- distributed search (scatter-gather, SURVEY §3.2) -------------------

    def search(self, index: str, body: dict | None,
               callback: Callable[[dict], None],
               query_group: str | None = None,
               lane: str | None = None) -> None:
        # wlm search admission BEFORE the fan-out (the bulk twin): an
        # enforced group past its slot share sheds a typed 429 here and
        # burns no transport or device work; the slot releases exactly
        # once when the (possibly degraded) response completes
        try:
            release_admission = self.query_groups.admit_search(query_group)
        except RejectedExecutionException as e:
            callback({"error": f"{type(e).__name__}: {e}", "status": 429})
            return
        inner_callback = callback

        def callback(resp: dict) -> None:  # noqa: F811 - admission wrapper
            release_admission()
            inner_callback(resp)

        state = self.applied_state
        meta = state.indices.get(index)
        if meta is None:
            callback({"error": f"no such index [{index}]"})
            return
        body = dict(body or {})
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        sort = body.get("sort")
        if isinstance(sort, (str, dict)):
            # normalize once and forward the normalized form — shards and
            # coordinator must agree on the sort spec
            sort = [sort]
            body["sort"] = sort
        # candidate copies per shard (every STARTED/RELOCATING copy)
        candidates: dict[int, list[ShardRoutingEntry]] = {}
        for r in state.shards_for_index(index):
            # RELOCATING sources keep serving reads until the routing swap
            if r.state not in ("STARTED", "RELOCATING") or r.node_id is None:
                continue
            candidates.setdefault(r.shard, []).append(r)
        missing = meta.num_shards - len(candidates)
        if not candidates:
            callback({"error": "not all shards available"})
            return
        # device-kNN bodies route through the shard-mesh data plane: ONE
        # search[node] RPC per node holding target shards — the node runs
        # a single sharded launch over all of them (cluster/shard_mesh.py)
        # — instead of one RPC per shard with a host-Python merge; the
        # coordinator stream-merges the pre-merged node partials
        # (search/reduce.py). Ineligible bodies keep the per-shard path.
        # RESIDENCY-AWARE ROUTING (ISSUE 11): for the kNN path, each
        # shard's launch lands on the copy whose mesh bundle / IVF-PQ slab
        # is already HBM-resident (the board learned it from earlier
        # partials' _residency stamps); no warm copy -> round-robin.
        if self._mesh_search_eligible(body):
            field = residency_mod.knn_query_field(body)
            targets, _warm = residency_mod.choose_copies(
                self.residency_board, index, field, candidates,
                next(self._route_rr))
            self._search_node_grouped(
                index, body, targets, missing, size, from_, callback,
                lane=lane, field=field,
            )
            return
        # non-mesh bodies keep the legacy prefer-primary selection
        targets: dict[int, ShardRoutingEntry] = {}
        for num, cands in candidates.items():
            targets[num] = next((r for r in cands if r.primary), cands[0])
        # shards with no serving copy (mid-failover) degrade the response
        # instead of refusing it: the reachable shards answer and the
        # missing ones count into _shards.failed
        # (allow_partial_search_results=true semantics)
        results: dict[int, dict] = {}
        remaining = [len(targets)]
        tracer = self.telemetry.tracer
        # coordinator ROOT span covers the whole distributed operation —
        # begin_span/end_span because responses arrive in later scheduled
        # callbacks where the lexical scope is long gone (same recipe as
        # the recovery.target root)
        root = tracer.begin_span(
            "search.coordinator",
            {"index": index, "node": self.node_id, "shards": len(targets)},
        )
        ctx = {"trace_id": root.trace_id, "span_id": root.span_id}

        def one_result(shard_num: int):
            def handle(resp: dict) -> None:
                results[shard_num] = resp
                remaining[0] -= 1
                if remaining[0] == 0:
                    # re-enter the trace so coordinator -> shard -> reduce
                    # share one trace_id
                    try:
                        with tracing.restore_trace_context(ctx), \
                                tracer.start_span("search.reduce", {
                                    "index": index, "node": self.node_id,
                                    "shards": len(results)}):
                            merged = self._merge_search_results(
                                results, size, from_, sort,
                                extra_failed=missing)
                    except Exception as e:  # noqa: BLE001
                        # a reduce failure runs inside a transport
                        # completion callback — raising here leaks the
                        # listener and wedges the search forever (TPU008's
                        # failure class); fail it instead
                        merged = {"error": f"{type(e).__name__}: {e}"}
                    tracer.end_span(root)
                    callback(merged)
            return handle

        # the fan-out sends capture the root context, so the per-shard
        # handler spans on remote nodes parent under it
        with tracing.restore_trace_context(ctx):
            for shard_num, r in sorted(targets.items()):
                self.transport.send(
                    self.node_id, r.node_id, "indices:data/read/search[shard]",
                    {"index": index, "shard": shard_num, "body": body},
                    on_response=one_result(shard_num),
                    on_failure=one_result(shard_num),  # missing shard
                )

    # -- shard-mesh search fan-out (one sharded launch per node) ------------

    # body keys the node-grouped device-kNN path accepts: a bare knn query
    # plus paging/_source/profile — everything else (sort, aggs, rescore,
    # highlight, ...) keeps the per-shard scatter-gather
    _MESH_SEARCH_KEYS = frozenset({
        "query", "size", "from", "_source", "track_total_hits",
        "version", "seq_no_primary_term", "profile",
    })

    @classmethod
    def _mesh_search_eligible(cls, body: dict) -> bool:
        if not isinstance(body, dict) or set(body) - cls._MESH_SEARCH_KEYS:
            return False
        query = body.get("query")
        return isinstance(query, dict) and set(query) == {"knn"}

    def _search_node_grouped(self, index: str, body: dict, targets: dict,
                             missing: int, size: int, from_: int,
                             callback: Callable[[dict], None],
                             lane: str | None = None,
                             field: str | None = None) -> None:
        """Device-kNN fan-out grouped BY NODE: each data node receives one
        search[node] request covering every target shard it holds, executes
        them as one shard_map launch (service.search -> shard-mesh path),
        and the coordinator reduces the pre-merged partials. A node RPC
        failure — or a shard copy missing on the node — degrades that
        node's shards to per-shard search[shard] execution against another
        serving copy (allow_partial_search_results semantics when none
        exists)."""
        from opensearch_tpu.search.reduce import reduce_search_responses

        by_node: dict[str, list[int]] = {}
        for num, r in sorted(targets.items()):
            by_node.setdefault(r.node_id, []).append(num)
        track_total = body.get("track_total_hits", True)
        node_body = dict(body)
        node_body["from"] = 0
        node_body["size"] = from_ + size
        node_body["track_total_hits"] = True
        tracer = self.telemetry.tracer
        # coordinator ROOT span: begin/end because partials arrive in later
        # scheduled callbacks (same recipe as the per-shard coordinator)
        root = tracer.begin_span(
            "search.coordinator",
            {"index": index, "node": self.node_id, "mesh": True,
             "fanout": len(by_node), "shards": len(targets)},
        )
        ctx = {"trace_id": root.trace_id, "span_id": root.span_id}
        partials: list[dict] = []
        extra_failed = [missing]
        pending = [len(by_node)]

        def finish() -> None:
            try:
                with tracing.restore_trace_context(ctx), \
                        tracer.start_span("search.reduce", {
                            "index": index, "node": self.node_id,
                            "partials": len(partials)}):
                    resp = reduce_search_responses(
                        body, partials, size=size, from_=from_,
                        track_total=track_total,
                    )
                resp["_shards"]["total"] += extra_failed[0]
                resp["_shards"]["failed"] += extra_failed[0]
            except Exception as e:  # noqa: BLE001 - a reduce failure inside
                # a transport completion callback must FAIL the search, not
                # leak the caller (TPU008's failure class)
                resp = {"error": f"{type(e).__name__}: {e}"}
            tracer.end_span(root)
            callback(resp)

        def one_node_done() -> None:
            pending[0] -= 1
            if pending[0] == 0:
                finish()

        def make_handlers(nid: str, nums: list[int]):
            def handle(resp: Any) -> None:
                if not isinstance(resp, dict) or "hits" not in resp:
                    # whole-node failure: every shard degrades to the
                    # per-shard path on another copy
                    self._per_shard_fallback(
                        index, node_body, nums, nid, partials,
                        extra_failed, one_node_done)
                    return
                # residency stamp: the data node consulted its ledger/
                # registry rows after serving — the board learns which
                # copies are warm so the NEXT fan-out lands on them
                res = resp.pop("_residency", None)
                if isinstance(res, dict) and res.get("field"):
                    self.residency_board.observe(
                        nid, index, res["field"], bool(res.get("warm")))
                failed_nums = resp.pop("_failed_shards", None)
                if failed_nums:
                    # hand the missing copies to the fallback instead of
                    # double-counting them (the partial already bumped its
                    # _shards for them)
                    resp["_shards"]["total"] -= len(failed_nums)
                    resp["_shards"]["failed"] -= len(failed_nums)
                partials.append(resp)
                if failed_nums:
                    self._per_shard_fallback(
                        index, node_body, failed_nums, nid, partials,
                        extra_failed, one_node_done)
                else:
                    one_node_done()

            def fail(_e: Exception) -> None:
                self._per_shard_fallback(
                    index, node_body, nums, nid, partials,
                    extra_failed, one_node_done)

            return handle, fail

        with tracing.restore_trace_context(ctx):
            for nid, nums in sorted(by_node.items()):
                handle, fail = make_handlers(nid, nums)
                payload = {"index": index, "shards": nums,
                           "body": node_body}
                if lane is not None:
                    payload["lane"] = lane
                self.transport.send(
                    self.node_id, nid, "indices:data/read/search[node]",
                    payload,
                    on_response=handle, on_failure=fail,
                )

    def _per_shard_fallback(self, index: str, node_body: dict,
                            nums: list[int], failed_node: str,
                            partials: list[dict], extra_failed: list[int],
                            done: Callable[[], None]) -> None:
        """Mesh-path degrade: re-execute `nums` through per-shard
        search[shard] against another serving copy (the failed node is
        excluded); shards with no other copy count into _shards.failed."""
        state = self.applied_state
        remaining = [len(nums)]

        def one_done() -> None:
            remaining[0] -= 1
            if remaining[0] == 0:
                done()

        def make_shard_handlers(num: int):
            def handle(resp: Any) -> None:
                if isinstance(resp, dict) and "hits" in resp:
                    partials.append(self._shard_resp_as_partial(num, resp))
                else:
                    extra_failed[0] += 1
                one_done()

            def fail(_e: Exception) -> None:
                extra_failed[0] += 1
                one_done()

            return handle, fail

        for num in nums:
            alt = next(
                (r for r in state.shards_for_index(index)
                 if r.shard == num and r.node_id not in (None, failed_node)
                 and r.state in ("STARTED", "RELOCATING")), None)
            if alt is None:
                extra_failed[0] += 1
                one_done()
                continue
            handle, fail = make_shard_handlers(num)
            self.transport.send(
                self.node_id, alt.node_id, "indices:data/read/search[shard]",
                {"index": index, "shard": num, "body": node_body},
                on_response=handle, on_failure=fail,
            )

    @staticmethod
    def _shard_resp_as_partial(shard_num: int, resp: dict) -> dict:
        """Wrap a per-shard search[shard] response as a reduce-compatible
        partial. `_tb` = [shard, 0, rank] preserves the merge order exactly:
        within one shard, rank order IS (segment, doc) order for equal
        scores, and cross-shard ties compare on the shard number first."""
        hits = []
        for i, h in enumerate(resp.get("hits") or []):
            h = dict(h)
            h["_tb"] = [shard_num, 0, i]
            hits.append(h)
        return {
            "took": 0, "timed_out": False,
            "_shards": {"total": 1, "successful": 1, "skipped": 0,
                        "failed": 0},
            "hits": {"total": {"value": resp.get("total", 0),
                               "relation": "eq"},
                     "max_score": resp.get("max_score"),
                     "hits": hits},
        }

    # -- per-node search partials (the QuerySearchResult wire analog) -------

    # bounded search pool: enough parallelism for the dispatch batcher to
    # see concurrent requests, small enough that one node cannot starve
    # the host (the reference's fixed `search` threadpool sizing)
    _SEARCH_POOL_WORKERS = 4

    def _offload(self, fn):
        """Run `fn` on the serial data worker thread (engine single-writer
        discipline), resolving a DeferredResponse on the transport loop.
        Falls back to synchronous execution under the deterministic sim
        (no loop, no threads)."""
        loop = getattr(self.scheduler, "loop", None)
        if loop is None:
            delay = self.data_worker_delay_ms
            if delay <= 0:
                return fn()
            # slow-data-worker fault injection: the job runs after a
            # virtual-time stall, resolving the same DeferredResponse the
            # threaded path uses (every consumer isinstance-checks it)
            from opensearch_tpu.transport.base import DeferredResponse

            deferred = DeferredResponse()

            def run() -> None:
                try:
                    result = fn()
                except Exception as e:  # noqa: BLE001 - travels back as error
                    deferred.set_exception(e)
                else:
                    deferred.set_result(result)

            self.scheduler.schedule(delay, run)
            return deferred
        from concurrent.futures import ThreadPoolExecutor

        if self._data_executor is None:
            self._data_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"{self.node_id}-data"
            )
        return self._submit_deferred(loop, self._data_executor, fn)

    # background lane pool: half the interactive width (min 1) — enough to
    # keep msearch/bulk-adjacent fan-outs flowing, small enough that a
    # flood of them leaves the interactive workers untouched
    _BG_POOL_WORKERS = 2

    def _offload_search(self, fn, lane: str | None = None):
        """Run read-only query work on the BOUNDED PARALLEL search pool:
        executions touch only immutable acquired snapshots, so concurrent
        search[node] requests proceed side by side — which is what lets the
        kNN dispatch batcher coalesce them into one shard-mesh launch (and
        what parallelizes the non-mesh per-shard fallback path).

        `lane` (search/lanes.py) picks the pool: background-lane work runs
        a separate, smaller executor so a background flood can saturate
        only its own workers — an interactive search[node] always finds an
        interactive slot. Lanes disabled -> everything shares the
        interactive pool (the pre-lane behavior)."""
        from opensearch_tpu.search import lanes as lanes_mod

        lane = lane or lanes_mod.INTERACTIVE
        loop = getattr(self.scheduler, "loop", None)
        if loop is None:
            # deterministic sim: synchronous, but the lane scope still
            # rides into the batcher and the tracker still counts
            self.lane_tracker.try_submit(lane)
            try:
                with lanes_mod.lane_scope(lane):
                    return fn()
            finally:
                self.lane_tracker.complete(lane)
        from concurrent.futures import ThreadPoolExecutor

        background = (lanes_mod.default_config.enabled
                      and lane == lanes_mod.BACKGROUND)
        if background:
            if self._bg_search_executor is None:
                self._bg_search_executor = ThreadPoolExecutor(
                    max_workers=self._BG_POOL_WORKERS,
                    thread_name_prefix=f"{self.node_id}-search-bg",
                )
            executor = self._bg_search_executor
        else:
            if self._search_executor is None:
                self._search_executor = ThreadPoolExecutor(
                    max_workers=self._SEARCH_POOL_WORKERS,
                    thread_name_prefix=f"{self.node_id}-search",
                )
            executor = self._search_executor
        self.lane_tracker.try_submit(lane)
        lanes_mod.record_lane_metrics(
            self.telemetry.metrics, lane, self.lane_tracker.depth(lane))

        def tracked():
            try:
                with lanes_mod.lane_scope(lane):
                    return fn()
            finally:
                self.lane_tracker.complete(lane)

        return self._submit_deferred(loop, executor, tracked)

    @staticmethod
    def _submit_deferred(loop, executor, fn):
        from opensearch_tpu.transport.base import DeferredResponse

        deferred = DeferredResponse()
        # carry the contextvars context (restored trace context, active
        # tracer) onto the worker thread so spans opened by offloaded work
        # stitch into the caller's trace (same recipe as rest/http.py)
        import contextvars as _cv

        ctx = _cv.copy_context()

        def run() -> None:
            try:
                result = ctx.run(fn)
            except Exception as e:  # noqa: BLE001 - travels back as error
                loop.call_soon_threadsafe(deferred.set_exception, e)
            else:
                loop.call_soon_threadsafe(deferred.set_result, result)

        executor.submit(run)
        return deferred

    def _on_node_search(self, sender: str, payload: dict):
        """Execute the FULL per-shard search service over this node's local
        shards of one index, returning a wire partial
        (search/service.search(partial=True)). Optionally pins the
        snapshots in a reader context for scroll/PIT.

        A requested shard whose local copy is MISSING (stale routing: the
        copy moved/failed while the coordinator's fan-out was in flight)
        degrades the partial instead of failing the whole node: the present
        shards answer (mesh launch or per-shard fallback over the present
        subset) and the missing ones ride back in `_failed_shards` /
        `_shards.failed` — allow_partial_search_results semantics at the
        node level. A scroll-pinning request still needs every shard, so
        `keep_context` keeps the strict behavior."""
        index = payload["index"]
        nums = list(payload["shards"])
        body = payload.get("body") or {}
        lane = payload.get("lane")
        keep = bool(payload.get("keep_context"))
        keep_alive_ms = int(payload.get("keep_alive_ms") or 60_000)
        self._reap_reader_contexts()

        shards, present, missing = [], [], []
        for n in nums:
            local = self.local_shards.get((index, n))
            if local is None and not keep:
                missing.append(n)
                continue
            shards.append(self._local_shard(index, n))
            present.append(n)
        if not shards:
            raise ShardNotFoundException(
                f"no copy of [{index}]{nums} on node {self.node_id}"
            )
        snaps = [s.acquire_searcher() for s in shards]

        def run() -> dict:
            from opensearch_tpu.search import service as search_service

            with tracing.activate(self.telemetry.tracer), \
                    self.telemetry.tracer.start_span("search.node_partial", {
                        "index": index, "node": self.node_id,
                        "shards": len(present)}):
                resp = search_service.search(
                    shards, body, acquired=snaps, partial=True,
                    shard_numbers=present,
                )
            # residency stamp for the coordinator's replica router: after
            # serving, consult THIS node's registry/ledger rows — a kNN
            # body leaves the mesh bundle (or finds the IVF-PQ slab)
            # HBM-resident, so the stamp teaches the board this copy is
            # the warm one for the next fan-out. The kill switch disables
            # the bookkeeping too: routing off must cost nothing on the
            # hot path (no warm_for scan, no extra wire bytes).
            if residency_mod.default_config.enabled:
                field = residency_mod.knn_query_field(body)
                if field is not None:
                    resp["_residency"] = self._residency_stamp(
                        index, field, shards, snaps)
            if missing:
                resp["_shards"]["total"] += len(missing)
                resp["_shards"]["failed"] += len(missing)
                resp["_failed_shards"] = missing
            if keep:
                # register only on success — a failed first search must not
                # leak a context whose id never reaches the coordinator
                ctx_id = f"{self.node_id}#{next(self._ctx_counter)}"
                self._reader_contexts[ctx_id] = {
                    "index": index, "nums": present, "shards": shards,
                    "snaps": snaps, "body": body,
                    "keep_alive_ms": keep_alive_ms,
                    "expires_at": self._now_ms() + keep_alive_ms,
                }
                resp["_ctx_id"] = ctx_id
            return resp

        return self._offload_search(run, lane=lane)

    def _residency_advertisement(self) -> list[tuple]:
        """This node's warm (index, field) set: mesh bundles keyed to OUR
        engines (in-process sims share the registry, so the engine filter
        keeps another node's bundles out), plus published IVF-PQ
        structures (their slabs are device-resident from publish to
        retirement) — the same two signals as _residency_stamp, for the
        whole node instead of one query's shards."""
        engines = {
            sh.engine.instance_id for sh in self.local_shards.values()
        }
        pairs = set(self.shard_mesh.warm_pairs(engines))
        for (index, _num), shard in list(self.local_shards.items()):
            for _host, dev in list(shard.engine._segments):
                for fname, vf in dev.vector_fields.items():
                    if vf.ann is not None:
                        pairs.add((index, fname))
        return sorted(pairs)

    def _observe_residency(self, node_id: str, resp: Any) -> None:
        """Feed a stats answer's piggybacked warm set into the board.
        The advertisement is the node's WHOLE warm set, so a pair that
        dropped out since the last answer (its bundle evicted under
        budget pressure) is observed COLD — advertise-only learning
        would latch stale warmth and route launches onto a copy that
        must rebuild the slab."""
        pairs = resp.get("residency") if isinstance(resp, dict) else None
        if pairs is None:
            return
        warm = {
            (pair[0], pair[1]) for pair in pairs
            if isinstance(pair, (list, tuple)) and len(pair) == 2
        }
        with self._advertised_lock:
            gone = self._advertised_residency.get(node_id, set()) - warm
            self._advertised_residency[node_id] = warm
        for index, field in sorted(gone):
            self.residency_board.observe(node_id, index, field, False)
        for index, field in sorted(warm):
            self.residency_board.observe(node_id, index, field, True)

    def _maybe_seed_residency_board(self) -> None:
        """Cold-start seeding (ISSUE 15): at the first state application
        that shows other data nodes, fan ONE light stats RPC per node and
        learn their advertised warm sets — a coordinator that just joined
        a warm cluster routes its first kNN fan-out onto the copies that
        already hold the mesh bundles instead of round-robining a
        duplicate build. Best-effort: failures are ignored (the stamped
        partials keep teaching the board as before)."""
        if self._residency_seeded or not residency_mod.default_config.enabled:
            return
        others = [nid for nid in sorted(self.applied_state.nodes)
                  if nid != self.node_id]
        if not others:
            return
        self._residency_seeded = True
        for nid in others:
            self.transport.send(
                self.node_id, nid, "indices:monitor/stats[node]", {},
                on_response=(
                    lambda r, nid=nid: self._observe_residency(nid, r)),
                on_failure=lambda e: None,
            )

    def _residency_stamp(self, index: str, field: str, shards: list,
                         snaps: list) -> dict:
        """This node's residency truth for (index, field): a mesh bundle
        keyed to these shards' engines resident in the registry, or a
        published IVF-PQ structure (its slab is device-resident from
        publish to retirement)."""
        engines = {sh.engine.instance_id for sh in shards}
        mesh_warm = self.shard_mesh.warm_for(index, field, engines)
        ann_warm = any(
            (vf := dev.vector_fields.get(field)) is not None
            and vf.ann is not None
            for snap in snaps for _host, dev in snap.segments
        )
        # both signals ARE ledger-backed residency: a registry bundle
        # holds its ledger allocation until eviction frees it, and a
        # published ANN structure's slab is registered at build and freed
        # at segment retirement — so no per-query scan of the ledger's
        # full live-allocation table is needed (it grows with every
        # resident column and this runs on the hot serving path)
        return {"field": field, "warm": bool(mesh_warm or ann_warm)}

    def _on_node_msearch(self, sender: str, payload: dict):
        """Execute several search bodies over this node's local shards of
        one index, returning one wire partial per body. Bodies that are all
        bare knn queries run their query phase as ONE batched device
        dispatch (search_service.try_batched_knn_msearch); otherwise each
        body runs exactly like search[node]. msearch fan-outs are
        BACKGROUND-lane work unless the coordinator says otherwise."""
        from opensearch_tpu.search import lanes as lanes_mod

        index = payload["index"]
        nums = list(payload["shards"])
        bodies = list(payload.get("bodies") or [])
        lane = payload.get("lane") or lanes_mod.BACKGROUND

        shards = [self._local_shard(index, n) for n in nums]
        snaps = [s.acquire_searcher() for s in shards]

        def run() -> dict:
            from opensearch_tpu.search import service as search_service

            batched = search_service.try_batched_knn_msearch(
                shards, bodies, snaps
            )
            out = []
            for bi, body in enumerate(bodies):
                try:
                    out.append(search_service.search(
                        shards, body, acquired=snaps, partial=True,
                        shard_numbers=nums,
                        precomputed_results=(
                            batched[bi] if batched is not None else None
                        ),
                    ))
                except Exception as e:  # noqa: BLE001 - per-body error slot
                    out.append({"error": f"{type(e).__name__}: {e}"})
            return {"responses": out}

        return self._offload_search(run, lane=lane)

    def _now_ms(self) -> int:
        # injectable clock: the deterministic sim controls context expiry.
        # clock_skew_ms shifts only THIS node's reads (the fault-injection
        # hook: the sim's clock is process-global, so per-node skew lives
        # here) — expiry decisions degrade gracefully, never wedge
        from opensearch_tpu.common.timeutil import monotonic_millis

        return monotonic_millis() + self.clock_skew_ms

    def _reap_reader_contexts(self) -> None:
        now = self._now_ms()
        # snapshot first: registration happens on the search pool while
        # this runs on the transport loop — iterating the live dict could
        # see a concurrent insert mid-walk
        for cid, x in list(self._reader_contexts.items()):
            if x["expires_at"] < now:
                self._reader_contexts.pop(cid, None)

    def _on_ctx_search(self, sender: str, payload: dict):
        """Search against a pinned reader context (scroll page / PIT
        search). `body` overrides the stored one (PIT); from/size override
        paging (scroll deepening)."""
        self._reap_reader_contexts()
        ctx = self._reader_contexts.get(payload["ctx_id"])
        if ctx is None:
            from opensearch_tpu.common.errors import (
                SearchContextMissingException,
            )

            raise SearchContextMissingException(
                f"no search context [{payload['ctx_id']}]"
            )
        ctx["expires_at"] = self._now_ms() + ctx["keep_alive_ms"]
        if payload.get("body") is not None:
            body = dict(payload["body"])  # PIT: fresh body, aggs included
        else:
            # scroll page: stored body minus aggs (computed on page 1 only)
            body = dict(ctx["body"] or {})
            body.pop("aggs", None)
            body.pop("aggregations", None)
        if "from" in payload:
            body["from"] = int(payload["from"])
        if "size" in payload:
            body["size"] = int(payload["size"])
        shards, snaps, nums = ctx["shards"], ctx["snaps"], ctx["nums"]

        def run() -> dict:
            from opensearch_tpu.search import service as search_service

            with tracing.activate(self.telemetry.tracer), \
                    self.telemetry.tracer.start_span("search.node_partial", {
                        "index": ctx["index"], "node": self.node_id,
                        "shards": len(nums), "pinned": True}):
                return search_service.search(
                    shards, body, acquired=snaps, partial=True,
                    shard_numbers=nums,
                )

        return self._offload_search(run)

    def _on_ctx_close(self, sender: str, payload: dict) -> dict:
        freed = 0
        for cid in payload.get("ctx_ids", []):
            if self._reader_contexts.pop(cid, None) is not None:
                freed += 1
        return {"freed": freed}

    def _on_node_flush(self, sender: str, payload: dict):
        names = payload.get("indices")  # resolved list from the coordinator

        def run() -> dict:
            flushed = 0
            for (index, num), shard in list(self.local_shards.items()):
                if names is None or index in names:
                    shard.flush()
                    flushed += 1
            return {"ack": True, "flushed": flushed}

        return self._offload(run)

    def _on_node_forcemerge(self, sender: str, payload: dict):
        names = payload.get("indices")

        def run() -> dict:
            merged = []
            for (index, num), shard in list(self.local_shards.items()):
                if names is not None and index not in names:
                    continue
                if shard.replication == "SEGMENT" and not shard.primary:
                    # segrep replicas never merge locally — the primary's
                    # merged segment arrives via the next checkpoint
                    continue
                shard.engine.force_merge(
                    max_num_segments=int(payload.get("max_num_segments", 1)),
                )
                if shard.primary and shard.replication == "SEGMENT":
                    merged.append((index, num))
            return {"ack": True, "_publish": merged}

        deferred = self._offload(run)
        from opensearch_tpu.transport.base import DeferredResponse

        def publish_after(d):
            if d.error is None and isinstance(d.result, dict):
                for index, num in d.result.get("_publish", []):
                    self._publish_checkpoint(index, num)

        if isinstance(deferred, DeferredResponse):
            deferred.on_done(publish_after)
        return deferred

    def _on_node_stats(self, sender: str, payload: dict) -> dict:
        out = {}
        for (index, num), shard in self.local_shards.items():
            out[f"{index}#{num}"] = {
                "index": index, "shard": num,
                "primary": bool(shard.primary),
                "docs": shard.num_docs,
            }
        resp: dict[str, Any] = {
            "shards": out,
            "shard_mesh": self.shard_mesh.snapshot_stats(),
        }
        # cross-node residency advertisement (ISSUE 15): this node's warm
        # (index, field) set piggybacks on EVERY stats answer — light and
        # full — so any coordinator that talks stats to us learns which
        # copies are warm without waiting for a stamped kNN partial. The
        # kill switch drops it (routing off must cost nothing).
        if residency_mod.default_config.enabled:
            resp["residency"] = [
                list(p) for p in self._residency_advertisement()
            ]
        if payload.get("full"):
            # the cluster-wide _nodes/stats fan-out: this node's whole
            # telemetry surface rides back to the coordinator — metrics
            # with exemplars, the spans-ring tail, exporter accounting,
            # batcher stats and any coordinator-registered extras (the
            # facade's request cache). The light form (no flag) stays cheap
            # for index_stats' per-shard doc counts. An optional "sections"
            # list narrows the payload: a recurring Prometheus scrape asks
            # for ["metrics"] alone instead of shipping ~100 serialized
            # spans per node over the transport every 15 seconds.
            sections = payload.get("sections")

            def want(section: str) -> bool:
                return sections is None or section in sections

            telemetry: dict[str, Any] = dict(self.telemetry.metrics.stats())
            if want("spans"):
                telemetry["spans"] = [
                    s.to_dict()
                    for s in self.telemetry.tracer.finished_spans()[-100:]
                ]
                exporter = self.telemetry.tracer.exporter
                if exporter is not None:
                    telemetry["exporter"] = exporter.snapshot_stats()
            resp["name"] = self.node_id
            resp["telemetry"] = telemetry
            if want("knn_batch"):
                resp["knn_batch"] = self.knn_batcher.snapshot_stats()
            if want("device"):
                # device-memory residency (telemetry/device_ledger.py):
                # per-structure HBM bytes, the accounting identity, and the
                # per-kernel-family compile table. Process-wide — in-process
                # sim nodes report the shared ledger, like the batcher.
                from opensearch_tpu.telemetry import device_ledger

                resp["device"] = device_ledger.stats_section()
            if want("device_totals"):
                # lightweight per-device byte totals for the recurring
                # federated Prometheus scrape (the full structure rows stay
                # off that path, like the span-ring narrowing)
                from opensearch_tpu.telemetry.device_ledger import (
                    default_ledger as _ledger,
                )

                resp["device_totals"] = _ledger.device_totals()
            if want("tail"):
                resp["tail"] = self.tail_stats()
            if want("roofline"):
                # kernel roofline accounting (telemetry/roofline.py):
                # per-family achieved FLOP/s + roofline fractions against
                # the calibrated peaks. Process-wide — in-process sim
                # nodes report the shared recorder, like the ledger.
                from opensearch_tpu.telemetry import roofline

                resp["roofline"] = roofline.stats_section()
            if want("heat"):
                # structure access heat (telemetry/device_ledger.py touch
                # accounting): per-structure touch/recency/class rows the
                # tiering advisor replays. Process-wide, like the ledger.
                from opensearch_tpu.telemetry import device_ledger

                resp["heat"] = device_ledger.heat_section()
            if want("providers"):
                for name, provider in list(self.stats_providers.items()):
                    try:
                        resp[name] = provider()
                    except Exception as e:  # noqa: BLE001 - never fail stats
                        import logging

                        logging.getLogger(__name__).warning(
                            "stats provider [%s] failed: %s", name, e)
        return resp

    def tail_stats(self) -> dict:
        """The `tail` stats section (ISSUE 11): lane queue depths + shed
        counts, residency-routing decisions, and wlm search-slot budgets —
        the whole tail-latency control plane in one read. `lanes` is the
        data-plane (search-pool) tracker; `http_lanes` — present when a
        REST facade is attached — is the HTTP boundary's, which is where
        the bounded background queue sheds 429s."""
        from opensearch_tpu.search import lanes as lanes_mod

        out = {
            "lanes": {
                "enabled": lanes_mod.default_config.enabled,
                "background_max_queue":
                    lanes_mod.default_config.background_max_queue,
                **self.lane_tracker.snapshot(),
            },
            "routing": self.residency_board.snapshot_stats(),
            "wlm_search": self.query_groups.search_slot_stats(),
        }
        http_tracker = getattr(self, "http_lane_tracker", None)
        if http_tracker is not None:
            out["http_lanes"] = http_tracker.snapshot()
        return out

    def _on_otel_flush(self, sender: str, payload: dict) -> dict:
        """`POST /_otel/flush` per-node leg: force the span exporter to
        decide + drain everything it holds, then report the exporter
        ledger and the device-residency snapshot — the admin's "show me
        the telemetry truth right now" button."""
        from opensearch_tpu.telemetry import device_ledger

        exporter = self.telemetry.tracer.exporter
        if exporter is not None:
            exporter.flush()
        return {
            "name": self.node_id,
            "flushed": exporter is not None,
            "exporter": (exporter.snapshot_stats()
                         if exporter is not None else None),
            "device": device_ledger.stats_section(),
        }

    def _on_shard_search(self, sender: str, payload: dict):
        def run() -> dict:
            # shard query-phase span: the transport restored the sender's
            # trace context, so this parents under the coordinator span
            with tracing.activate(self.telemetry.tracer), \
                    self.telemetry.tracer.start_span("search.shard_query", {
                        "index": payload["index"],
                        "shard": payload["shard"],
                        "node": self.node_id}):
                return self._shard_search_local(payload)

        return self._offload_search(run, lane=payload.get("lane"))

    def _shard_search_local(self, payload: dict) -> dict:
        """Per-shard query+fetch (the combined phase; split q/f is the
        optimization path). Returns hits with _id/_score/_source; with
        `"profile": true` a deep per-operator profile entry rides along
        (device kernel time, transfer bytes, retrace flag)."""
        from opensearch_tpu.search import profile as search_profile

        shard = self._local_shard(payload["index"], payload["shard"])
        body = payload.get("body") or {}
        node = query_dsl.parse_query(body.get("query"))
        size = int(body.get("size", 10)) + int(body.get("from", 0))
        sort = body.get("sort")
        if isinstance(sort, (str, dict)):
            sort = [sort]
        snapshot = shard.acquire_searcher()
        prof = (search_profile.ShardProfiler()
                if body.get("profile") else None)
        with search_profile.profiling(prof):
            result = execute_query_phase(
                snapshot, shard.mapper_service, node, size=size,
                sort=sort,
            )
        src_filter = _source_filter(body.get("_source", True))
        hits = []
        for h in result.hits:
            host = snapshot.segments[h.segment][0]
            hit = {"_id": host.doc_ids[h.doc], "_score": h.score,
                   "_index": payload["index"]}
            src = src_filter(json.loads(host.sources[h.doc]))
            if src is not None:
                hit["_source"] = src
            if h.sort_values:
                hit["sort"] = h.sort_values
            hits.append(hit)
        out = {"total": result.total, "hits": hits,
               "max_score": result.max_score}
        if prof is not None:
            out["profile"] = {
                "id": f"[{payload['index']}][{payload['shard']}]",
                "searches": [{
                    "query": prof.query_entries(),
                    "rewrite_time": prof.rewrite_ns,
                    "collector": [{
                        "name": "SimpleTopDocsCollector",
                        "reason": "search_top_hits",
                        "time_in_nanos": prof.collect_ns,
                    }],
                }],
                "tpu": prof.tpu_summary(),
                "aggregations": [],
            }
        return out

    def _merge_search_results(
        self, results: dict[int, dict], size: int,
        from_: int = 0, sort: list | None = None,
        extra_failed: int = 0,
    ) -> dict:
        total = 0
        max_score = None
        merged = []
        failed = 0
        profile_shards = []
        for shard_num in sorted(results):
            resp = results[shard_num]
            if not isinstance(resp, dict) or "hits" not in resp:
                failed += 1
                continue
            total += resp["total"]
            if resp["max_score"] is not None and (
                max_score is None or resp["max_score"] > max_score
            ):
                max_score = resp["max_score"]
            if "profile" in resp:
                profile_shards.append(resp["profile"])
            for h in resp["hits"]:
                merged.append((shard_num, h))
        if sort:
            # k-way merge on per-hit sort values (SearchPhaseController
            # mergeTopDocs for field sorts), shard index as tie-break
            from opensearch_tpu.search.service import _values_key

            merged.sort(
                key=lambda sh: (_values_key(sort, sh[1].get("sort", [])),
                                sh[0], sh[1]["_id"])
            )
        else:
            merged.sort(key=lambda sh: (-(sh[1]["_score"] or 0.0), sh[0], sh[1]["_id"]))
        out = {
            "took": 0,
            "timed_out": False,
            "_shards": {"total": len(results) + extra_failed,
                        "successful": len(results) - failed,
                        "skipped": 0, "failed": failed + extra_failed},
            "hits": {
                "total": {"value": total, "relation": "eq"},
                "max_score": max_score,
                "hits": [h for _, h in merged[from_: from_ + size]],
            },
        }
        if profile_shards:
            # per-shard profiles merge into the standard response shape
            # (each data node already built its shard entry)
            out["profile"] = {"shards": sorted(
                profile_shards, key=lambda s: s.get("id", ""))}
        return out

    def close(self) -> None:
        self._closed = True
        # flush-on-shutdown: pending trace fragments decide + drain before
        # the rest of the node tears down
        from opensearch_tpu.telemetry.export import close_exporter

        close_exporter(self.telemetry)
        timer = getattr(self, "_shard_tick_timer", None)
        if timer is not None:
            timer.cancel()
        for driver in self._recovery_drivers.values():
            driver.cancel()
        self._recovery_drivers.clear()
        self.coordinator.stop()
        if self._data_executor is not None:
            self._data_executor.shutdown(wait=False)
        if self._search_executor is not None:
            self._search_executor.shutdown(wait=False)
        if self._bg_search_executor is not None:
            self._bg_search_executor.shutdown(wait=False)
        self._reader_contexts.clear()
        for shard in self.local_shards.values():
            shard.close()
