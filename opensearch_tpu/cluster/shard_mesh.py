"""Shard-mesh registry: a node's device-resident shards as ONE sharded array.

The data-plane residency layer behind the single-launch-per-node kNN path
(ROADMAP item 1): every (index, field) whose shards live on this node is
flattened into one [S, n_flat, d] slab sharded over a `Mesh` data axis
(parallel/distributed.build_knn_serving_step), so a multi-shard query is a
single `shard_map` launch — per-shard scoring + top-k on each device slot,
`all_gather` + top_k across the axis — instead of a serialized per-shard
Python loop with a host merge (TPU-KNN's roofline argument: the scan AND
the reduce must stay on device to amortize dispatch overhead).

Residency is keyed by READER GENERATION: the registry key embeds each
shard's engine instance id, snapshot generation and segment count, so a
refresh mid-flight can never be answered from another snapshot's slab — a
bumped generation is a different key, a different bundle, a different
launch (the same snapshot-safety invariant the kNN micro-batcher's batch
keys carry). One bundle stays live per (index, field); superseded
generations are evicted on insert, and `invalidate_index` drops an index's
bundles when its shards leave the node (cluster-state application).

HBM budgeting (ISSUE 10): the registry enforces a BYTE budget — dynamic
``search.mesh.hbm_budget_bytes`` — with LRU-by-bytes eviction, replacing
the old bundle-count bound (eight tiny one-shard bundles and eight
million-doc slabs are not the same residency pressure; TPU-KNN's roofline
is bytes, not bundle counts). Every eviction frees the bundle's
device-residency-ledger allocation and lands a ``mesh.evict`` span EVENT
on whichever request triggered it, so the decision is observable in
``_nodes/stats`` AND in traces.

The registry is process-wide (one process == one device set — the same
scope as the kNN dispatch batcher); sim nodes sharing an interpreter share
it safely because engine instance ids keep their keys disjoint.
"""

from __future__ import annotations

import threading
from typing import Any

from opensearch_tpu.common.settings import Property, Setting, parse_bytes

# registered metric name for the fenced sharded-launch wall (metric names
# are constants, never built at the record site — tpulint TPU013)
MESH_LAUNCH_WALL_MS = "mesh.launch.wall_ms"

# -- settings (registered dynamic in cluster/cluster_settings.py) -----------


def _validate_budget(v: int) -> None:
    if v < 0:
        raise ValueError(
            f"search.mesh.hbm_budget_bytes must be >= 0 (0 disables the "
            f"byte bound), got [{v}]")


# default one GiB of mesh-bundle residency; "1gb"-style values accepted on
# PUT (parse_bytes), 0 disables the byte bound
MESH_HBM_BUDGET_SETTING = Setting(
    "search.mesh.hbm_budget_bytes", 1 << 30, parse_bytes,
    Property.NODE_SCOPE, Property.DYNAMIC, validator=_validate_budget,
)

MESH_SETTINGS = (MESH_HBM_BUDGET_SETTING,)


def _bundle_nbytes(bundle: Any) -> int:
    return int(getattr(bundle, "nbytes", 0) or 0)


def _free_bundle(bundle: Any, reason: str) -> None:
    alloc = getattr(bundle, "allocation", None)
    if alloc is not None:
        alloc.free(reason=reason)


class ShardMeshRegistry:
    """Tracks device-resident shard bundles keyed by reader generation,
    bounded by an HBM byte budget (LRU-by-bytes)."""

    def __init__(self, hbm_budget_bytes: int | None = None,
                 max_bundles: int | None = None):
        from opensearch_tpu.common.settings import Settings

        self.hbm_budget_bytes = (
            hbm_budget_bytes if hbm_budget_bytes is not None
            else MESH_HBM_BUDGET_SETTING.default(Settings.EMPTY))
        # optional legacy count backstop (tests may pin it); the byte
        # budget is the production bound
        self.max_bundles = max_bundles
        self.metrics = None  # MetricsRegistry sink (ClusterNode attaches)
        self._lock = threading.Lock()
        # insertion-ordered dict as LRU: hits re-insert, eviction pops head
        self._bundles: dict[tuple, Any] = {}
        # dict cell (not a bare attribute) so the *_locked helpers mutate
        # it by subscript under the caller-held lock
        self._mem = {"resident_bytes": 0}
        self._launch_seq = 0
        self.stats = {
            "builds": 0,          # slabs uploaded (cold generations)
            "hits": 0,            # launches served by a resident bundle
            "evictions": 0,       # superseded generations + budget pressure
            "evicted_bytes": 0,   # bytes released by those evictions
            "invalidations": 0,   # index-level drops (shard left the node)
            "invalidated_bytes": 0,  # bytes released by those drops
            "launches": 0,        # sharded device launches issued
            "fused_launches": 0,  # launches served by the fused per-shard
            #                       scan (search.knn.kernel = pallas)
        }
        # last resolved exact-path policy a launch ran under (attribution
        # for _nodes/stats; the roofline report names the kernel family,
        # this names the policy that picked it)
        self.last_kernel: str | None = None
        self.last_score_precision: str | None = None

    # -- config --------------------------------------------------------------

    def configure(self, *, hbm_budget_bytes: int | None = None) -> None:
        if hbm_budget_bytes is None:
            return
        # plain atomic rebind read racily by design (the dynamic-settings
        # contract, same as the batcher's config fields); the eviction pass
        # below then enforces the new bound under the lock
        self.hbm_budget_bytes = max(0, int(hbm_budget_bytes))
        with self._lock:
            self._enforce_budget_locked(incoming=0)

    def apply_settings(self, flat: dict) -> None:
        """Pick this registry's keys out of a flat effective-settings map
        (the cluster-settings update consumer — same adapter shape as the
        kNN batcher's)."""
        from opensearch_tpu.common.settings import Settings

        s = Settings.from_flat({
            st.key: flat[st.key] for st in MESH_SETTINGS if st.key in flat
        })
        self.configure(hbm_budget_bytes=MESH_HBM_BUDGET_SETTING.get(s))

    # -- keys ---------------------------------------------------------------

    @staticmethod
    def residency_key(index: str, field: str, shards: list, snaps: list) -> tuple:
        """Generation-pinned identity of one node's shard set for a field.

        Engine instance ids make the key immune to delete+recreate cycles
        (generations restart at 0 on a fresh engine); the generation tuple
        is the refresh-isolation invariant — a refresh never merges across
        snapshots because it can never share a key."""
        return (
            index, field, len(shards),
            tuple(sh.engine.instance_id for sh in shards),
            tuple(snap.generation for snap in snaps),
            tuple(len(snap.segments) for snap in snaps),
        )

    # -- bundle cache -------------------------------------------------------

    def get(self, key: tuple) -> Any | None:
        with self._lock:
            bundle = self._bundles.get(key)
            if bundle is not None:
                self.stats["hits"] += 1
                # LRU touch
                del self._bundles[key]
                self._bundles[key] = bundle
            return bundle

    def _evict_locked(self, key: tuple, reason: str) -> None:
        bundle = self._bundles.pop(key)
        nbytes = _bundle_nbytes(bundle)
        self._mem["resident_bytes"] -= nbytes
        self.stats["evictions"] += 1
        self.stats["evicted_bytes"] += nbytes
        _free_bundle(bundle, reason=reason)
        # the eviction decision rides the triggering request's trace as a
        # span EVENT (no-op outside a span): budget pressure is diagnosable
        # from the trace that paid for it, not only from counters
        from opensearch_tpu.telemetry.tracing import add_span_event

        add_span_event("mesh.evict", {
            "index": key[0], "field": key[1], "reason": reason,
            "bytes": nbytes,
        })

    def _enforce_budget_locked(self, incoming: int) -> None:
        """LRU-by-bytes: evict from the cold end until `incoming` more
        bytes fit the budget. A single bundle larger than the whole budget
        is still admitted (the query must be served; everything else
        evicts) — the stats make that state visible."""
        budget = self.hbm_budget_bytes
        if budget <= 0:
            return
        while self._bundles and \
                self._mem["resident_bytes"] + incoming > budget:
            self._evict_locked(next(iter(self._bundles)), "hbm-budget")

    def put(self, key: tuple, bundle: Any) -> Any:
        """Insert a freshly built bundle; returns the WINNING bundle (an
        entry another thread raced in first wins, so callers always launch
        against the cached slab — the losing duplicate's ledger allocation
        is freed here)."""
        with self._lock:
            existing = self._bundles.get(key)
            if existing is not None:
                if existing is not bundle:
                    _free_bundle(bundle, reason="duplicate-build")
                return existing
            # one live bundle per residency SLOT — (index, field, engine
            # instance ids), i.e. per node's shard set: a refresh bumps
            # the generations but keeps the engines, so the old
            # generation's bundle evicts now, not at budget pressure.
            # Keying the slot by engine ids (not just index/field) lets
            # in-process sim nodes hold their OWN copies' bundles side by
            # side — the residency-aware router depends on a warm copy
            # STAYING warm while another node serves its disjoint shards.
            for stale in [k for k in self._bundles
                          if k[:2] == key[:2] and k[3] == key[3]]:
                self._evict_locked(stale, "superseded")
            self._enforce_budget_locked(incoming=_bundle_nbytes(bundle))
            if self.max_bundles is not None:
                while len(self._bundles) >= self.max_bundles:
                    self._evict_locked(next(iter(self._bundles)),
                                       "bundle-count")
            self._bundles[key] = bundle
            self._mem["resident_bytes"] += _bundle_nbytes(bundle)
            self.stats["builds"] += 1
            return bundle

    def warm_for(self, index: str, field: str,
                 engine_ids: set | frozenset) -> bool:
        """True when a resident bundle serves (index, field) for shards
        whose engines are all in `engine_ids` — the node-side residency
        truth the coordinator's replica router learns from (a bundle
        keyed to ANOTHER node's engine instances in a shared-process sim
        never counts as this node's warmth). Pure read: no LRU touch, no
        hit accounting — consulting residency is not serving from it."""
        with self._lock:
            return any(
                k[0] == index and k[1] == field
                and set(k[3]) <= set(engine_ids)
                for k in self._bundles
            )

    def warm_pairs(self, engine_ids: set | frozenset) -> list[tuple]:
        """Every (index, field) with a resident bundle keyed to engines in
        `engine_ids` — THIS node's warm set, advertised on stats/join
        traffic so a fresh coordinator's ResidencyBoard seeds before the
        first stamped partial (ISSUE 15). Pure read, like warm_for."""
        ids = set(engine_ids)
        with self._lock:
            return sorted({
                (k[0], k[1]) for k in self._bundles if set(k[3]) <= ids
            })

    def invalidate_index(self, index: str) -> int:
        """Drop every bundle of `index` (its shards left this node or the
        index was deleted); returns the number of bundles dropped."""
        with self._lock:
            stale = [k for k in self._bundles if k[0] == index]
            stale_bytes = sum(
                _bundle_nbytes(self._bundles[k]) for k in stale)
            for k in stale:
                self._evict_locked(k, "invalidated")
            if stale:
                # invalidations are their own counters; _evict_locked
                # already counted them as evictions (count AND bytes), so
                # rebalance both — evicted_bytes must reconcile with the
                # evictions counter it documents
                self.stats["evictions"] -= len(stale)
                self.stats["evicted_bytes"] -= stale_bytes
                self.stats["invalidations"] += len(stale)
                self.stats["invalidated_bytes"] = (
                    self.stats.get("invalidated_bytes", 0) + stale_bytes)
            return len(stale)

    # -- launch bookkeeping -------------------------------------------------

    def next_launch_id(self) -> int:
        with self._lock:
            self._launch_seq += 1
            self.stats["launches"] += 1
            return self._launch_seq

    def record_launch_kernel(self, kernel: str, precision: str) -> None:
        """Per-launch exact-path policy attribution (search.knn.kernel):
        counts launches the fused per-shard scan served and pins the last
        resolved kernel/precision into the stats surface."""
        with self._lock:
            if kernel == "pallas":
                self.stats["fused_launches"] += 1
            self.last_kernel = kernel
            self.last_score_precision = precision

    def record_launch_wall(self, wall_ns: int) -> None:
        """Feed the fenced launch wall into the EXECUTING node's metrics
        (the activate() scope its request handler opened — so in-process
        sim nodes don't all record into the last-attached sink), falling
        back to the attached MetricsRegistry; records an exemplar-linked
        `mesh.launch.wall_ms` histogram point."""
        from opensearch_tpu.telemetry.tracing import active_metrics

        metrics = active_metrics() or self.metrics
        if metrics is not None:
            metrics.histogram(MESH_LAUNCH_WALL_MS).record(wall_ns / 1e6)

    # -- introspection ------------------------------------------------------

    def resident(self) -> list[dict]:
        """What is device-resident right now (for node stats / debugging):
        one row per bundle with its byte size."""
        with self._lock:
            return [
                {"index": k[0], "field": k[1], "shards": k[2],
                 "generations": list(k[4]),
                 "bytes": _bundle_nbytes(b)}
                for k, b in self._bundles.items()
            ]

    def resident_bytes(self) -> int:
        with self._lock:
            return self._mem["resident_bytes"]

    def snapshot_stats(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["resident_bundles"] = len(self._bundles)
            out["resident_bytes"] = self._mem["resident_bytes"]
            out["hbm_budget_bytes"] = self.hbm_budget_bytes
            if self.last_kernel is not None:
                out["last_kernel"] = self.last_kernel
                out["last_score_precision"] = self.last_score_precision
        return out

    def clear(self) -> None:
        with self._lock:
            for bundle in self._bundles.values():
                _free_bundle(bundle, reason="cleared")
            self._bundles.clear()
            # fixed-key accounting cell, not a growing buffer
            self._mem["resident_bytes"] = 0  # tpulint: disable=TPU009

    def reset_stats(self) -> None:
        """Test hook: zero the counters (never the resident bundles)."""
        with self._lock:
            zeroed = dict.fromkeys(self.stats, 0)
            self.stats.clear()
            self.stats.update(zeroed)


# process-wide default registry: adopted by serving nodes (TpuNode /
# ClusterNode) the same way the default kNN batcher is
default_registry = ShardMeshRegistry()
