"""Shard-mesh registry: a node's device-resident shards as ONE sharded array.

The data-plane residency layer behind the single-launch-per-node kNN path
(ROADMAP item 1): every (index, field) whose shards live on this node is
flattened into one [S, n_flat, d] slab sharded over a `Mesh` data axis
(parallel/distributed.build_knn_serving_step), so a multi-shard query is a
single `shard_map` launch — per-shard scoring + top-k on each device slot,
`all_gather` + top_k across the axis — instead of a serialized per-shard
Python loop with a host merge (TPU-KNN's roofline argument: the scan AND
the reduce must stay on device to amortize dispatch overhead).

Residency is keyed by READER GENERATION: the registry key embeds each
shard's engine instance id, snapshot generation and segment count, so a
refresh mid-flight can never be answered from another snapshot's slab — a
bumped generation is a different key, a different bundle, a different
launch (the same snapshot-safety invariant the kNN micro-batcher's batch
keys carry). One bundle stays live per (index, field); superseded
generations are evicted on insert, and `invalidate_index` drops an index's
bundles when its shards leave the node (cluster-state application).

The registry is process-wide (one process == one device set — the same
scope as the kNN dispatch batcher); sim nodes sharing an interpreter share
it safely because engine instance ids keep their keys disjoint.
"""

from __future__ import annotations

import threading
from typing import Any

# insertion-ordered dict as LRU: hits re-insert, eviction pops the head
_DEFAULT_MAX_BUNDLES = 8

# registered metric name for the fenced sharded-launch wall (metric names
# are constants, never built at the record site — tpulint TPU013)
MESH_LAUNCH_WALL_MS = "mesh.launch.wall_ms"


class ShardMeshRegistry:
    """Tracks device-resident shard bundles keyed by reader generation."""

    def __init__(self, max_bundles: int = _DEFAULT_MAX_BUNDLES):
        self.max_bundles = max_bundles
        self.metrics = None  # MetricsRegistry sink (ClusterNode attaches)
        self._lock = threading.Lock()
        self._bundles: dict[tuple, Any] = {}
        self._launch_seq = 0
        self.stats = {
            "builds": 0,          # slabs uploaded (cold generations)
            "hits": 0,            # launches served by a resident bundle
            "evictions": 0,       # superseded generations + LRU pressure
            "invalidations": 0,   # index-level drops (shard left the node)
            "launches": 0,        # sharded device launches issued
        }

    # -- keys ---------------------------------------------------------------

    @staticmethod
    def residency_key(index: str, field: str, shards: list, snaps: list) -> tuple:
        """Generation-pinned identity of one node's shard set for a field.

        Engine instance ids make the key immune to delete+recreate cycles
        (generations restart at 0 on a fresh engine); the generation tuple
        is the refresh-isolation invariant — a refresh never merges across
        snapshots because it can never share a key."""
        return (
            index, field, len(shards),
            tuple(sh.engine.instance_id for sh in shards),
            tuple(snap.generation for snap in snaps),
            tuple(len(snap.segments) for snap in snaps),
        )

    # -- bundle cache -------------------------------------------------------

    def get(self, key: tuple) -> Any | None:
        with self._lock:
            bundle = self._bundles.get(key)
            if bundle is not None:
                self.stats["hits"] += 1
                # LRU touch
                del self._bundles[key]
                self._bundles[key] = bundle
            return bundle

    def put(self, key: tuple, bundle: Any) -> Any:
        """Insert a freshly built bundle; returns the WINNING bundle (an
        entry another thread raced in first wins, so callers always launch
        against the cached slab)."""
        with self._lock:
            existing = self._bundles.get(key)
            if existing is not None:
                return existing
            # one live bundle per (index, field): superseded generations
            # of the same residency slot evict now, not at LRU pressure
            for stale in [k for k in self._bundles if k[:2] == key[:2]]:
                del self._bundles[stale]
                self.stats["evictions"] += 1
            while len(self._bundles) >= self.max_bundles:
                del self._bundles[next(iter(self._bundles))]
                self.stats["evictions"] += 1
            self._bundles[key] = bundle
            self.stats["builds"] += 1
            return bundle

    def invalidate_index(self, index: str) -> int:
        """Drop every bundle of `index` (its shards left this node or the
        index was deleted); returns the number of bundles dropped."""
        with self._lock:
            stale = [k for k in self._bundles if k[0] == index]
            for k in stale:
                del self._bundles[k]
            if stale:
                self.stats["invalidations"] += len(stale)
            return len(stale)

    # -- launch bookkeeping -------------------------------------------------

    def next_launch_id(self) -> int:
        with self._lock:
            self._launch_seq += 1
            self.stats["launches"] += 1
            return self._launch_seq

    def record_launch_wall(self, wall_ns: int) -> None:
        """Feed the fenced launch wall into the EXECUTING node's metrics
        (the activate() scope its request handler opened — so in-process
        sim nodes don't all record into the last-attached sink), falling
        back to the attached MetricsRegistry; records an exemplar-linked
        `mesh.launch.wall_ms` histogram point."""
        from opensearch_tpu.telemetry.tracing import active_metrics

        metrics = active_metrics() or self.metrics
        if metrics is not None:
            metrics.histogram(MESH_LAUNCH_WALL_MS).record(wall_ns / 1e6)

    # -- introspection ------------------------------------------------------

    def resident(self) -> list[dict]:
        """What is device-resident right now (for node stats / debugging)."""
        with self._lock:
            return [
                {"index": k[0], "field": k[1], "shards": k[2],
                 "generations": list(k[4])}
                for k in self._bundles
            ]

    def snapshot_stats(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["resident_bundles"] = len(self._bundles)
        return out

    def clear(self) -> None:
        with self._lock:
            self._bundles.clear()

    def reset_stats(self) -> None:
        """Test hook: zero the counters (never the resident bundles)."""
        with self._lock:
            self.stats = dict.fromkeys(self.stats, 0)


# process-wide default registry: adopted by serving nodes (TpuNode /
# ClusterNode) the same way the default kNN batcher is
default_registry = ShardMeshRegistry()
