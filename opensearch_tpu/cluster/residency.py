"""Residency-aware replica routing: land each shard's launch on the warm copy.

FusionANNS' core serving argument (PAPERS.md): at scale, routing work to
where the data ALREADY RESIDES is the dominant tail lever — a kNN launch
against a node whose mesh bundle or IVF-PQ slab is HBM-resident costs one
kernel; against a cold copy it first pays the full slab upload (the
"cold-rebuild tax" the PR 10 residency ledger made visible). This module
closes that loop: the DATA NODE consults its own
:mod:`~opensearch_tpu.telemetry.device_ledger` /
:class:`~opensearch_tpu.cluster.shard_mesh.ShardMeshRegistry` rows after
serving a kNN partial and stamps the wire response with its residency
truth; the COORDINATOR collects those stamps in a :class:`ResidencyBoard`
and, on the next fan-out, prefers the copy whose structures are warm —
falling back to round-robin when no copy is (spreading the first build),
and to the existing per-shard degrade path when the warm copy is lost
mid-stream.

The board is per-coordinator (not a process-wide singleton): residency
facts arrive over the wire, so the design holds over TCP where each node
is its own process — there is no shared-registry shortcut baked into the
routing decision. Entries are bounded (LRU) and pruned at cluster-state
application when a node or index leaves.

``search.routing.residency`` (dynamic) is the kill switch: disabled, the
coordinator keeps the legacy prefer-primary selection — the bench's
control-plane-off configuration.
"""

from __future__ import annotations

import threading
from typing import Any

from opensearch_tpu.common.settings import Property, Setting

# -- settings (registered dynamic in cluster/cluster_settings.py) -----------

RESIDENCY_ROUTING_SETTING = Setting.bool_setting(
    "search.routing.residency", True,
    Property.NODE_SCOPE, Property.DYNAMIC,
)

ROUTING_SETTINGS = (RESIDENCY_ROUTING_SETTING,)


class RoutingConfig:
    """Process-wide routing policy toggle (the lane-config adapter
    shape); read racily by design like every dynamic knob."""

    def __init__(self, enabled: bool | None = None):
        from opensearch_tpu.common.settings import Settings

        self.enabled = (enabled if enabled is not None
                        else RESIDENCY_ROUTING_SETTING.default(Settings.EMPTY))

    def configure(self, *, enabled: bool | None = None) -> None:
        if enabled is not None:
            self.enabled = bool(enabled)

    def apply_settings(self, flat: dict) -> None:
        from opensearch_tpu.common.settings import Settings

        s = Settings.from_flat({
            st.key: flat[st.key] for st in ROUTING_SETTINGS if st.key in flat
        })
        self.configure(enabled=RESIDENCY_ROUTING_SETTING.get(s))


default_config = RoutingConfig()


def knn_query_field(body: dict | None) -> str | None:
    """The single kNN field of a bare knn body ({"query": {"knn": {f:
    ...}}}), or None — residency facts are per (index, field)."""
    if not isinstance(body, dict):
        return None
    query = body.get("query")
    if not isinstance(query, dict) or set(query) != {"knn"}:
        return None
    knn = query["knn"]
    if isinstance(knn, dict) and len(knn) == 1:
        return next(iter(knn))
    return None


# board entries are per (node, index, field); a serving tier holds a few
# indices x a few vector fields x a few dozen nodes — 512 is generous,
# and LRU eviction keeps a pathological workload bounded (TPU009)
MAX_BOARD_ENTRIES = 512


class ResidencyBoard:
    """Coordinator-side map of which copies are warm, learned from the
    ``_residency`` stamps data nodes attach to kNN partials."""

    def __init__(self, max_entries: int = MAX_BOARD_ENTRIES):
        self._lock = threading.Lock()
        self.max_entries = max_entries
        # insertion-ordered dict as LRU: observe re-inserts, prune pops
        self._warm: dict[tuple[str, str, str], bool] = {}
        self.stats = {
            "warm_hits": 0,     # fan-outs where >= 1 shard landed warm
            "cold_routes": 0,   # fan-outs routed with no warm copy known
            "observations": 0,  # residency stamps consumed
        }

    # -- learning ----------------------------------------------------------

    def observe(self, node_id: str, index: str, field: str,
                warm: bool) -> None:
        key = (node_id, index, field)
        with self._lock:
            self.stats["observations"] += 1
            self._warm.pop(key, None)
            self._warm[key] = bool(warm)
            while len(self._warm) > self.max_entries:
                self._warm.pop(next(iter(self._warm)))

    def warm_nodes(self, index: str, field: str) -> set[str]:
        with self._lock:
            return {nid for (nid, idx, f), warm in self._warm.items()
                    if warm and idx == index and f == field}

    def prune(self, live_nodes: set[str] | None = None,
              live_indices: set[str] | None = None) -> None:
        """Drop entries for departed nodes / deleted indices (cluster-state
        application): a dead node must never look warm to the router."""
        with self._lock:
            stale = [
                k for k in self._warm
                if (live_nodes is not None and k[0] not in live_nodes)
                or (live_indices is not None and k[1] not in live_indices)
            ]
            for k in stale:
                del self._warm[k]

    # -- routing -----------------------------------------------------------

    def record_route(self, warm: bool) -> None:
        with self._lock:
            if warm:
                self.stats["warm_hits"] += 1
            else:
                self.stats["cold_routes"] += 1

    def snapshot_stats(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["entries"] = len(self._warm)
            out["warm_entries"] = sum(1 for w in self._warm.values() if w)
        out["enabled"] = default_config.enabled
        return out


def choose_copies(board: ResidencyBoard | None, index: str,
                  field: str | None,
                  candidates_by_shard: dict[int, list],
                  rr_seq: int) -> tuple[dict[int, Any], bool]:
    """Pick one serving copy per shard. With residency routing on and a
    kNN field known: a candidate on a warm node wins (the launch lands on
    the resident slab); with no warm copy, every shard routes to the SAME
    round-robin rank so the node-grouped fan-out stays one-RPC-per-node
    and the first build lands on one replica set, not scattered. Returns
    (shard -> routing entry, any_warm)."""
    targets: dict[int, Any] = {}
    if (board is None or field is None or not default_config.enabled):
        for num, cands in candidates_by_shard.items():
            targets[num] = next(
                (r for r in cands if r.primary), cands[0])
        return targets, False
    warm = board.warm_nodes(index, field)
    any_warm = False
    for num, cands in sorted(candidates_by_shard.items()):
        ordered = sorted(cands, key=lambda r: (not r.primary, r.node_id))
        hot = next((r for r in ordered if r.node_id in warm), None)
        if hot is not None:
            targets[num] = hot
            any_warm = True
        else:
            targets[num] = ordered[rr_seq % len(ordered)]
    board.record_route(any_warm)
    return targets, any_warm
