"""Cross-cluster search: remote cluster registry + fan-out client.

The analog of the reference's CCS stack
(server/src/main/java/org/opensearch/transport/RemoteClusterService.java:80
+ RemoteClusterAware's "cluster:index" expression split and
TransportSearchAction's remote shard fan-out): remote clusters register
under `cluster.remote.<alias>.seeds` dynamic settings; search expressions
`alias:pattern` route to them; the coordinator merges remote hits with
local ones and reports the per-cluster `_clusters` section.

Transport: the remote's REST surface over HTTP (urllib). The reference
dials the binary transport; this engine's REST carries the same search
contract, and a zero-dependency HTTP client keeps CCS usable against any
node of a remote cluster — the sniff/proxy connection-strategy split
collapses to "first reachable seed".
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any

from opensearch_tpu.common.errors import (
    ConnectTransportException,
    IllegalArgumentException,
)

REMOTE_SEPARATOR = ":"


def split_index_expression(expr: str) -> tuple[dict[str, list[str]], list[str]]:
    """"c1:logs-*,local,c2:x" -> ({"c1": ["logs-*"], "c2": ["x"]}, ["local"])
    (RemoteClusterAware.groupClusterIndices)."""
    remotes: dict[str, list[str]] = {}
    locals_: list[str] = []
    for part in (expr or "").split(","):
        part = part.strip()
        if not part:
            continue
        if REMOTE_SEPARATOR in part and not part.startswith(REMOTE_SEPARATOR):
            alias, _, pattern = part.partition(REMOTE_SEPARATOR)
            remotes.setdefault(alias, []).append(pattern)
        else:
            locals_.append(part)
    return remotes, locals_


class RemoteClusterService:
    """Registry of remote clusters + HTTP search client."""

    def __init__(self, node):
        self.node = node

    def registered(self) -> dict[str, list[str]]:
        """alias -> seed list from cluster.remote.<alias>.seeds settings."""
        out: dict[str, list[str]] = {}
        for store in (getattr(self.node, "_cluster_settings", {}) or {},
                      getattr(self.node, "_transient_cluster_settings", {}) or {}):
            for key, value in store.items():
                parts = key.split(".")
                if len(parts) == 4 and parts[0] == "cluster" \
                        and parts[1] == "remote" and parts[3] == "seeds" \
                        and value is not None:
                    seeds = (value if isinstance(value, list)
                             else str(value).split(","))
                    out[parts[2]] = [str(s).strip() for s in seeds if s]
        return out

    def info(self) -> dict:
        """GET /_remote/info (RemoteClusterService.getRemoteConnectionInfos)."""
        return {
            alias: {
                "seeds": seeds,
                "connected": True,  # lazily dialed on first use
                "num_nodes_connected": 1,
                "max_connections_per_cluster": 1,
                "initial_connect_timeout": "30s",
                "skip_unavailable": False,
            }
            for alias, seeds in self.registered().items()
        }

    def _base_url(self, alias: str) -> str:
        seeds = self.registered().get(alias)
        if not seeds:
            raise IllegalArgumentException(
                f"no such remote cluster: [{alias}]"
            )
        seed = seeds[0]
        if not seed.startswith("http"):
            seed = f"http://{seed}"
        return seed.rstrip("/")

    def search_remote(self, alias: str, index_expr: str, body: dict,
                      timeout_s: float = 30.0) -> dict:
        """One remote cluster's full search response."""
        url = f"{self._base_url(alias)}/{index_expr or '_all'}/_search"
        data = json.dumps(body or {}).encode()
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:200]
            raise IllegalArgumentException(
                f"remote cluster [{alias}] search failed: HTTP {e.code} "
                f"{detail}"
            ) from e
        except (urllib.error.URLError, OSError) as e:
            raise ConnectTransportException(
                f"unable to connect to remote cluster [{alias}]: {e}"
            ) from e


def merge_cross_cluster(local_resp: dict | None,
                        remote_resps: dict[str, dict],
                        body: dict) -> dict:
    """Merge a local response with per-remote responses: hits re-sorted by
    (score|sort values), remote hit _index prefixed "alias:index"
    (SearchResponseMerger semantics)."""
    size = int(body.get("size", 10))
    from_ = int(body.get("from", 0))
    sort = body.get("sort")
    all_hits: list[tuple[Any, dict]] = []
    total = 0
    max_score = None
    took = 0
    shards = {"total": 0, "successful": 0, "skipped": 0, "failed": 0}
    responses = ([("", local_resp)] if local_resp is not None else []) + [
        (alias, r) for alias, r in remote_resps.items()
    ]
    for alias, resp in responses:
        took = max(took, resp.get("took", 0))
        for k in shards:
            shards[k] += resp.get("_shards", {}).get(k, 0)
        h = resp.get("hits", {})
        t = h.get("total")
        if isinstance(t, dict):
            total += t.get("value", 0)
        elif isinstance(t, int):
            total += t
        ms = h.get("max_score")
        if ms is not None and (max_score is None or ms > max_score):
            max_score = ms
        for hit in h.get("hits", []):
            if alias:
                hit = {**hit, "_index": f"{alias}:{hit.get('_index')}"}
            all_hits.append(hit)

    if sort:
        sort_list = [sort] if isinstance(sort, (str, dict)) else list(sort)
        orders = []
        for spec in sort_list:
            if isinstance(spec, str):
                orders.append("desc" if spec == "_score" else "asc")
            else:
                fname = next(iter(spec), None)
                conf = spec.get(fname)
                if isinstance(conf, str):
                    orders.append(conf)
                elif isinstance(conf, dict):
                    orders.append(conf.get(
                        "order", "desc" if fname == "_score" else "asc"))
                else:
                    orders.append("desc" if fname == "_score" else "asc")

        def key(hit):
            parts = []
            for i, v in enumerate(hit.get("sort", [])):
                desc = i < len(orders) and orders[i] == "desc"
                if v is None:
                    parts.append((1, 0, 0))
                elif isinstance(v, str):
                    parts.append((0, 1, _Rev(v) if desc else v))
                else:
                    parts.append((0, 0, -v if desc else v))
            return tuple(parts)

        all_hits.sort(key=key)
    else:
        all_hits.sort(key=lambda hh: -(hh.get("_score") or 0.0))
    page = all_hits[from_: from_ + size]
    num_clusters = len(remote_resps) + (1 if local_resp is not None else 0)
    return {
        "took": took,
        "timed_out": False,
        "_shards": shards,
        "_clusters": {"total": num_clusters, "successful": num_clusters,
                      "skipped": 0},
        "hits": {
            "total": {"value": total, "relation": "eq"},
            "max_score": max_score,
            "hits": page,
        },
    }


class _Rev:
    """Reverses string comparison for descending merge keys."""

    __slots__ = ("v",)

    def __init__(self, v: str):
        self.v = v

    def __lt__(self, other: "_Rev") -> bool:
        return other.v < self.v

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _Rev) and other.v == self.v
