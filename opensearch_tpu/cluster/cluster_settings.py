"""Dynamic cluster settings registry (ClusterSettings.java:205).

The two-phase persistent/transient model: `PUT /_cluster/settings` carries
{"persistent": {...}, "transient": {...}}; values validate BEFORE the
cluster-state task applies them; null deletes a key. Effective value =
transient over persistent over default. Persistent settings ride the
durable cluster state (gateway) and survive full-cluster restart;
transient settings are stripped at recovery.

Update consumers (ClusterSettings.addSettingsUpdateConsumer): components
register a callback per key prefix; every state application diffs the
effective settings and notifies the consumers whose keys changed.
"""

from __future__ import annotations

from typing import Any, Callable

from opensearch_tpu.common.errors import IllegalArgumentException


def _validate_pct(v: Any) -> None:
    pct = float(str(v).rstrip("%"))
    if not 0 <= pct <= 100:
        raise IllegalArgumentException(f"watermark [{v}] must be 0-100%")


def _validate_pos_int(v: Any) -> None:
    if int(v) < 1:
        raise IllegalArgumentException(f"[{v}] must be >= 1")


def _validate_enable(v: Any) -> None:
    if str(v).lower() not in ("all", "none", "primaries", "replicas"):
        raise IllegalArgumentException(
            f"[{v}] must be one of [all, none, primaries, replicas]"
        )


# registered dynamic cluster settings: key -> validator (None = any value)
DYNAMIC_CLUSTER_SETTINGS: dict[str, Callable[[Any], None] | None] = {
    "cluster.routing.allocation.node_concurrent_recoveries": _validate_pos_int,
    "cluster.routing.allocation.disk.watermark.low": _validate_pct,
    "cluster.routing.allocation.disk.watermark.high": _validate_pct,
    "cluster.routing.allocation.awareness.attributes": None,
    # cluster-level FilterAllocationDecider: comma-separated node NAMES to
    # drain (graceful decommission — shards relocate off, then the node
    # can leave with zero acked-write loss)
    "cluster.routing.allocation.exclude._name": None,
    "cluster.routing.allocation.enable": _validate_enable,
    "cluster.routing.rebalance.enable": _validate_enable,
    "search.max_buckets": _validate_pos_int,
    "search.max_keep_alive": None,
    "search.allow_expensive_queries": None,
    "search.default_keep_alive": None,
    "search.default_search_timeout": None,
    "cluster.max_shards_per_node": _validate_pos_int,
    "action.auto_create_index": None,
    "action.destructive_requires_name": None,
    "cluster.blocks.read_only": None,
    "indices.recovery.max_bytes_per_sec": None,
}


def _validate_backpressure_mode(v: Any) -> None:
    if str(v) not in ("monitor_only", "enforced", "disabled"):
        raise IllegalArgumentException(
            f"Invalid SearchBackpressureMode: {v}")


def _pos_double(key: str) -> Callable[[Any], None]:
    def validate(v: Any) -> None:
        if float(v) <= 0:
            raise IllegalArgumentException(f"{key} must be > 0")
    return validate


# search backpressure settings (SearchBackpressureSettings +
# SearchTaskSettings/SearchShardTaskSettings in the reference)
DYNAMIC_CLUSTER_SETTINGS["search_backpressure.mode"] = \
    _validate_backpressure_mode
for _task in ("search_task", "search_shard_task"):
    for _name, _v in [
        ("cancellation_burst", None),
        ("cancellation_rate",
         _pos_double(f"search_backpressure.{_task}.cancellation_rate")),
        ("cancellation_ratio",
         _pos_double(f"search_backpressure.{_task}.cancellation_ratio")),
        ("elapsed_time_millis_threshold", None),
        ("cpu_time_millis_threshold", None),
        ("heap_percent_threshold", None),
        ("total_heap_percent_threshold", None),
        ("heap_variance", None),
        ("heap_moving_average_window_size", None),
    ]:
        DYNAMIC_CLUSTER_SETTINGS[
            f"search_backpressure.{_task}.{_name}"] = _v
for _name in ("num_successive_breaches", "cpu_threshold", "heap_threshold"):
    DYNAMIC_CLUSTER_SETTINGS[f"search_backpressure.node_duress.{_name}"] = None

def _validate_with_setting(setting) -> Callable[[Any], None]:
    """Adapt a common.settings.Setting parser+validator to this registry."""
    def validate(v: Any) -> None:
        try:
            value = setting.parser(v)
        except (ValueError, TypeError):
            raise IllegalArgumentException(
                f"failed to parse value [{v!r}] for setting [{setting.key}]"
            ) from None
        if setting.validator is not None:
            try:
                setting.validator(value)
            except Exception as e:  # noqa: BLE001 - surface as 400
                raise IllegalArgumentException(str(e)) from None
    return validate


def _register_typed_settings() -> None:
    # kNN dispatch batcher (search/batcher.py) + ANN serving knobs
    # (search/ann.py) + request-cache budget: the Setting objects carry
    # parser/validator/default; the registry reuses them so
    # PUT /_cluster/settings validation cannot drift from the component's
    # own parsing
    from opensearch_tpu.cluster.residency import ROUTING_SETTINGS
    from opensearch_tpu.cluster.shard_mesh import MESH_SETTINGS
    from opensearch_tpu.index.request_cache import CACHE_SIZE_SETTING
    from opensearch_tpu.search.ann import ANN_SETTINGS
    from opensearch_tpu.search.batcher import BATCH_SETTINGS
    from opensearch_tpu.search.lanes import LANE_SETTINGS
    from opensearch_tpu.telemetry.device_ledger import HEAT_SETTINGS
    from opensearch_tpu.telemetry.export import TRACING_SETTINGS

    for s in (*BATCH_SETTINGS, *ANN_SETTINGS, CACHE_SIZE_SETTING,
              *TRACING_SETTINGS, *MESH_SETTINGS, *LANE_SETTINGS,
              *ROUTING_SETTINGS, *HEAT_SETTINGS):
        DYNAMIC_CLUSTER_SETTINGS[s.key] = _validate_with_setting(s)


_register_typed_settings()

# prefix-registered settings (affix settings in the reference —
# Setting.affixKeySetting): any key matching "<prefix>.<name>.<suffix>"
DYNAMIC_AFFIX_SETTINGS: list[tuple[str, str]] = [
    ("cluster.remote.", ".seeds"),
    ("cluster.remote.", ".skip_unavailable"),
]


def validate_settings(flat: dict[str, Any]) -> None:
    for key, value in flat.items():
        if any(key.startswith(p) and key.endswith(sfx)
               for p, sfx in DYNAMIC_AFFIX_SETTINGS):
            continue
        validator = DYNAMIC_CLUSTER_SETTINGS.get(key, "__missing__")
        if validator == "__missing__":
            raise IllegalArgumentException(
                f"unknown cluster setting [{key}] — not registered as a "
                f"dynamic setting"
            )
        if validator is not None and value is not None:
            validator(value)


def flatten(obj: dict, prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    for k, v in (obj or {}).items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(flatten(v, f"{key}."))
        else:
            out[key] = v
    return out


def merge(current: dict, updates: dict) -> dict:
    """Apply a flat update map: null values delete keys."""
    out = dict(current)
    for k, v in updates.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = v
    return out


def effective(persistent: dict, transient: dict) -> dict:
    return {**persistent, **transient}


class SettingsUpdateConsumers:
    """addSettingsUpdateConsumer registry: notified on effective-value
    changes at state application."""

    def __init__(self) -> None:
        self._consumers: list[tuple[str, Callable[[dict], None]]] = []
        self._last: dict[str, Any] = {}

    def register(self, key_prefix: str,
                 consumer: Callable[[dict], None]) -> None:
        self._consumers.append((key_prefix, consumer))

    def apply(self, eff: dict) -> None:
        changed = {
            k for k in set(eff) | set(self._last)
            if eff.get(k) != self._last.get(k)
        }
        if not changed:
            return
        self._last = dict(eff)
        for prefix, consumer in self._consumers:
            if any(k.startswith(prefix) for k in changed):
                consumer(eff)
