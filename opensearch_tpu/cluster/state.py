"""Cluster state: the immutable, versioned snapshot every node applies.

The analog of the reference's ClusterState + Metadata + RoutingTable
(server/src/main/java/org/opensearch/cluster/ClusterState.java,
cluster/metadata/Metadata.java, cluster/routing/RoutingTable.java) with the
same versioning semantics: `term` advances with elections, `version` with
every published state; diffs ship (version N -> N+1) deltas so repeated
publications don't reserialize whole states (DiffableUtils analog).

Plain dataclasses + dict serialization — the control plane is host-side
Python; nothing here touches JAX.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field, replace
from typing import Any


@dataclass(frozen=True)
class DiscoveryNode:
    node_id: str
    name: str = ""
    address: str = ""
    roles: tuple[str, ...] = ("cluster_manager", "data")
    # node attributes for awareness allocation (node.attr.* in the
    # reference, e.g. {"zone": "us-east-1a"})
    attrs: tuple = ()

    @property
    def is_cluster_manager_eligible(self) -> bool:
        return "cluster_manager" in self.roles

    @property
    def is_data(self) -> bool:
        return "data" in self.roles

    @property
    def attr_map(self) -> dict:
        return dict(self.attrs)

    def to_dict(self) -> dict:
        return {"node_id": self.node_id, "name": self.name,
                "address": self.address, "roles": list(self.roles),
                "attrs": [list(kv) for kv in self.attrs]}

    @staticmethod
    def from_dict(d: dict) -> "DiscoveryNode":
        return DiscoveryNode(d["node_id"], d.get("name", ""), d.get("address", ""),
                             tuple(d.get("roles", ("cluster_manager", "data"))),
                             tuple(tuple(kv) for kv in d.get("attrs", [])))


@dataclass(frozen=True)
class VotingConfiguration:
    """The quorum set (CoordinationMetadata.VotingConfiguration)."""

    node_ids: frozenset[str] = frozenset()

    def has_quorum(self, votes: set[str]) -> bool:
        if not self.node_ids:
            return False
        return len(votes & self.node_ids) * 2 > len(self.node_ids)

    def to_dict(self) -> list:
        return sorted(self.node_ids)

    @staticmethod
    def of(*node_ids: str) -> "VotingConfiguration":
        return VotingConfiguration(frozenset(node_ids))


@dataclass(frozen=True)
class ShardRoutingEntry:
    """One shard copy's assignment (ShardRouting).

    A relocation is modeled as the reference does: the serving copy moves
    to state RELOCATING with `relocating_node` = the target node, and a
    shadow target entry appears on the target node in state INITIALIZING
    with `relocating_node` = the source node. The pair is ONE logical copy;
    when the target reports started, the swap drops the source entry and
    the target becomes a plain STARTED copy (ShardRouting.relocatingNodeId
    + RoutingNodes.relocateShard semantics)."""

    index: str
    shard: int
    node_id: str | None            # None = unassigned
    primary: bool
    state: str = "UNASSIGNED"      # UNASSIGNED | INITIALIZING | STARTED | RELOCATING
    relocating_node: str | None = None

    @property
    def is_relocation_target(self) -> bool:
        return self.state == "INITIALIZING" and self.relocating_node is not None

    def to_dict(self) -> dict:
        return {"index": self.index, "shard": self.shard, "node_id": self.node_id,
                "primary": self.primary, "state": self.state,
                "relocating_node": self.relocating_node}

    @staticmethod
    def from_dict(d: dict) -> "ShardRoutingEntry":
        return ShardRoutingEntry(d["index"], d["shard"], d.get("node_id"),
                                 d["primary"], d.get("state", "UNASSIGNED"),
                                 d.get("relocating_node"))


@dataclass(frozen=True)
class IndexMeta:
    name: str
    num_shards: int
    num_replicas: int
    settings: dict = field(default_factory=dict)
    mappings: dict = field(default_factory=dict)
    version: int = 1               # bumped on every mapping/settings change

    def to_dict(self) -> dict:
        return {"name": self.name, "num_shards": self.num_shards,
                "num_replicas": self.num_replicas, "settings": self.settings,
                "mappings": self.mappings, "version": self.version}

    @staticmethod
    def from_dict(d: dict) -> "IndexMeta":
        return IndexMeta(d["name"], d["num_shards"], d["num_replicas"],
                         d.get("settings", {}), d.get("mappings", {}),
                         d.get("version", 1))


@dataclass(frozen=True)
class ClusterState:
    term: int = 0
    version: int = 0
    cluster_uuid: str = "_na_"
    leader_id: str | None = None
    nodes: dict[str, DiscoveryNode] = field(default_factory=dict)
    indices: dict[str, IndexMeta] = field(default_factory=dict)
    routing: tuple[ShardRoutingEntry, ...] = ()
    last_committed_config: VotingConfiguration = field(default_factory=VotingConfiguration)
    last_accepted_config: VotingConfiguration = field(default_factory=VotingConfiguration)
    # dynamic cluster settings (ClusterSettings.java:205): persistent
    # survives full-cluster restart; transient is dropped on restart
    # (stripped by the gateway at recovery). Effective = transient over
    # persistent over default.
    settings: dict = field(default_factory=dict)
    transient_settings: dict = field(default_factory=dict)

    # -- builders ---------------------------------------------------------

    def with_(self, **kwargs) -> "ClusterState":
        return replace(self, **kwargs)

    def next_version(self, **kwargs) -> "ClusterState":
        return replace(self, version=self.version + 1, **kwargs)

    # -- views ------------------------------------------------------------

    def shards_for_node(self, node_id: str) -> list[ShardRoutingEntry]:
        return [r for r in self.routing if r.node_id == node_id]

    def shards_for_index(self, index: str) -> list[ShardRoutingEntry]:
        return [r for r in self.routing if r.index == index]

    # name used by the REST-facing views (RoutingTable.index(...) analog)
    routing_for_index = shards_for_index

    def primary(self, index: str, shard: int) -> ShardRoutingEntry | None:
        for r in self.routing:
            if r.index == index and r.shard == shard and r.primary:
                return r
        return None

    # -- serialization -----------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "term": self.term,
            "version": self.version,
            "cluster_uuid": self.cluster_uuid,
            "leader_id": self.leader_id,
            "nodes": {nid: n.to_dict() for nid, n in self.nodes.items()},
            "indices": {name: m.to_dict() for name, m in self.indices.items()},
            "routing": [r.to_dict() for r in self.routing],
            "last_committed_config": self.last_committed_config.to_dict(),
            "last_accepted_config": self.last_accepted_config.to_dict(),
            "settings": self.settings,
            "transient_settings": self.transient_settings,
        }

    @staticmethod
    def from_dict(d: dict) -> "ClusterState":
        return ClusterState(
            term=d["term"],
            version=d["version"],
            cluster_uuid=d.get("cluster_uuid", "_na_"),
            leader_id=d.get("leader_id"),
            nodes={nid: DiscoveryNode.from_dict(n) for nid, n in d["nodes"].items()},
            indices={k: IndexMeta.from_dict(v) for k, v in d["indices"].items()},
            routing=tuple(ShardRoutingEntry.from_dict(r) for r in d["routing"]),
            last_committed_config=VotingConfiguration(frozenset(d["last_committed_config"])),
            last_accepted_config=VotingConfiguration(frozenset(d["last_accepted_config"])),
            settings=d.get("settings", {}),
            transient_settings=d.get("transient_settings", {}),
        )


def diff_states(prev: ClusterState, new: ClusterState) -> dict:
    """Version-to-version delta (Diffable machinery analog). Receivers that
    have `prev.version` apply the diff; others request the full state."""
    d: dict[str, Any] = {
        "from_version": prev.version,
        "to_version": new.version,
        "term": new.term,
        "leader_id": new.leader_id,
        "cluster_uuid": new.cluster_uuid,
        "last_committed_config": new.last_committed_config.to_dict(),
        "last_accepted_config": new.last_accepted_config.to_dict(),
    }
    d["nodes_added"] = {
        nid: n.to_dict() for nid, n in new.nodes.items() if nid not in prev.nodes
    }
    d["nodes_removed"] = [nid for nid in prev.nodes if nid not in new.nodes]
    d["indices_changed"] = {
        name: m.to_dict() for name, m in new.indices.items()
        if name not in prev.indices or prev.indices[name] != m
    }
    d["indices_removed"] = [n for n in prev.indices if n not in new.indices]
    if new.routing != prev.routing:
        d["routing"] = [r.to_dict() for r in new.routing]
    if new.settings != prev.settings:
        d["settings"] = new.settings
    if new.transient_settings != prev.transient_settings:
        d["transient_settings"] = new.transient_settings
    return d


def apply_diff(prev: ClusterState, diff: dict) -> ClusterState:
    if diff["from_version"] != prev.version:
        raise ValueError(
            f"diff from version {diff['from_version']} cannot apply to {prev.version}"
        )
    nodes = dict(prev.nodes)
    for nid in diff["nodes_removed"]:
        nodes.pop(nid, None)
    for nid, n in diff["nodes_added"].items():
        nodes[nid] = DiscoveryNode.from_dict(n)
    indices = dict(prev.indices)
    for name in diff["indices_removed"]:
        indices.pop(name, None)
    for name, m in diff["indices_changed"].items():
        indices[name] = IndexMeta.from_dict(m)
    routing = (
        tuple(ShardRoutingEntry.from_dict(r) for r in diff["routing"])
        if "routing" in diff
        else prev.routing
    )
    return ClusterState(
        term=diff["term"],
        version=diff["to_version"],
        cluster_uuid=diff.get("cluster_uuid", prev.cluster_uuid),
        leader_id=diff.get("leader_id"),
        nodes=nodes,
        indices=indices,
        routing=routing,
        last_committed_config=VotingConfiguration(frozenset(diff["last_committed_config"])),
        last_accepted_config=VotingConfiguration(frozenset(diff["last_accepted_config"])),
        settings=diff.get("settings", prev.settings),
        transient_settings=diff.get("transient_settings", prev.transient_settings),
    )
