"""ClusterServer: one bootable process = transport + coordinator + data + REST.

The Node.java:494 analog. One ClusterServer = a TcpTransport (L2), a
ClusterNode (coordinator + shards + action handlers), a LoopScheduler
(timers), and — the round-3 unification (VERDICT r2 missing #4) — the SAME
128-route trie router the single-node server uses (rest/handlers.py),
served over a ClusterFacade that gives every handler the TpuNode API with
cluster semantics (one RestController + NodeClient in front of one action
registry, rest/RestController.java:285 + action/ActionModule.java:527).

    python -m opensearch_tpu.server --node-id n1 --port 9301 --http-port 9211 \
        --seeds n1=127.0.0.1:9301,n2=127.0.0.1:9302,n3=127.0.0.1:9303 \
        --data /tmp/c/n1 --bootstrap n1,n2,n3

HTTP handlers run on the HttpServer's executor thread and bridge onto the
transport loop through the facade; the loop itself never blocks on data
work (ClusterNode offloads engine ops to its data worker).
"""

from __future__ import annotations

import argparse
import asyncio
from pathlib import Path

from opensearch_tpu.cluster.cluster_node import ClusterNode
from opensearch_tpu.cluster.facade import ClusterFacade
from opensearch_tpu.rest.http import HttpServer
from opensearch_tpu.transport.tcp import LoopScheduler, TcpTransport

REQUEST_TIMEOUT_S = 30.0


def parse_seeds(spec: str) -> dict[str, tuple[str, int]]:
    """"n1=127.0.0.1:9301,n2=..." -> {node_id: (host, port)}"""
    out: dict[str, tuple[str, int]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        node_id, _, addr = part.partition("=")
        host, _, port = addr.rpartition(":")
        out[node_id.strip()] = (host.strip(), int(port))
    return out


class ClusterServer:
    def __init__(
        self,
        node_id: str,
        data_path: str | Path,
        transport_host: str,
        transport_port: int,
        http_port: int,
        seeds: dict[str, tuple[str, int]],
        *,
        loop: asyncio.AbstractEventLoop | None = None,
        roles: tuple[str, ...] = ("cluster_manager", "data"),
    ):
        self.loop = loop or asyncio.get_event_loop()
        self.transport = TcpTransport(
            node_id, transport_host, transport_port, seeds, loop=self.loop
        )
        self.scheduler = LoopScheduler(self.loop)
        # durable cluster state (gateway/PersistedClusterStateService:137):
        # term + accepted state survive restart; recovery happens before
        # elections so a rebooted node cannot double-vote in its old term
        from opensearch_tpu.cluster.coordination import PersistedState
        from opensearch_tpu.gateway import GatewayStore

        self.gateway = GatewayStore(Path(data_path) / "_state")
        recovered = self.gateway.load()
        if recovered is not None:
            # transient cluster settings do NOT survive a restart (the
            # persistent/transient contract of ClusterSettings.java:205)
            term, state = recovered
            persisted = PersistedState(
                term, state.with_(transient_settings={}), store=self.gateway
            )
        else:
            persisted = PersistedState(store=self.gateway)
        self.node = ClusterNode(
            node_id, data_path, self.transport, self.scheduler,
            peers=[p for p in seeds if p != node_id], roles=roles,
            persisted=persisted,
        )
        self.facade = ClusterFacade(self.node, self.loop)
        self.http = HttpServer(self.facade, transport_host, http_port)
        self.http_host = transport_host
        self.http_port = http_port

    async def start(self, bootstrap: list[str] | None = None) -> None:
        await self.transport.start()
        self.node.start()
        if bootstrap:
            self.node.bootstrap(bootstrap)
        await self.http.start()

    async def aclose(self) -> None:
        await self.http.stop()
        self.node.close()
        await self.transport.aclose()


async def amain(args: argparse.Namespace) -> None:
    seeds = parse_seeds(args.seeds)
    server = ClusterServer(
        args.node_id, args.data, args.host,
        seeds[args.node_id][1], args.http_port, seeds,
        loop=asyncio.get_running_loop(),
    )
    bootstrap = args.bootstrap.split(",") if args.bootstrap else None
    await server.start(bootstrap=bootstrap)
    print(f"[{args.node_id}] transport {seeds[args.node_id]} "
          f"http 127.0.0.1:{args.http_port}", flush=True)
    await asyncio.Event().wait()  # run forever


def main() -> None:
    parser = argparse.ArgumentParser(description="opensearch-tpu cluster node")
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--http-port", type=int, required=True)
    parser.add_argument("--data", required=True)
    parser.add_argument("--seeds", required=True,
                        help="n1=127.0.0.1:9301,n2=127.0.0.1:9302,...")
    parser.add_argument("--bootstrap", default=None,
                        help="comma-separated voting node ids (first boot)")
    args = parser.parse_args()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
