"""ClusterServer: one bootable process = transport + coordinator + data + REST.

The Node.java:494 analog — the wiring that fuses the previously separate
silos (VERDICT r1 #1/#2): a TcpTransport (L2), a ClusterNode (coordinator +
shards + action handlers), a LoopScheduler (timers), and an HTTP front end
serving the cluster through ANY node. Start three of these on localhost and
you have a real cluster over real sockets:

    python -m opensearch_tpu.server --node-id n1 --port 9301 --http-port 9211 \
        --seeds n1=127.0.0.1:9301,n2=127.0.0.1:9302,n3=127.0.0.1:9303 \
        --data /tmp/c/n1 --bootstrap n1,n2,n3

Every REST handler bridges the ClusterNode's continuation-passing API onto
an asyncio future resolved on the SAME event loop the transport runs on —
no threads touch cluster state (the single-threaded applier model of
ClusterApplierService).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import re
from pathlib import Path
from typing import Any, Callable

from opensearch_tpu.cluster.cluster_node import ClusterNode
from opensearch_tpu.transport.tcp import LoopScheduler, TcpTransport

REQUEST_TIMEOUT_S = 30.0


def parse_seeds(spec: str) -> dict[str, tuple[str, int]]:
    """"n1=127.0.0.1:9301,n2=..." -> {node_id: (host, port)}"""
    out: dict[str, tuple[str, int]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        node_id, _, addr = part.partition("=")
        host, _, port = addr.rpartition(":")
        out[node_id.strip()] = (host.strip(), int(port))
    return out


class ClusterServer:
    def __init__(
        self,
        node_id: str,
        data_path: str | Path,
        transport_host: str,
        transport_port: int,
        http_port: int,
        seeds: dict[str, tuple[str, int]],
        *,
        loop: asyncio.AbstractEventLoop | None = None,
        roles: tuple[str, ...] = ("cluster_manager", "data"),
    ):
        self.loop = loop or asyncio.get_event_loop()
        self.transport = TcpTransport(
            node_id, transport_host, transport_port, seeds, loop=self.loop
        )
        self.scheduler = LoopScheduler(self.loop)
        self.node = ClusterNode(
            node_id, data_path, self.transport, self.scheduler,
            peers=[p for p in seeds if p != node_id], roles=roles,
        )
        self.http_host = transport_host
        self.http_port = http_port
        self._http_server: asyncio.AbstractServer | None = None

    async def start(self, bootstrap: list[str] | None = None) -> None:
        await self.transport.start()
        self.node.start()
        if bootstrap:
            self.node.bootstrap(bootstrap)
        self._http_server = await asyncio.start_server(
            self._handle_http, self.http_host, self.http_port
        )

    async def aclose(self) -> None:
        if self._http_server is not None:
            self._http_server.close()
            await self._http_server.wait_closed()
        self.node.close()
        await self.transport.aclose()

    # -- callback -> future bridge ----------------------------------------

    def _call(self, fn: Callable, *args, **kwargs) -> "asyncio.Future[dict]":
        fut: asyncio.Future = self.loop.create_future()

        def cb(resp: Any) -> None:
            if not fut.done():
                fut.set_result(resp)

        try:
            fn(*args, cb, **kwargs)
        except Exception as e:  # noqa: BLE001 - surface as the response
            if not fut.done():
                fut.set_result({"error": str(e)})
        return fut

    async def _await(self, fut: "asyncio.Future[dict]") -> dict:
        try:
            return await asyncio.wait_for(fut, REQUEST_TIMEOUT_S)
        except asyncio.TimeoutError:
            return {"error": "request timed out inside the cluster"}

    # -- HTTP front end ----------------------------------------------------

    async def _handle_http(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    method, target, _ = line.decode("latin1").split(" ", 2)
                except ValueError:
                    break
                headers: dict[str, str] = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                length = int(headers.get("content-length", 0))
                body = await reader.readexactly(length) if length else b""
                from urllib.parse import parse_qsl, unquote, urlsplit

                split = urlsplit(target)
                query = dict(parse_qsl(split.query, keep_blank_values=True))
                status, payload = await self._route(
                    method, unquote(split.path), query, body
                )
                data = json.dumps(payload).encode()
                writer.write(
                    (f"HTTP/1.1 {status} X\r\ncontent-type: application/json"
                     f"\r\ncontent-length: {len(data)}\r\n\r\n").encode() + data
                )
                await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    def _status_of(resp: dict, ok: int = 200) -> int:
        if isinstance(resp, dict) and "error" in resp:
            msg = str(resp["error"])
            if "no such index" in msg or "not found" in msg.lower():
                return 404
            return 500
        return ok

    async def _route(self, method: str, path: str, query: dict,
                     raw: bytes) -> tuple[int, Any]:
        node = self.node
        body = None
        if raw:
            if path.rstrip("/").rsplit("/", 1)[-1] == "_bulk":
                body = [json.loads(ln) for ln in raw.split(b"\n") if ln.strip()]
            else:
                try:
                    body = json.loads(raw)
                except json.JSONDecodeError as e:
                    return 400, {"error": {"type": "parse_exception",
                                           "reason": str(e)}, "status": 400}

        try:
            # -- cluster APIs --
            if path == "/_cluster/health":
                return 200, node.cluster_health()
            if path == "/_cluster/state":
                return 200, node.applied_state.to_dict()
            if path in ("/", ""):
                return 200, {"name": node.node_id,
                             "cluster_name": "opensearch-tpu",
                             "leader": node.coordinator.leader_id}

            # -- bulk --
            if path.rstrip("/").endswith("_bulk"):
                default_index = None
                m = re.fullmatch(r"/([^/_][^/]*)/_bulk/?", path)
                if m:
                    default_index = m.group(1)
                ops = _parse_bulk_ndjson(body or [], default_index)
                resp = await self._await(self._call(node.bulk, ops))
                if query.get("refresh") == "true":
                    touched = {
                        o[1]["_index"] for o in ops if o[1].get("_index")
                    }
                    for idx in touched:
                        await self._await(self._call(node.refresh, idx))
                return self._status_of(resp), resp

            # -- index-level --
            m = re.fullmatch(r"/([^/_][^/]*)/?", path)
            if m:
                name = m.group(1)
                if method == "PUT":
                    resp = await self._await(
                        self._call(node.create_index, name, body)
                    )
                    await self._wait_for_active_shards(name)
                    return self._status_of(resp), resp
                if method == "DELETE":
                    resp = await self._await(self._call(node.delete_index, name))
                    return self._status_of(resp), resp

            m = re.fullmatch(r"/([^/]+)/_mapping/?", path)
            if m and method == "PUT":
                resp = await self._await(
                    self._call(node.put_mapping, m.group(1), body or {})
                )
                return self._status_of(resp), resp

            m = re.fullmatch(r"/([^/]+)/_refresh/?", path)
            if m:
                resp = await self._await(self._call(node.refresh, m.group(1)))
                return self._status_of(resp), resp

            m = re.fullmatch(r"/([^/]+)/_search/?", path)
            if m:
                resp = await self._await(
                    self._call(node.search, m.group(1), body)
                )
                return self._status_of(resp), resp

            # -- documents --
            m = re.fullmatch(r"/([^/]+)/_doc/([^/]+)/?", path)
            if m:
                index, doc_id = m.group(1), m.group(2)
                routing = query.get("routing")
                if method in ("PUT", "POST"):
                    resp = await self._await(self._call(
                        node.index_doc, index, doc_id, body, routing=routing
                    ))
                    if query.get("refresh") == "true":
                        await self._await(self._call(node.refresh, index))
                    return self._status_of(resp, 201), resp
                if method == "GET":
                    resp = await self._await(self._call(
                        node.get_doc, index, doc_id, routing=routing
                    ))
                    if resp.get("found") is False:
                        return 404, resp
                    return self._status_of(resp), resp
                if method == "DELETE":
                    resp = await self._await(self._call(
                        node.delete_doc, index, doc_id, routing=routing
                    ))
                    return self._status_of(resp), resp

            return 400, {"error": {"type": "illegal_argument_exception",
                                   "reason": f"no route for {method} {path}"},
                         "status": 400}
        except Exception as e:  # noqa: BLE001 - top-level 500 guard
            return 500, {"error": {"type": "exception", "reason": str(e)},
                         "status": 500}

    async def _wait_for_active_shards(self, index: str,
                                      timeout_s: float = 10.0) -> None:
        """Block the create-index response until primaries are STARTED
        (the reference's wait_for_active_shards=1 default)."""
        deadline = self.loop.time() + timeout_s
        while self.loop.time() < deadline:
            state = self.node.applied_state
            entries = [r for r in state.routing
                       if r.index == index and r.primary]
            if entries and all(r.state == "STARTED" for r in entries):
                return
            await asyncio.sleep(0.05)


def _parse_bulk_ndjson(lines: list[dict], default_index: str | None
                       ) -> list[tuple[str, dict, dict | None]]:
    ops: list[tuple[str, dict, dict | None]] = []
    i = 0
    while i < len(lines):
        action_line = lines[i]
        action, meta = next(iter(action_line.items()))
        meta = dict(meta or {})
        if default_index and not meta.get("_index"):
            meta["_index"] = default_index
        i += 1
        source = None
        if action in ("index", "create", "update"):
            source = lines[i]
            i += 1
        ops.append((action, meta, source))
    return ops


async def amain(args: argparse.Namespace) -> None:
    seeds = parse_seeds(args.seeds)
    server = ClusterServer(
        args.node_id, args.data, args.host,
        seeds[args.node_id][1], args.http_port, seeds,
        loop=asyncio.get_running_loop(),
    )
    bootstrap = args.bootstrap.split(",") if args.bootstrap else None
    await server.start(bootstrap=bootstrap)
    print(f"[{args.node_id}] transport {seeds[args.node_id]} "
          f"http 127.0.0.1:{args.http_port}", flush=True)
    await asyncio.Event().wait()  # run forever


def main() -> None:
    parser = argparse.ArgumentParser(description="opensearch-tpu cluster node")
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--http-port", type=int, required=True)
    parser.add_argument("--data", required=True)
    parser.add_argument("--seeds", required=True,
                        help="n1=127.0.0.1:9301,n2=127.0.0.1:9302,...")
    parser.add_argument("--bootstrap", default=None,
                        help="comma-separated voting node ids (first boot)")
    args = parser.parse_args()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
