"""Rank evaluation: offline relevance metrics over templated searches.

The analog of modules/rank-eval (SURVEY.md §2.3: P@k, MRR, DCG, expected
reciprocal rank over rated documents). Pure coordinator-side compute: run
each request through the normal search path, score the ranked hits against
the provided ratings.
"""

from __future__ import annotations

import math
from typing import Any

from opensearch_tpu.common.errors import IllegalArgumentException

DEFAULT_K = 10


def _ratings_map(ratings: list[dict]) -> dict[tuple[str, str], int]:
    out = {}
    for r in ratings or []:
        out[(r.get("_index", ""), str(r["_id"]))] = int(r.get("rating", 0))
    return out


def _precision_at_k(hits, rated, k, relevant_threshold=1):
    top = hits[:k]
    if not top:
        return 0.0
    relevant = sum(
        1 for h in top
        if rated.get((h["_index"], h["_id"]), 0) >= relevant_threshold
    )
    return relevant / len(top)


def _recall_at_k(hits, rated, k, relevant_threshold=1):
    total_relevant = sum(1 for v in rated.values() if v >= relevant_threshold)
    if total_relevant == 0:
        return 0.0
    top = hits[:k]
    found = sum(
        1 for h in top
        if rated.get((h["_index"], h["_id"]), 0) >= relevant_threshold
    )
    return found / total_relevant


def _mrr(hits, rated, k, relevant_threshold=1):
    for i, h in enumerate(hits[:k]):
        if rated.get((h["_index"], h["_id"]), 0) >= relevant_threshold:
            return 1.0 / (i + 1)
    return 0.0


def _dcg(hits, rated, k, normalize=False):
    def gain(rating, pos):
        return (2 ** rating - 1) / math.log2(pos + 2)

    dcg = sum(
        gain(rated.get((h["_index"], h["_id"]), 0), i)
        for i, h in enumerate(hits[:k])
    )
    if not normalize:
        return dcg
    ideal = sorted(rated.values(), reverse=True)[:k]
    idcg = sum(gain(r, i) for i, r in enumerate(ideal))
    return dcg / idcg if idcg > 0 else 0.0


def _err(hits, rated, k, max_rating=3):
    """Expected reciprocal rank (cascade model)."""
    err = 0.0
    p_continue = 1.0
    for i, h in enumerate(hits[:k]):
        rating = rated.get((h["_index"], h["_id"]), 0)
        r = (2 ** rating - 1) / (2 ** max_rating)
        err += p_continue * r / (i + 1)
        p_continue *= 1.0 - r
    return err


def rank_eval(node, index: str | None, body: dict) -> dict:
    body = body or {}
    requests = body.get("requests")
    if not isinstance(requests, list) or not requests:
        raise IllegalArgumentException("[rank_eval] requires [requests]")
    metric_conf = body.get("metric") or {"precision": {}}
    if len(metric_conf) != 1:
        raise IllegalArgumentException("[rank_eval] requires exactly one metric")
    metric_name, mconf = next(iter(metric_conf.items()))
    mconf = mconf or {}
    k = int(mconf.get("k", DEFAULT_K))

    details: dict[str, Any] = {}
    scores: list[float] = []
    failures: dict[str, Any] = {}
    for i, req in enumerate(requests):
        rid = str(req.get("id", i))
        rated = _ratings_map(req.get("ratings"))
        try:
            search_body = dict(req.get("request") or {})
            search_body.setdefault("size", max(k, DEFAULT_K))
            resp = node.search(index, search_body)
        except Exception as e:  # per-request failures reported, not fatal
            failures[rid] = {"error": str(e)}
            continue
        hits = resp["hits"]["hits"]
        if metric_name == "precision":
            score = _precision_at_k(
                hits, rated, k, int(mconf.get("relevant_rating_threshold", 1))
            )
        elif metric_name == "recall":
            score = _recall_at_k(
                hits, rated, k, int(mconf.get("relevant_rating_threshold", 1))
            )
        elif metric_name == "mean_reciprocal_rank":
            score = _mrr(
                hits, rated, k, int(mconf.get("relevant_rating_threshold", 1))
            )
        elif metric_name == "dcg":
            score = _dcg(hits, rated, k, bool(mconf.get("normalize", False)))
        elif metric_name == "expected_reciprocal_rank":
            score = _err(hits, rated, k, int(mconf.get("maximum_relevance", 3)))
        else:
            raise IllegalArgumentException(
                f"unknown rank-eval metric [{metric_name}]"
            )
        scores.append(score)
        unrated = [
            {"_index": h["_index"], "_id": h["_id"]}
            for h in hits[:k]
            if (h["_index"], h["_id"]) not in rated
        ]
        details[rid] = {
            "metric_score": score,
            "unrated_docs": unrated,
            "hits": [
                {
                    "hit": {"_index": h["_index"], "_id": h["_id"],
                            "_score": h.get("_score")},
                    "rating": rated.get((h["_index"], h["_id"])),
                }
                for h in hits[:k]
            ],
        }
    return {
        "metric_score": sum(scores) / len(scores) if scores else 0.0,
        "details": details,
        "failures": failures,
    }
