"""Coordinator-side reduce of per-node search partials.

The cluster analog of the reference's reduce pipeline
(action/search/SearchPhaseController.java:224 mergeTopDocs +
search/aggregations/InternalAggregations.java:162 reduce): each data node
runs search/service.search(partial=True) over its local shards and returns
a JSON partial — hits annotated with a [shard, segment, doc] tie-break
triple, aggregations decorated with `_p_*` reduce extras (sum+count for
avg, raw value lists for cardinality/percentiles, full counts for
rare_terms). This module merges those partials into the final response:
k-way hit merge with the OpenSearch tie-break (score desc / sort values,
then shard asc, segment asc, doc asc), type-directed aggregation reduce
driven by the REQUEST body (the coordinator knows every agg's type), then
pipeline aggregations once over the reduced tree.

Aggregation types whose final JSON is not losslessly mergeable and that
carry no partial decoration yet (composite, sampler, significant_terms,
scripted_metric, matrix_stats, auto_date_histogram, top_hits) raise a
clear unsupported error in cluster mode rather than returning wrong
numbers.
"""

from __future__ import annotations

import heapq
import math
from typing import Any

import numpy as np

from opensearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
)

# agg types the cross-node reduce handles exactly
_BUCKET_MERGE = {
    "terms", "multi_terms", "histogram", "date_histogram", "range",
    "date_range", "filters", "adjacency_matrix",
}
_SINGLE_BUCKET = {"filter", "missing", "global"}
_PASSTHROUGH_METRICS = {"min", "max", "sum", "value_count", "stats",
                        "extended_stats"}
_DECORATED_METRICS = {"avg", "cardinality", "percentiles",
                      "percentile_ranks", "median_absolute_deviation",
                      "weighted_avg"}
_SPECIAL = {"rare_terms"}
UNSUPPORTED_CLUSTER_AGGS = {
    "composite", "sampler", "diversified_sampler", "significant_terms",
    "scripted_metric", "matrix_stats", "auto_date_histogram", "top_hits",
    "geo_distance", "nested", "reverse_nested",
}


def check_cluster_aggs_supported(aggs_body: dict | None) -> None:
    """Raise early (before any fan-out) for agg types the cross-node
    reduce cannot merge exactly."""
    if not aggs_body:
        return
    for name, body in aggs_body.items():
        for key, val in body.items():
            if key in ("aggs", "aggregations"):
                check_cluster_aggs_supported(val)
            elif key in UNSUPPORTED_CLUSTER_AGGS:
                raise IllegalArgumentException(
                    f"aggregation type [{key}] (in [{name}]) is not yet "
                    f"supported for cross-node reduce in cluster mode"
                )


def _agg_type_of(body: dict) -> tuple[str, dict, dict | None]:
    from opensearch_tpu.search.aggs import AGG_TYPES, EXTENSION_AGGS

    sub = body.get("aggs") or body.get("aggregations")
    keys = [k for k in body if k in AGG_TYPES or k in EXTENSION_AGGS]
    if len(keys) != 1:
        raise ParsingException(
            f"aggregation must have exactly one known type, got {sorted(body)}"
        )
    return keys[0], body[keys[0]], sub


# --------------------------------------------------------------------- #
# hits
# --------------------------------------------------------------------- #


def reduce_hits(
    partials: list[dict],
    *,
    size: int,
    from_: int,
    sort: list | None,
    track_total: Any,
    collapse_field: str | None = None,
) -> dict:
    """Merge per-node hit lists. Each partial is a full search response
    whose hits carry `_tb` = [shard, segment, doc]. With `collapse_field`,
    per-node collapsed hits are re-collapsed across nodes (first-per-group
    survives both levels).

    Partials flagged `_premerged` (the shard-mesh launch already produced
    them in the canonical (-score, _tb) order — search/service.py) are
    k-way STREAM-merged with a heap instead of globally re-sorted: the
    launch did the per-node merge on device, so the coordinator only
    interleaves S sorted streams."""
    from opensearch_tpu.search.service import _values_key

    streams: list[list[tuple[Any, dict]]] = []
    total = 0
    max_score = None
    all_premerged = bool(partials) and not sort
    for p in partials:
        h = p.get("hits") or {}
        t = h.get("total")
        if isinstance(t, dict):
            total += int(t.get("value", 0))
        ms = h.get("max_score")
        if ms is not None and (max_score is None or ms > max_score):
            max_score = ms
        if not p.get("_premerged"):
            all_premerged = False
        stream: list[tuple[Any, dict]] = []
        for hit in h.get("hits") or []:
            tb = tuple(hit.get("_tb") or [0, 0, 0])
            if sort:
                key = (_values_key(sort, hit.get("sort") or []), *tb)
            else:
                score = hit.get("_score") or 0.0
                key = (-score, *tb)
            stream.append((key, hit))
        streams.append(stream)
    if all_premerged:
        rows = list(heapq.merge(*streams, key=lambda r: r[0]))
    else:
        rows = [r for stream in streams for r in stream]
        rows.sort(key=lambda r: r[0])
    if collapse_field is not None:
        seen: set = set()
        deduped = []
        for key, hit in rows:
            value = (hit.get("fields") or {}).get(collapse_field, [None])[0]
            if value is not None:
                hv = tuple(value) if isinstance(value, list) else value
                if hv in seen:
                    continue
                seen.add(hv)
            deduped.append((key, hit))
        rows = deduped
    page = []
    for _key, hit in rows[from_: from_ + size]:
        hit = dict(hit)
        hit.pop("_tb", None)
        page.append(hit)

    hits_obj: dict[str, Any] = {
        "max_score": max_score if not sort else None,
        "hits": page,
    }
    if track_total is True:
        hits_obj["total"] = {"value": total, "relation": "eq"}
    elif track_total is not False:
        cap = int(track_total)
        hits_obj["total"] = (
            {"value": cap, "relation": "gte"} if total > cap
            else {"value": total, "relation": "eq"}
        )
    return hits_obj


# --------------------------------------------------------------------- #
# aggregations
# --------------------------------------------------------------------- #


def reduce_aggs(aggs_body: dict, partials: list[dict]) -> dict:
    """Reduce per-node aggregation partials (each the `aggregations` object
    of one node's partial response) into the final tree, then apply
    pipeline aggregations."""
    from opensearch_tpu.search.aggs_pipeline import (
        PIPELINE_TYPES,
        apply_pipeline_aggs,
    )

    out: dict[str, Any] = {}
    for name, body in aggs_body.items():
        if any(k in PIPELINE_TYPES for k in body):
            continue
        parts = [p[name] for p in partials if name in p]
        out[name] = _reduce_one(body, parts)
    apply_pipeline_aggs(aggs_body, out)
    return out


def _reduce_one(body: dict, parts: list[dict]) -> dict:
    typ, conf, sub = _agg_type_of(body)
    if not parts:
        return _empty_result(typ, conf, sub)
    if typ in _PASSTHROUGH_METRICS:
        return _reduce_metric(typ, conf, parts)
    if typ in _DECORATED_METRICS:
        return _reduce_decorated(typ, conf, parts)
    if typ in _SINGLE_BUCKET:
        merged = {"doc_count": sum(int(p.get("doc_count", 0)) for p in parts)}
        if sub:
            merged.update(_reduce_sub(sub, parts))
        return merged
    if typ in _BUCKET_MERGE:
        return _reduce_buckets(typ, conf, sub, parts)
    if typ == "rare_terms":
        return _reduce_rare_terms(conf, sub, parts)
    raise IllegalArgumentException(
        f"aggregation type [{typ}] is not yet supported for cross-node "
        f"reduce in cluster mode"
    )


def _empty_result(typ: str, conf: dict, sub: dict | None) -> dict:
    """Canonical zero-doc shapes (what the single-node path returns over an
    empty mask) — used for reduce-side gap-filled buckets."""
    if typ in ("min", "max", "avg", "weighted_avg",
               "median_absolute_deviation", "cardinality"):
        return {"value": 0 if typ == "cardinality" else None}
    if typ == "sum":
        return {"value": 0.0}
    if typ == "value_count":
        return {"value": 0}
    if typ == "stats":
        return {"count": 0, "min": None, "max": None, "avg": None,
                "sum": 0.0}
    if typ in _BUCKET_MERGE or typ == "rare_terms":
        out: dict[str, Any] = {"buckets": []}
        if typ in ("terms", "multi_terms"):
            out = {"doc_count_error_upper_bound": 0,
                   "sum_other_doc_count": 0, "buckets": []}
        return out
    if typ in _SINGLE_BUCKET:
        merged: dict[str, Any] = {"doc_count": 0}
        if sub:
            merged.update(_reduce_sub(sub, []))
        return merged
    return {}


def _reduce_sub(sub: dict, bucket_parts: list[dict]) -> dict:
    """Reduce the sub-aggregations embedded in same-key buckets."""
    out: dict[str, Any] = {}
    from opensearch_tpu.search.aggs_pipeline import PIPELINE_TYPES

    for name, body in sub.items():
        if any(k in PIPELINE_TYPES for k in body):
            continue
        parts = [b[name] for b in bucket_parts if name in b]
        out[name] = _reduce_one(body, parts)
    return out


def _reduce_metric(typ: str, conf: dict, parts: list[dict]) -> dict:
    if typ == "value_count":
        return {"value": sum(int(p.get("value", 0)) for p in parts)}
    if typ in ("min", "max"):
        vals = [p.get("value") for p in parts if p.get("value") is not None]
        if not vals:
            return {"value": None}
        return {"value": (min if typ == "min" else max)(vals)}
    if typ == "sum":
        return {"value": float(sum(p.get("value") or 0.0 for p in parts))}
    if typ == "stats":
        count = sum(int(p.get("count", 0)) for p in parts)
        if count == 0:
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0}
        mins = [p["min"] for p in parts if p.get("min") is not None]
        maxs = [p["max"] for p in parts if p.get("max") is not None]
        s = float(sum(p.get("sum") or 0.0 for p in parts))
        return {"count": count, "min": min(mins), "max": max(maxs),
                "avg": s / count, "sum": s}
    # extended_stats: recompute the variance family from merged moments
    count = sum(int(p.get("count", 0)) for p in parts)
    sigma = float(conf.get("sigma", 2.0))
    if count == 0:
        return next(p for p in parts)  # the canonical empty shape
    mins = [p["min"] for p in parts if p.get("min") is not None]
    maxs = [p["max"] for p in parts if p.get("max") is not None]
    s = float(sum(p.get("sum") or 0.0 for p in parts))
    sos = float(sum(p.get("sum_of_squares") or 0.0 for p in parts))
    avg = s / count
    var_pop = max(sos / count - avg * avg, 0.0)
    var_samp = var_pop * count / (count - 1) if count > 1 else float("nan")
    std_pop = math.sqrt(var_pop)
    std_samp = math.sqrt(var_samp) if count > 1 else float("nan")

    def _clean(x):
        return None if isinstance(x, float) and math.isnan(x) else x

    return {
        "count": count, "min": min(mins), "max": max(maxs), "avg": avg,
        "sum": s, "sum_of_squares": sos,
        "variance": var_pop, "variance_population": var_pop,
        "variance_sampling": _clean(var_samp),
        "std_deviation": std_pop, "std_deviation_population": std_pop,
        "std_deviation_sampling": _clean(std_samp),
        "std_deviation_bounds": {
            "upper": avg + sigma * std_pop,
            "lower": avg - sigma * std_pop,
            "upper_population": avg + sigma * std_pop,
            "lower_population": avg - sigma * std_pop,
            "upper_sampling": (
                _clean(avg + sigma * std_samp) if count > 1 else None
            ),
            "lower_sampling": (
                _clean(avg - sigma * std_samp) if count > 1 else None
            ),
        },
    }


def _reduce_decorated(typ: str, conf: dict, parts: list[dict]) -> dict:
    if typ == "avg":
        n = sum(int(p.get("_p_count", 0)) for p in parts)
        s = float(sum(p.get("_p_sum", 0.0) or 0.0 for p in parts))
        return {"value": s / n if n else None}
    if typ == "cardinality":
        seen: set = set()
        for p in parts:
            seen.update(tuple(v) if isinstance(v, list) else v
                        for v in p.get("_p_values", []))
        return {"value": len(seen)}
    if typ == "weighted_avg":
        num = float(sum(p.get("_p_num", 0.0) or 0.0 for p in parts))
        den = float(sum(p.get("_p_den", 0.0) or 0.0 for p in parts))
        return {"value": num / den if den else None}
    # value-shipping metrics: recompute over the concatenated values with
    # the exact same formulas the single-node path uses
    vals = np.asarray(
        [v for p in parts for v in p.get("_p_values", [])], np.float64
    )
    keyed = bool(conf.get("keyed", True))
    if typ == "percentiles":
        from opensearch_tpu.search.aggs_ext import _DEFAULT_PERCENTS

        percents = [float(x) for x in conf.get("percents", _DEFAULT_PERCENTS)]
        if len(vals) == 0:
            results = [(p, None) for p in percents]
        else:
            qs = np.percentile(vals, percents)
            results = [(p, float(q)) for p, q in zip(percents, qs)]
        if keyed:
            return {"values": {str(float(p)): v for p, v in results}}
        return {"values": [{"key": p, "value": v} for p, v in results]}
    if typ == "percentile_ranks":
        targets = [float(x) for x in conf["values"]]
        n = len(vals)
        results = [
            (t, float((vals <= t).sum()) * 100.0 / n if n else None)
            for t in targets
        ]
        if keyed:
            return {"values": {f"{t}": r for t, r in results}}
        return {"values": [{"key": t, "value": r} for t, r in results]}
    # median_absolute_deviation
    if len(vals) == 0:
        return {"value": None}
    med = float(np.median(vals))
    return {"value": float(np.median(np.abs(vals - med)))}


def _bucket_key(typ: str, bucket: dict) -> Any:
    key = bucket.get("key")
    return tuple(key) if isinstance(key, list) else key


def _reduce_buckets(typ: str, conf: dict, sub: dict | None,
                    parts: list[dict]) -> dict:
    # keyed filters/range come back as {"buckets": {name: bucket}}
    keyed_out = all(isinstance(p.get("buckets"), dict) for p in parts)
    merged: dict[Any, list[dict]] = {}
    order_seen: list[Any] = []
    for p in parts:
        buckets = p.get("buckets")
        items = buckets.items() if isinstance(buckets, dict) else [
            (_bucket_key(typ, b), b) for b in (buckets or [])
        ]
        for key, b in items:
            if key not in merged:
                merged[key] = []
                order_seen.append(key)
            merged[key].append(b)

    out_buckets = []
    for key in order_seen:
        group = merged[key]
        nb: dict[str, Any] = {}
        # carry key fields from the first occurrence (key/key_as_string/
        # from/to for ranges)
        for field in ("key", "key_as_string", "from", "from_as_string",
                      "to", "to_as_string"):
            if field in group[0]:
                nb[field] = group[0][field]
        nb["doc_count"] = sum(int(b.get("doc_count", 0)) for b in group)
        if sub:
            nb.update(_reduce_sub(sub, group))
        out_buckets.append((key, nb))

    if typ in ("terms", "multi_terms"):
        size = int(conf.get("size", 10))
        order_conf = conf.get("order", {"_count": "desc"})
        out_buckets = _sort_term_buckets(out_buckets, order_conf)
        total_count = sum(b["doc_count"] for _, b in out_buckets)
        prior_other = sum(
            int(p.get("sum_other_doc_count", 0)) for p in parts
        )
        top = out_buckets[:size]
        other = prior_other + sum(
            b["doc_count"] for _, b in out_buckets[size:]
        )
        return {
            "doc_count_error_upper_bound": 0,
            "sum_other_doc_count": other,
            "buckets": [b for _, b in top],
        }
    if typ in ("histogram", "date_histogram"):
        out_buckets.sort(key=lambda kb: kb[0])
        out_buckets = _gap_fill_histogram(typ, conf, sub, out_buckets)
    if keyed_out:
        return {"buckets": {k: b for k, b in out_buckets}}
    return {"buckets": [b for _, b in out_buckets]}


def _gap_fill_histogram(typ: str, conf: dict, sub: dict | None,
                        out_buckets: list[tuple[Any, dict]]):
    """min_doc_count=0 must yield a CONTIGUOUS key range after the
    cross-node merge — each node only gap-fills its local [min, max]
    (InternalHistogram.addEmptyBuckets runs at reduce time in the
    reference, so this is exactly where it belongs)."""
    date = typ == "date_histogram"
    min_doc_count = int(conf.get("min_doc_count", 0 if date else 1))
    if min_doc_count != 0 or len(out_buckets) < 2:
        return out_buckets
    from opensearch_tpu.search.aggs import _CALENDAR_UNITS, _calendar_next
    from opensearch_tpu.common.settings import parse_time_millis

    if date:
        interval_conf = (
            conf.get("fixed_interval") or conf.get("calendar_interval")
            or conf.get("interval")
        )
        calendar = (str(interval_conf) in _CALENDAR_UNITS
                    or conf.get("calendar_interval") in _CALENDAR_UNITS)
        step = None if calendar else float(parse_time_millis(interval_conf))
    else:
        calendar = False
        step = float(conf["interval"])

    def next_key(k: float) -> float:
        if calendar:
            return _calendar_next(k, str(interval_conf))
        return k + step

    def fmt(k: float) -> dict:
        import datetime as _dt

        b: dict[str, Any] = {"key": int(k) if date else k, "doc_count": 0}
        if date:
            b["key_as_string"] = (
                _dt.datetime.fromtimestamp(k / 1000, _dt.timezone.utc)
                .isoformat().replace("+00:00", "Z")
            )
        if sub:
            b.update(_reduce_sub(sub, []))
        return b

    filled: list[tuple[Any, dict]] = []
    present = {k for k, _ in out_buckets}
    for i, (key, bucket) in enumerate(out_buckets):
        filled.append((key, bucket))
        if i + 1 < len(out_buckets):
            k = next_key(float(key))
            guard = 0
            while k < float(out_buckets[i + 1][0]) - 1e-9:
                if k not in present:
                    filled.append((k, fmt(k)))
                k = next_key(k)
                guard += 1
                if guard > 65_536:
                    break
    return filled


def _sort_term_buckets(out_buckets: list[tuple[Any, dict]],
                       order_conf: Any) -> list[tuple[Any, dict]]:
    from opensearch_tpu.search.aggs import _KeyOrd

    if isinstance(order_conf, dict):
        order_specs = list(order_conf.items())
    elif isinstance(order_conf, list):
        order_specs = [next(iter(o.items())) for o in order_conf]
    else:
        raise ParsingException(f"invalid terms order [{order_conf!r}]")

    def path_value(bucket: dict, path: str) -> Any:
        name, _, prop = path.partition(".")
        result = bucket.get(name)
        if result is None:
            raise ParsingException(
                f"terms order references unknown agg [{path}]"
            )
        v = result.get(prop or "value")
        return v if v is not None else float("-inf")

    def sort_key(kb):
        key, bucket = kb
        parts = []
        for okey, odir in order_specs:
            desc = odir == "desc"
            if okey == "_count":
                parts.append(-bucket["doc_count"] if desc
                             else bucket["doc_count"])
            elif okey == "_key":
                parts.append(_KeyOrd(key, desc))
            else:
                v = path_value(bucket, okey)
                parts.append(-v if desc else v)
        parts.append(_KeyOrd(key, False))
        return tuple(parts)

    return sorted(out_buckets, key=sort_key)


def _reduce_rare_terms(conf: dict, sub: dict | None,
                       parts: list[dict]) -> dict:
    max_doc_count = int(conf.get("max_doc_count", 1))
    counts: dict[Any, int] = {}
    for p in parts:
        for key, c in p.get("_p_counts", []):
            k = tuple(key) if isinstance(key, list) else key
            counts[k] = counts.get(k, 0) + int(c)
    rare_keys = [(k, c) for k, c in counts.items() if c <= max_doc_count]
    rare_keys.sort(key=lambda kv: (kv[1], str(kv[0])))
    # collect the partial buckets (with sub-aggs) for surviving keys
    by_key: dict[Any, list[dict]] = {}
    for p in parts:
        for b in p.get("buckets", []):
            k = _bucket_key("rare_terms", b)
            by_key.setdefault(k, []).append(b)
    buckets = []
    for key, count in rare_keys:
        nb: dict[str, Any] = {"key": key, "doc_count": count}
        group = by_key.get(key, [])
        if sub and group:
            nb.update(_reduce_sub(sub, group))
        buckets.append(nb)
    return {"buckets": buckets}


# --------------------------------------------------------------------- #
# full response
# --------------------------------------------------------------------- #


def reduce_search_responses(
    body: dict,
    partials: list[dict],
    *,
    size: int,
    from_: int,
    track_total: Any,
) -> dict:
    """Merge per-node partial responses into the final SearchResponse."""
    sort = body.get("sort")
    if isinstance(sort, (str, dict)):
        sort = [sort]
    took = max((p.get("took", 0) for p in partials), default=0)
    shards_total = sum(
        (p.get("_shards") or {}).get("total", 0) for p in partials
    )
    shards_ok = sum(
        (p.get("_shards") or {}).get("successful", 0) for p in partials
    )
    out: dict[str, Any] = {
        "took": took,
        "timed_out": any(p.get("timed_out") for p in partials),
        "_shards": {
            "total": shards_total,
            "successful": shards_ok,
            "skipped": sum(
                (p.get("_shards") or {}).get("skipped", 0) for p in partials
            ),
            "failed": shards_total - shards_ok,
        },
        "hits": reduce_hits(
            partials, size=size, from_=from_, sort=sort,
            track_total=track_total,
            collapse_field=(body.get("collapse") or {}).get("field"),
        ),
    }
    aggs_body = body.get("aggs") or body.get("aggregations")
    if aggs_body:
        out["aggregations"] = reduce_aggs(
            aggs_body, [p.get("aggregations") or {} for p in partials]
        )
    if any("profile" in p for p in partials):
        out["profile"] = {"shards": [
            s for p in partials for s in (p.get("profile") or {}).get("shards", [])
        ]}
    return out
