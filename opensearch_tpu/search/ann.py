"""Live-tunable ANN serving configuration (the ANNS-AMP knobs + the
kernel selection policy).

The kNN serving paths (executor.shard_knn_selection's ANN and exact
branches) read five dynamic settings on every dispatch:

  search.knn.ann.adc_precision       "fp32" | "bf16" | "int8"
  search.knn.ann.rescore_multiplier  exact-rescore pool = multiplier * k
  search.knn.ann.kernel              "auto" | "pallas" | "xla"
  search.knn.kernel                  "auto" | "pallas" | "xla" (EXACT path)
  search.knn.score_precision         "fp32" | "bf16" | "int8" (EXACT scan)

``search.knn.kernel`` extends the ANN policy's auto/pallas/xla shape to
the EXACT path (ISSUE 19): "pallas" serves the fused blockwise exact-kNN
kernel (ops/pallas_knn.knn_fused_auto — running top-R pool in VMEM, only
[B, R] winners to HBM) instead of the materializing / streaming XLA
lowerings; ``search.knn.score_precision`` picks the fused SCAN's matmul
width (reduced precisions widen the pool and exact-rescore in fp32, so
returned scores stay in the serving score space). Both values ride the
batch key, so a live flip never re-ranks an in-flight batch.

Reduced-precision ADC (ops/ivfpq.search) only ranks CANDIDATES; the fused
program always ends in an exact fp32 rescore over the widened pool, so
recall recovers while the ADC scan sheds bytes (ANNS-AMP, PAPERS.md). All
three values ride the batch key: flipping a knob mid-stream starts new
batches under the new configuration and can never re-rank (or re-route)
an in-flight one.

``kernel`` selects the ADC scan implementation (:func:`resolve_kernel`):
"xla" is the monolithic ops/ivfpq.search lowering; "pallas" is the fused
blockwise scan (ops/pallas_adc) behind the FusionANNS-style host/device
cooperative split — host coarse quantization + probe selection, one
batched device scan — running interpret-mode off-TPU (the parity path,
mirroring ``knn_*_auto``; NOT a speed path on the CPU sim). "auto"
resolves to "pallas" on a TPU backend and "xla" elsewhere, so the CPU sim
keeps the fast lowering unless a test/soak forces the kernel.

The config object is PROCESS-wide for the same reason the kNN dispatch
batcher is (search/batcher.py `default_batcher`): the executor's dispatch
sites are module-level code with no node handle, and one process serves
one device. TpuNode / ClusterNode apply dynamic settings into it with the
same guarded adapter shape as the batch settings, so a sibling in-process
node's unrelated update can never clobber live configuration.

``bucket_nprobe`` is the serving tier's nprobe shape policy: nprobe is a
static jit argument, so raw per-request values would compile one fused
program per distinct nprobe. Bucketing to the next power of two (clamped
to nlist) keeps the program cache warm; extra probes only ever ADD recall.
"""

from __future__ import annotations

from opensearch_tpu.common.settings import Property, Setting


def _validate_precision(v: str) -> None:
    # single source of truth for the precision set is the kernel module
    # (ops/ivfpq.ADC_PRECISIONS — the dtypes the fused search compiles
    # for); imported lazily so settings registration stays jax-free
    from opensearch_tpu.ops.ivfpq import ADC_PRECISIONS

    if v not in ADC_PRECISIONS:
        raise ValueError(
            f"unknown [search.knn.ann.adc_precision] value [{v}] "
            f"(choose from {list(ADC_PRECISIONS)})"
        )


# ADC kernel selection policies the serving tier accepts ("auto" resolves
# per platform at dispatch time; see resolve_kernel)
ANN_KERNELS = ("auto", "pallas", "xla")


def _validate_kernel(v: str) -> None:
    if v not in ANN_KERNELS:
        raise ValueError(
            f"unknown [search.knn.ann.kernel] value [{v}] "
            f"(choose from {list(ANN_KERNELS)})"
        )


ADC_PRECISION_SETTING: Setting[str] = Setting(
    "search.knn.ann.adc_precision", "fp32", str,
    Property.NODE_SCOPE, Property.DYNAMIC,
    validator=_validate_precision,
)
RESCORE_MULTIPLIER_SETTING = Setting.int_setting(
    "search.knn.ann.rescore_multiplier", 4,
    Property.NODE_SCOPE, Property.DYNAMIC, min_value=1, max_value=256,
)
KERNEL_SETTING: Setting[str] = Setting(
    "search.knn.ann.kernel", "auto", str,
    Property.NODE_SCOPE, Property.DYNAMIC,
    validator=_validate_kernel,
)


def _validate_exact_kernel(v: str) -> None:
    if v not in ANN_KERNELS:
        raise ValueError(
            f"unknown [search.knn.kernel] value [{v}] "
            f"(choose from {list(ANN_KERNELS)})"
        )


def _validate_score_precision(v: str) -> None:
    # single source of truth is the fused exact kernel module
    # (ops/pallas_knn.SCORE_PRECISIONS); lazy import keeps settings
    # registration jax-free
    from opensearch_tpu.ops.pallas_knn import SCORE_PRECISIONS

    if v not in SCORE_PRECISIONS:
        raise ValueError(
            f"unknown [search.knn.score_precision] value [{v}] "
            f"(choose from {list(SCORE_PRECISIONS)})"
        )


# the EXACT path's kernel policy (ISSUE 19): same auto/pallas/xla shape as
# the ANN policy, applied to the fused exact-kNN scan (ops/pallas_knn.
# knn_fused_auto) vs the XLA exact lowerings (fused.knn_topk / streaming)
EXACT_KERNEL_SETTING: Setting[str] = Setting(
    "search.knn.kernel", "auto", str,
    Property.NODE_SCOPE, Property.DYNAMIC,
    validator=_validate_exact_kernel,
)
SCORE_PRECISION_SETTING: Setting[str] = Setting(
    "search.knn.score_precision", "fp32", str,
    Property.NODE_SCOPE, Property.DYNAMIC,
    validator=_validate_score_precision,
)

ANN_SETTINGS = (ADC_PRECISION_SETTING, RESCORE_MULTIPLIER_SETTING,
                KERNEL_SETTING, EXACT_KERNEL_SETTING,
                SCORE_PRECISION_SETTING)


def resolve_kernel(policy: str) -> str:
    """The EFFECTIVE ADC scan for this dispatch: "pallas" or "xla". The
    resolved value (not the policy) rides the batch key — two nodes of one
    process can never disagree about what a merged batch will launch, and
    a policy flip mid-stream starts new batches instead of re-routing an
    in-flight one. "auto" keeps the XLA lowering off-TPU because
    interpret-mode Pallas is a parity tool, not a serving speed path."""
    if policy in ("pallas", "xla"):
        return policy
    import jax

    return "pallas" if jax.devices()[0].platform == "tpu" else "xla"


def bucket_nprobe(nprobe: int, nlist: int) -> int:
    """Power-of-two ceiling, clamped to [1, nlist] (nprobe is a static
    shape arg of the fused search; more probes never lose recall)."""
    nprobe = max(1, int(nprobe))
    return min(1 << (nprobe - 1).bit_length(), max(1, int(nlist)))


class AnnServingConfig:
    """Process-wide ANN serving knobs, applied live by the settings tier.

    Fields are plain atomic assignments read racily by design (the
    dynamic-settings contract, same as KnnDispatchBatcher.configure): a
    dispatch that read the old values completes under the old policy — and
    since both values are part of the batch key, never inside a batch
    formed under the new one.
    """

    def __init__(self) -> None:
        from opensearch_tpu.common.settings import Settings

        self.adc_precision: str = ADC_PRECISION_SETTING.default(
            Settings.EMPTY)
        self.rescore_multiplier: int = RESCORE_MULTIPLIER_SETTING.default(
            Settings.EMPTY)
        self.kernel: str = KERNEL_SETTING.default(Settings.EMPTY)
        self.exact_kernel: str = EXACT_KERNEL_SETTING.default(
            Settings.EMPTY)
        self.score_precision: str = SCORE_PRECISION_SETTING.default(
            Settings.EMPTY)

    def configure(self, *, adc_precision: str | None = None,
                  rescore_multiplier: int | None = None,
                  kernel: str | None = None,
                  exact_kernel: str | None = None,
                  score_precision: str | None = None) -> None:
        if adc_precision is not None:
            _validate_precision(adc_precision)
            self.adc_precision = adc_precision
        if rescore_multiplier is not None:
            self.rescore_multiplier = max(1, int(rescore_multiplier))
        if kernel is not None:
            _validate_kernel(kernel)
            self.kernel = kernel
        if exact_kernel is not None:
            _validate_exact_kernel(exact_kernel)
            self.exact_kernel = exact_kernel
        if score_precision is not None:
            _validate_score_precision(score_precision)
            self.score_precision = score_precision

    def apply_settings(self, flat: dict) -> None:
        """Pick this config's keys out of a flat effective-settings map
        (the cluster-settings update consumer; absent keys -> defaults)."""
        from opensearch_tpu.common.settings import Settings

        s = Settings.from_flat({
            st.key: flat[st.key] for st in ANN_SETTINGS if st.key in flat
        })
        self.configure(
            adc_precision=ADC_PRECISION_SETTING.get(s),
            rescore_multiplier=RESCORE_MULTIPLIER_SETTING.get(s),
            kernel=KERNEL_SETTING.get(s),
            exact_kernel=EXACT_KERNEL_SETTING.get(s),
            score_precision=SCORE_PRECISION_SETTING.get(s),
        )

    def snapshot(self) -> dict:
        out = {
            "adc_precision": self.adc_precision,
            "rescore_multiplier": self.rescore_multiplier,
            "kernel": self.kernel,
            "exact_kernel": self.exact_kernel,
            "score_precision": self.score_precision,
        }
        # index-build accounting (index/device.py): how many IVF-PQ
        # structures this process built at publish time, and their cost
        from opensearch_tpu.index.device import ann_build_stats

        out["index_builds"] = ann_build_stats()
        return out


default_config = AnnServingConfig()
