"""Live-tunable ANN serving configuration (the ANNS-AMP knob pair).

The IVF-PQ serving path (executor.shard_knn_selection's ANN branch) reads
two dynamic settings on every dispatch:

  search.knn.ann.adc_precision       "fp32" | "bf16" | "int8"
  search.knn.ann.rescore_multiplier  exact-rescore pool = multiplier * k

Reduced-precision ADC (ops/ivfpq.search) only ranks CANDIDATES; the fused
program always ends in an exact fp32 rescore over the widened pool, so
recall recovers while the ADC scan sheds bytes (ANNS-AMP, PAPERS.md). Both
values ride the batch key: flipping a knob mid-stream starts new batches
under the new configuration and can never re-rank an in-flight one.

The config object is PROCESS-wide for the same reason the kNN dispatch
batcher is (search/batcher.py `default_batcher`): the executor's dispatch
sites are module-level code with no node handle, and one process serves
one device. TpuNode / ClusterNode apply dynamic settings into it with the
same guarded adapter shape as the batch settings, so a sibling in-process
node's unrelated update can never clobber live configuration.

``bucket_nprobe`` is the serving tier's nprobe shape policy: nprobe is a
static jit argument, so raw per-request values would compile one fused
program per distinct nprobe. Bucketing to the next power of two (clamped
to nlist) keeps the program cache warm; extra probes only ever ADD recall.
"""

from __future__ import annotations

from opensearch_tpu.common.settings import Property, Setting


def _validate_precision(v: str) -> None:
    # single source of truth for the precision set is the kernel module
    # (ops/ivfpq.ADC_PRECISIONS — the dtypes the fused search compiles
    # for); imported lazily so settings registration stays jax-free
    from opensearch_tpu.ops.ivfpq import ADC_PRECISIONS

    if v not in ADC_PRECISIONS:
        raise ValueError(
            f"unknown [search.knn.ann.adc_precision] value [{v}] "
            f"(choose from {list(ADC_PRECISIONS)})"
        )


ADC_PRECISION_SETTING: Setting[str] = Setting(
    "search.knn.ann.adc_precision", "fp32", str,
    Property.NODE_SCOPE, Property.DYNAMIC,
    validator=_validate_precision,
)
RESCORE_MULTIPLIER_SETTING = Setting.int_setting(
    "search.knn.ann.rescore_multiplier", 4,
    Property.NODE_SCOPE, Property.DYNAMIC, min_value=1, max_value=256,
)

ANN_SETTINGS = (ADC_PRECISION_SETTING, RESCORE_MULTIPLIER_SETTING)


def bucket_nprobe(nprobe: int, nlist: int) -> int:
    """Power-of-two ceiling, clamped to [1, nlist] (nprobe is a static
    shape arg of the fused search; more probes never lose recall)."""
    nprobe = max(1, int(nprobe))
    return min(1 << (nprobe - 1).bit_length(), max(1, int(nlist)))


class AnnServingConfig:
    """Process-wide ANN serving knobs, applied live by the settings tier.

    Fields are plain atomic assignments read racily by design (the
    dynamic-settings contract, same as KnnDispatchBatcher.configure): a
    dispatch that read the old values completes under the old policy — and
    since both values are part of the batch key, never inside a batch
    formed under the new one.
    """

    def __init__(self) -> None:
        from opensearch_tpu.common.settings import Settings

        self.adc_precision: str = ADC_PRECISION_SETTING.default(
            Settings.EMPTY)
        self.rescore_multiplier: int = RESCORE_MULTIPLIER_SETTING.default(
            Settings.EMPTY)

    def configure(self, *, adc_precision: str | None = None,
                  rescore_multiplier: int | None = None) -> None:
        if adc_precision is not None:
            _validate_precision(adc_precision)
            self.adc_precision = adc_precision
        if rescore_multiplier is not None:
            self.rescore_multiplier = max(1, int(rescore_multiplier))

    def apply_settings(self, flat: dict) -> None:
        """Pick this config's keys out of a flat effective-settings map
        (the cluster-settings update consumer; absent keys -> defaults)."""
        from opensearch_tpu.common.settings import Settings

        s = Settings.from_flat({
            st.key: flat[st.key] for st in ANN_SETTINGS if st.key in flat
        })
        self.configure(
            adc_precision=ADC_PRECISION_SETTING.get(s),
            rescore_multiplier=RESCORE_MULTIPLIER_SETTING.get(s),
        )

    def snapshot(self) -> dict:
        out = {
            "adc_precision": self.adc_precision,
            "rescore_multiplier": self.rescore_multiplier,
        }
        # index-build accounting (index/device.py): how many IVF-PQ
        # structures this process built at publish time, and their cost
        from opensearch_tpu.index.device import ann_build_stats

        out["index_builds"] = ann_build_stats()
        return out


default_config = AnnServingConfig()
