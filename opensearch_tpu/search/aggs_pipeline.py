"""Pipeline aggregations: reduce-time transforms over bucket streams.

The analog of search/aggregations/pipeline/ in the reference (~30 types,
SURVEY.md §2.2): sibling pipelines (avg_bucket, sum_bucket, min_bucket,
max_bucket, stats_bucket, extended_stats_bucket, percentiles_bucket)
compute a metric over another multi-bucket agg's values; parent pipelines
(derivative, cumulative_sum, moving_fn/moving_avg, serial_diff,
bucket_script, bucket_selector, bucket_sort) run inside a multi-bucket agg
and transform its bucket list in place.

Like the reference, pipelines run at final coordinator reduce
(InternalAggregations.topLevelReduce → pipeline aggregators), never
shard-side: apply_pipeline_aggs(aggs_body, results) is called once after
compute_aggs.
"""

from __future__ import annotations

import math
from typing import Any

from opensearch_tpu.common.errors import IllegalArgumentException, ParsingException

PARENT_TYPES = {
    "derivative", "cumulative_sum", "moving_fn", "moving_avg", "serial_diff",
    "bucket_script", "bucket_selector", "bucket_sort",
}
SIBLING_TYPES = {
    "avg_bucket", "sum_bucket", "min_bucket", "max_bucket", "stats_bucket",
    "extended_stats_bucket", "percentiles_bucket",
}
PIPELINE_TYPES = PARENT_TYPES | SIBLING_TYPES


def validate_pipeline_aggs(aggs_body: dict, top: bool = True) -> None:
    """Request-time parameter/placement validation for pipeline aggs
    (AbstractPipelineAggregationBuilder.validate): parent pipelines cannot
    sit at the top level, and moving windows must be positive."""
    if not isinstance(aggs_body, dict):
        return
    for name, body in aggs_body.items():
        if not isinstance(body, dict):
            continue
        typ = _agg_type(body)
        if typ in PARENT_TYPES:
            conf = body.get(typ) or {}
            # parameter errors outrank placement errors (the reference
            # validates the builder before tree placement)
            if typ in ("moving_fn", "moving_avg") and \
                    int(conf.get("window", 5)) <= 0:
                raise IllegalArgumentException(
                    "[window] must be a positive, non-zero integer.")
            if top:
                raise IllegalArgumentException(
                    f"{typ} aggregation [{name}] must be declared inside "
                    f"of another aggregation")
        sub = body.get("aggs") or body.get("aggregations")
        if sub:
            validate_pipeline_aggs(sub, top=False)


def apply_pipeline_aggs(aggs_body: dict, results: dict) -> None:
    """Walk the request body and materialize pipeline aggs into `results`
    (mutated in place)."""
    if not aggs_body or not isinstance(results, dict):
        return
    # 1. recurse into sub-aggregations of concrete aggs first (inner
    #    pipelines must resolve before outer ones that may reference them)
    for name, body in aggs_body.items():
        typ = _agg_type(body)
        if typ in PIPELINE_TYPES:
            continue
        sub = body.get("aggs") or body.get("aggregations")
        target = results.get(name)
        if not sub or target is None:
            continue
        buckets = target.get("buckets")
        if isinstance(buckets, list):
            for bucket in buckets:
                apply_pipeline_aggs(sub, bucket)
            # 2. parent pipelines declared in this agg's sub level
            _apply_parent_pipelines(sub, target)
        elif isinstance(buckets, dict):  # keyed filters agg
            for bucket in buckets.values():
                apply_pipeline_aggs(sub, bucket)
        else:
            # single-bucket agg (filter/missing/global/sampler): results
            # are inlined into the agg's own dict
            apply_pipeline_aggs(sub, target)
    # 3. sibling pipelines at this level
    for name, body in aggs_body.items():
        typ = _agg_type(body)
        if typ in SIBLING_TYPES:
            results[name] = _compute_sibling(typ, body[typ], results)


def _agg_type(body: dict) -> str | None:
    for k in body:
        if k not in ("aggs", "aggregations", "meta"):
            return k
    return None


def _bucket_value(bucket: dict, path: str) -> Any:
    """Resolve "metric", "metric.prop", "agg>agg.metric", "_count" within
    one bucket (AggregationPath semantics: '>' descends into single-bucket
    sub-aggregations)."""
    if path == "_count":
        return bucket.get("doc_count")
    if path == "_key":
        return bucket.get("key")
    node: Any = bucket
    segments = path.split(">")
    for seg in segments[:-1]:
        node = node.get(seg.strip()) if isinstance(node, dict) else None
        if node is None:
            raise IllegalArgumentException(
                f"no aggregation found for path [{path}]"
            )
    name, _, prop = segments[-1].strip().partition(".")
    node = node.get(name) if isinstance(node, dict) else None
    if node is None:
        raise IllegalArgumentException(f"no aggregation found for path [{path}]")
    return node.get(prop or "value")


def _resolve_sibling_values(path: str, results: dict) -> tuple[list, list]:
    """Resolve "multi_bucket_agg>metric[.prop]" to (keys, values)."""
    segments = path.split(">")
    if len(segments) == 1 and "." in segments[0]:
        # AggregationPath also accepts "agg.metric" dotted form when the
        # head is a multi-bucket aggregation (reference: "range.v")
        head, _, tail = segments[0].partition(".")
        if isinstance(results.get(head.strip()), dict) and \
                "buckets" in results[head.strip()]:
            segments = [head, tail]
    node = results
    for seg in segments[:-1]:
        node = node.get(seg.strip()) if isinstance(node, dict) else None
        if node is None:
            raise IllegalArgumentException(f"no aggregation found for path [{path}]")
    buckets = node.get("buckets") if isinstance(node, dict) else None
    if not isinstance(buckets, list):
        raise IllegalArgumentException(
            f"buckets_path [{path}] must reference a multi-bucket aggregation"
        )
    metric = segments[-1].strip()
    keys, vals = [], []
    for b in buckets:
        keys.append(b.get("key"))
        # BucketHelpers.resolveBucketValue: an EMPTY bucket resolves to
        # NaN under the default skip gap policy (doc_count counts as a
        # value only for the _count metric)
        if metric != "_count" and b.get("doc_count") == 0:
            vals.append(None)
            continue
        vals.append(_bucket_value(b, metric))
    return keys, vals


def _skip(vals: list) -> list[float]:
    return [float(v) for v in vals if v is not None and not (
        isinstance(v, float) and math.isnan(v))]


def _compute_sibling(typ: str, conf: dict, results: dict) -> dict:
    path = conf["buckets_path"]
    keys, raw = _resolve_sibling_values(path, results)
    vals = _skip(raw)
    if typ == "avg_bucket":
        return {"value": sum(vals) / len(vals) if vals else None}
    if typ == "sum_bucket":
        return {"value": sum(vals) if vals else 0.0}
    if typ in ("min_bucket", "max_bucket"):
        if not vals:
            return {"value": None, "keys": []}
        best = min(vals) if typ == "min_bucket" else max(vals)
        best_keys = [
            _key_str(k) for k, v in zip(keys, raw)
            if v is not None and float(v) == best
        ]
        return {"value": best, "keys": best_keys}
    if typ == "stats_bucket":
        if not vals:
            return {"count": 0, "min": None, "max": None, "avg": None, "sum": 0.0}
        return {
            "count": len(vals), "min": min(vals), "max": max(vals),
            "avg": sum(vals) / len(vals), "sum": sum(vals),
        }
    if typ == "extended_stats_bucket":
        n = len(vals)
        if n == 0:
            return {"count": 0, "min": None, "max": None, "avg": None,
                    "sum": 0.0, "sum_of_squares": None, "variance": None,
                    "std_deviation": None}
        s = sum(vals)
        avg = s / n
        sos = sum(v * v for v in vals)
        var = max(sos / n - avg * avg, 0.0)
        sigma = float(conf.get("sigma", 2.0))
        std = math.sqrt(var)
        return {
            "count": n, "min": min(vals), "max": max(vals), "avg": avg,
            "sum": s, "sum_of_squares": sos, "variance": var,
            "std_deviation": std,
            "std_deviation_bounds": {"upper": avg + sigma * std,
                                     "lower": avg - sigma * std},
        }
    if typ == "percentiles_bucket":
        percents = [float(p) for p in conf.get("percents", [1, 5, 25, 50, 75, 95, 99])]
        out = {}
        sv = sorted(vals)
        for p in percents:
            if not sv:
                out[f"{p}"] = None
            else:
                idx = min(int(round((p / 100.0) * len(sv) + 0.5)) - 1, len(sv) - 1)
                out[f"{p}"] = sv[max(idx, 0)]
        return {"values": out}
    raise ParsingException(f"unknown sibling pipeline [{typ}]")


def _key_str(k: Any) -> str:
    return str(k)


def _apply_parent_pipelines(sub_body: dict, parent_result: dict) -> None:
    buckets = parent_result.get("buckets")
    if not isinstance(buckets, list):
        return
    for name, body in sub_body.items():
        typ = _agg_type(body)
        if typ not in PARENT_TYPES:
            continue
        conf = body[typ]
        if typ == "derivative":
            _derivative(name, conf, buckets)
        elif typ == "cumulative_sum":
            _cumulative_sum(name, conf, buckets)
        elif typ in ("moving_fn", "moving_avg"):
            _moving_fn(name, conf, buckets, legacy_avg=(typ == "moving_avg"))
        elif typ == "serial_diff":
            _serial_diff(name, conf, buckets)
        elif typ == "bucket_script":
            _bucket_script(name, conf, buckets)
        elif typ == "bucket_selector":
            _bucket_selector(conf, buckets, parent_result)
        elif typ == "bucket_sort":
            _bucket_sort(conf, buckets, parent_result)


def _path_values(buckets: list, path: str) -> list:
    return [_bucket_value(b, path) for b in buckets]


def _derivative(name: str, conf: dict, buckets: list) -> None:
    path = conf["buckets_path"]
    unit_ms = None
    if conf.get("unit"):
        from opensearch_tpu.common.settings import parse_time_millis

        unit_ms = float(parse_time_millis(conf["unit"]))
    vals = _path_values(buckets, path)
    for i, b in enumerate(buckets):
        if i == 0 or vals[i] is None or vals[i - 1] is None:
            continue
        diff = float(vals[i]) - float(vals[i - 1])
        entry = {"value": diff}
        if unit_ms is not None:
            key_diff = float(buckets[i]["key"]) - float(buckets[i - 1]["key"])
            if key_diff > 0:
                entry["normalized_value"] = diff / (key_diff / unit_ms)
        b[name] = entry


def _cumulative_sum(name: str, conf: dict, buckets: list) -> None:
    path = conf["buckets_path"]
    total = 0.0
    for b in buckets:
        v = _bucket_value(b, path)
        if v is not None:
            total += float(v)
        b[name] = {"value": total}


def _serial_diff(name: str, conf: dict, buckets: list) -> None:
    path = conf["buckets_path"]
    lag = int(conf.get("lag", 1))
    vals = _path_values(buckets, path)
    for i, b in enumerate(buckets):
        if i < lag or vals[i] is None or vals[i - lag] is None:
            continue
        b[name] = {"value": float(vals[i]) - float(vals[i - lag])}


class _MovingFunctions:
    """The MovingFunctions builtin namespace for moving_fn scripts."""

    @staticmethod
    def _call(name: str, args: list):
        values = [v for v in (args[0] if args else []) if v is not None]
        if name == "max":
            return max(values) if values else None
        if name == "min":
            return min(values) if values else None
        if name == "sum":
            return sum(values) if values else 0.0
        if name == "unweightedAvg":
            return sum(values) / len(values) if values else None
        if name == "stdDev":
            if not values:
                return None
            avg = args[1] if len(args) > 1 else sum(values) / len(values)
            return math.sqrt(sum((v - avg) ** 2 for v in values) / len(values))
        if name == "linearWeightedAvg":
            if not values:
                return None
            num = sum(v * (i + 1) for i, v in enumerate(values))
            den = sum(range(1, len(values) + 1))
            return num / den
        if name == "ewma":
            if not values:
                return None
            alpha = args[1] if len(args) > 1 else 0.3
            avg = values[0]
            for v in values[1:]:
                avg = alpha * v + (1 - alpha) * avg
            return avg
        if name == "holt":
            if len(values) < 2:
                return values[0] if values else None
            alpha = args[1] if len(args) > 1 else 0.3
            beta = args[2] if len(args) > 2 else 0.1
            level, trend = values[0], values[1] - values[0]
            for v in values[1:]:
                last = level
                level = alpha * v + (1 - alpha) * (level + trend)
                trend = beta * (level - last) + (1 - beta) * trend
            return level + trend
        raise IllegalArgumentException(f"unknown MovingFunctions.{name}")

    def methods(self, name: str, args: list):
        return self._call(name, args)


def _moving_fn(name: str, conf: dict, buckets: list, legacy_avg: bool = False) -> None:
    from opensearch_tpu.script.painless import Evaluator
    from opensearch_tpu.script.service import default_script_service as svc

    path = conf["buckets_path"]
    window = int(conf.get("window", 5))
    shift = int(conf.get("shift", 0))
    vals = _path_values(buckets, path)
    if legacy_avg:
        script_src = "MovingFunctions.unweightedAvg(values)"
        params: dict = {}
    else:
        script = conf.get("script")
        if script is None:
            raise ParsingException("moving_fn requires a script")
        script_src = script if isinstance(script, str) else script.get("source", "")
        params = {} if isinstance(script, str) else (script.get("params") or {})
    ast, p = svc.compile(script_src)
    mf = _MovingFunctions()
    for i, b in enumerate(buckets):
        lo = max(0, i - window + shift)
        hi = max(0, i + shift)
        win = [float(v) for v in vals[lo:hi] if v is not None]
        env = {"values": win, "MovingFunctions": mf, "params": {**params, **p}}
        out = Evaluator(env).run(ast)
        b[name] = {"value": out if win else None}


def _bucket_script(name: str, conf: dict, buckets: list) -> None:
    from opensearch_tpu.script.painless import Evaluator
    from opensearch_tpu.script.service import default_script_service as svc

    paths = conf["buckets_path"]
    if not isinstance(paths, dict):
        paths = {"_value": paths}
    script = conf.get("script")
    script_src = script if isinstance(script, str) else (script or {}).get("source", "")
    s_params = {} if isinstance(script, str) else ((script or {}).get("params") or {})
    ast, p = svc.compile(script_src)
    gap_policy = conf.get("gap_policy", "skip")
    for b in buckets:
        params = {**s_params, **p}
        missing = False
        for var, path in paths.items():
            v = _bucket_value(b, path)
            if v is None:
                if gap_policy == "insert_zeros":
                    v = 0.0
                else:
                    missing = True
                    break
            params[var] = float(v)
        if missing:
            continue
        env = {"params": params}
        if "_value" in params:
            env["_value"] = params["_value"]
        out = Evaluator(env).run(ast)
        if out is not None:
            b[name] = {"value": float(out)}


def _bucket_selector(conf: dict, buckets: list, parent_result: dict) -> None:
    from opensearch_tpu.script.painless import Evaluator
    from opensearch_tpu.script.service import default_script_service as svc

    paths = conf["buckets_path"]
    if not isinstance(paths, dict):
        paths = {"_value": paths}
    script = conf.get("script")
    script_src = script if isinstance(script, str) else (script or {}).get("source", "")
    s_params = {} if isinstance(script, str) else ((script or {}).get("params") or {})
    ast, p = svc.compile(script_src)
    keep = []
    for b in buckets:
        params = {**s_params, **p}
        missing = False
        for var, path in paths.items():
            v = _bucket_value(b, path)
            if v is None:
                missing = True
                break
            params[var] = float(v)
        if missing:
            continue
        env = {"params": params}
        if "_value" in params:
            env["_value"] = params["_value"]
        if Evaluator(env).run(ast):
            keep.append(b)
    parent_result["buckets"] = keep


def _bucket_sort(conf: dict, buckets: list, parent_result: dict) -> None:
    sorts = conf.get("sort") or []
    if isinstance(sorts, (str, dict)):
        sorts = [sorts]
    from_ = int(conf.get("from", 0))
    size = conf.get("size")

    def sort_key(b):
        parts = []
        for s in sorts:
            if isinstance(s, str):
                path, order = s, "asc"
            else:
                path = next(iter(s))
                body = s[path]
                order = body.get("order", "asc") if isinstance(body, dict) else body
            v = _bucket_value(b, path)
            desc = order == "desc"
            if v is None:
                parts.append((1, 0))
            else:
                parts.append((0, -float(v) if desc else float(v)))
        return tuple(parts)

    out = sorted(buckets, key=sort_key) if sorts else list(buckets)
    out = out[from_:]
    if size is not None:
        out = out[: int(size)]
    parent_result["buckets"] = out
