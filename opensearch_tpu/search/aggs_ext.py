"""Extended aggregations: the remaining metric & bucket families.

Completes the inventory of search/aggregations/ in the reference
(SURVEY.md §2.2 — bucket/ ~35 types, metrics/ ~25): extended_stats,
percentiles / percentile_ranks (exact — strict-quality superset of the
reference's TDigest/HDR approximations), median_absolute_deviation,
weighted_avg, top_hits, scripted_metric, matrix_stats
(modules/aggs-matrix-stats), multi_terms, rare_terms, significant_terms
(JLH heuristic, search/aggregations/bucket/terms/SignificantTermsAggregator),
sampler / diversified_sampler, adjacency_matrix, date_range (with date
math), composite (after-key pagination,
search/aggregations/bucket/composite/), auto_date_histogram.

All register into aggs.EXTENSION_AGGS with signature
(conf, sub, segments, ms, masks, filter_fn, ext) where ext carries
optional per-segment score arrays and segment metadata (owning index).
"""

from __future__ import annotations

import datetime as _dt
import json
import math
from typing import Any

import numpy as np

from opensearch_tpu.common.errors import IllegalArgumentException, ParsingException
from opensearch_tpu.common.settings import parse_time_millis
from opensearch_tpu.common.timeutil import parse_date_math
from opensearch_tpu.index.mapper import parse_date_millis
from opensearch_tpu.search.aggs import (
    _CALENDAR_UNITS,
    EXTENSION_AGGS,
    _calendar_keys,
    _field_values,
    _run_filter,
    _sub_aggs,
    _value_masks,
)


def _collect(segments, ms, masks, field, missing=None) -> np.ndarray:
    chunks = [_field_values(seg, field, masks[i], ms) for i, seg in enumerate(segments)]
    vals = np.concatenate(chunks) if chunks else np.zeros(0)
    if missing is not None:
        # ValuesSourceConfig.missing: docs in the bucket without a value
        # aggregate the substitute instead; date fields accept date strings
        n_miss = 0
        for i, seg in enumerate(segments):
            nf = seg.numeric_fields.get(field)
            pres = nf.present if nf is not None else np.zeros(seg.n_docs, bool)
            n_miss += int((masks[i] & ~pres).sum())
        if n_miss:
            mapper = ms.field_mapper(field) if hasattr(ms, "field_mapper") \
                else None
            if getattr(mapper, "type", None) == "date" and \
                    isinstance(missing, str):
                mv = float(parse_date_millis(missing))
            else:
                mv = float(missing)
            vals = np.concatenate(
                [vals.astype(np.float64), np.full(n_miss, mv)])
    return vals


def _seg_numeric(seg, field, ms=None):
    # _column applies the unsigned_long unbias (stored biased -2^63)
    from opensearch_tpu.search.aggs import _column

    return _column(seg, field, ms)


def _iso(ms_val: float) -> str:
    return (
        _dt.datetime.fromtimestamp(ms_val / 1000, _dt.timezone.utc)
        .isoformat()
        .replace("+00:00", "Z")
    )


# -- metrics ----------------------------------------------------------------


def _extended_stats(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    vals = _collect(segments, ms, masks, conf["field"], conf.get("missing"))
    sigma = float(conf.get("sigma", 2.0))
    if sigma < 0:
        name = (ext or {}).get("agg_name", "extended_stats")
        raise IllegalArgumentException(
            f"[sigma] must be greater than or equal to 0. "
            f"Found [{sigma}] in [{name}]")
    n = len(vals)
    if n == 0:
        return {
            "count": 0, "min": None, "max": None, "avg": None, "sum": 0.0,
            "sum_of_squares": None, "variance": None,
            "variance_population": None, "variance_sampling": None,
            "std_deviation": None, "std_deviation_population": None,
            "std_deviation_sampling": None,
            "std_deviation_bounds": {
                "upper": None, "lower": None,
                "upper_population": None, "lower_population": None,
                "upper_sampling": None, "lower_sampling": None,
            },
        }
    v = vals.astype(np.float64)
    s = float(v.sum())
    avg = s / n
    sos = float((v * v).sum())
    # the reference's exact double expression (ExtendedStatsAggregator:
    # (sumOfSqrs - sum*sum/count)/count) — a different association loses
    # the last ulp and fails exact-match compliance tests
    var_pop = max((sos - s * s / n) / n, 0.0)
    var_samp = max((sos - s * s / n) / (n - 1), 0.0) \
        if n > 1 else float("nan")
    std_pop = math.sqrt(var_pop)
    std_samp = math.sqrt(var_samp) if n > 1 else float("nan")

    def _clean(x):
        return None if isinstance(x, float) and math.isnan(x) else x

    return {
        "count": n,
        "min": float(v.min()),
        "max": float(v.max()),
        "avg": avg,
        "sum": s,
        "sum_of_squares": sos,
        "variance": var_pop,
        "variance_population": var_pop,
        "variance_sampling": _clean(var_samp),
        "std_deviation": std_pop,
        "std_deviation_population": std_pop,
        "std_deviation_sampling": _clean(std_samp),
        "std_deviation_bounds": {
            "upper": avg + sigma * std_pop,
            "lower": avg - sigma * std_pop,
            "upper_population": avg + sigma * std_pop,
            "lower_population": avg - sigma * std_pop,
            "upper_sampling": _clean(avg + sigma * std_samp) if n > 1 else None,
            "lower_sampling": _clean(avg - sigma * std_samp) if n > 1 else None,
        },
    }


_DEFAULT_PERCENTS = [1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0]


def _hdr_value_at(sorted_vals: np.ndarray, p: float, digits: int) -> float:
    """HdrHistogram.getValueAtPercentile emulation (plugins use the real
    library; reference: search/aggregations/metrics/ HDR percentiles).

    DoubleHistogram auto-ranges so the smallest recorded value lands at
    sub_bucket_half_count in the backing integer histogram; the returned
    quantile is the HIGHEST equivalent value of the rank-selected sample,
    converted back through the same scale — reproducing the reference's
    exact doubles (e.g. 51.0302734375 for p50 of [1,51,101,151] at 3
    significant digits)."""
    import math as _m

    n = len(sorted_vals)
    rank = max(1, int(_m.ceil(p / 100.0 * n)))
    v = float(sorted_vals[min(rank, n) - 1])
    positive = sorted_vals[sorted_vals > 0]
    if len(positive) == 0 or v <= 0:
        return v
    sub_count = 1 << max(int(_m.ceil(_m.log2(2 * 10 ** digits))), 1)
    half = sub_count // 2
    scale_pow = _m.floor(_m.log2(float(positive[0])))
    scale = half / (2.0 ** scale_pow)
    lv = int(v * scale)
    if lv < sub_count:
        unit = 1
    else:
        unit = 1 << (lv.bit_length() - sub_count.bit_length() + 1)
    highest = (lv // unit + 1) * unit - 1
    return highest / scale


def _validate_percentile_params(conf, ext) -> int | None:
    """Returns HDR significant digits when the hdr engine is selected;
    raises the reference's parameter errors."""
    name = (ext or {}).get("agg_name", "percentiles")
    td = conf.get("tdigest")
    if td is not None:
        comp = td.get("compression")
        if comp is not None:
            if not isinstance(comp, (int, float)):
                raise ParsingException("[compression] must be a number")
            if float(comp) < 0:
                raise IllegalArgumentException(
                    f"[compression] must be greater than or equal to 0. "
                    f"Found [{float(comp)}] in [{name}]")
    hdr = conf.get("hdr")
    if hdr is None:
        return None
    digits = hdr.get("number_of_significant_value_digits", 3)
    if digits is None or not isinstance(digits, int) \
            or isinstance(digits, bool):
        raise ParsingException(
            "[number_of_significant_value_digits] must be an integer")
    if not 0 <= digits <= 5:
        raise IllegalArgumentException(
            f"[numberOfSignificantValueDigits] must be between 0 and 5 "
            f"when calculating percentiles. Found [{digits}] in [{name}]")
    return digits


def _require_numeric_field(conf, ms, segments, typ, ext) -> None:
    """Numeric-only metric aggs 400 on keyword/text fields
    (ValuesSourceConfig type resolution)."""
    field = conf.get("field")
    mapper = ms.field_mapper(field) if field else None
    if mapper is not None and mapper.type in ("text", "keyword") and \
            not any(seg.numeric_fields.get(field) is not None
                    for seg in segments):
        raise IllegalArgumentException(
            f"Field [{field}] of type "
            f"[{mapper.original_type or mapper.type}] is not supported "
            f"for aggregation [{typ}]")


def _percentiles(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    hdr_digits = _validate_percentile_params(conf, ext)
    _require_numeric_field(conf, ms, segments, "percentiles", ext)
    vals = _collect(segments, ms, masks, conf["field"], conf.get("missing"))
    raw_percents = conf.get("percents", _DEFAULT_PERCENTS)
    if not isinstance(raw_percents, list) or not raw_percents:
        raise IllegalArgumentException(
            "[percents] must not be empty")
    try:
        percents = [float(p) for p in raw_percents]
    except (TypeError, ValueError):
        raise ParsingException("[percents] values must be numbers")
    if any(p < 0 or p > 100 for p in percents):
        raise IllegalArgumentException(
            "percent must be in [0,100]")
    keyed = bool(conf.get("keyed", True))
    if len(vals) == 0:
        results = [(p, None) for p in percents]
    elif hdr_digits is not None:
        sv = np.sort(vals.astype(np.float64))
        results = [(p, _hdr_value_at(sv, p, hdr_digits)) for p in percents]
    else:
        qs = np.percentile(vals.astype(np.float64), percents)
        results = [(p, float(q)) for p, q in zip(percents, qs)]
    if keyed:
        out = {"values": {str(float(p)): v for p, v in results}}
    else:
        out = {"values": [{"key": p, "value": v} for p, v in results]}
    _attach_value_partial(out, vals, ext)
    return out


def _percentile_ranks(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    vals = _collect(segments, ms, masks, conf["field"], conf.get("missing")).astype(np.float64)
    targets = [float(x) for x in conf["values"]]
    keyed = bool(conf.get("keyed", True))
    n = len(vals)
    results = []
    for t in targets:
        rank = float((vals <= t).sum()) * 100.0 / n if n else None
        results.append((t, rank))
    if keyed:
        out = {"values": {f"{t}": r for t, r in results}}
    else:
        out = {"values": [{"key": t, "value": r} for t, r in results]}
    _attach_value_partial(out, vals, ext)
    return out


def _median_absolute_deviation(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    comp = conf.get("compression")
    if comp is not None and float(comp) <= 0:
        raise IllegalArgumentException(
            f"[compression] must be greater than 0. "
            f"Found [{float(comp)}] in [{(ext or {}).get('agg_name', 'mad')}]")
    _require_numeric_field(conf, ms, segments,
                           "median_absolute_deviation", ext)
    vals = _collect(segments, ms, masks, conf["field"], conf.get("missing")).astype(np.float64)
    if len(vals) == 0:
        out = {"value": None}
        _attach_value_partial(out, vals, ext)
        return out
    med = float(np.median(vals))
    out = {"value": float(np.median(np.abs(vals - med)))}
    _attach_value_partial(out, vals, ext)
    return out


def _attach_value_partial(out: dict, vals, ext) -> None:
    """Cross-node partial: ship the raw masked values (exact merge; capped —
    the reference ships TDigest/HDR sketches for this class of metric)."""
    if not (ext and ext.get("partial")):
        return
    from opensearch_tpu.search.aggs import MAX_PARTIAL_VALUES

    if len(vals) > MAX_PARTIAL_VALUES:
        raise IllegalArgumentException(
            f"metric over [{len(vals)}] values exceeds the cross-node "
            f"exact-merge cap [{MAX_PARTIAL_VALUES}]"
        )
    out["_p_values"] = np.asarray(vals, np.float64).tolist()


def _weighted_avg(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    v_conf = conf.get("value") or {}
    w_conf = conf.get("weight") or {}
    v_field, w_field = v_conf.get("field"), w_conf.get("field")
    if not v_field or not w_field:
        raise ParsingException("weighted_avg requires value.field and weight.field")
    v_missing = v_conf.get("missing")
    num = 0.0
    den = 0.0
    for i, seg in enumerate(segments):
        vv, vp = _seg_numeric(seg, v_field, ms)
        wv, wp = _seg_numeric(seg, w_field, ms)
        if wv is None:
            continue
        base = masks[i] & wp
        if vv is not None:
            both = base & vp
            num += float((vv[both].astype(np.float64) * wv[both]).sum())
            den += float(wv[both].astype(np.float64).sum())
            if v_missing is not None:
                only_w = base & ~vp
                num += float(v_missing) * float(wv[only_w].astype(np.float64).sum())
                den += float(wv[only_w].astype(np.float64).sum())
        elif v_missing is not None:
            num += float(v_missing) * float(wv[base].astype(np.float64).sum())
            den += float(wv[base].astype(np.float64).sum())
    out = {"value": num / den if den else None}
    if ext and ext.get("partial"):
        out["_p_num"] = num
        out["_p_den"] = den
    return out


def _top_hits(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    size = int(conf.get("size", 3))
    from_ = int(conf.get("from", 0))
    sort = conf.get("sort")
    if isinstance(sort, (str, dict)):
        sort = [sort]
    scores = ext.get("scores")
    seg_meta = ext.get("seg_meta")

    rows = []  # (sort_key_tuple, flat_idx, doc)
    total = 0
    for i, seg in enumerate(segments):
        docs = np.nonzero(masks[i])[0]
        total += len(docs)
        seg_scores = scores[i] if scores is not None and i < len(scores) else None
        for d in docs.tolist():
            sc = float(seg_scores[d]) if seg_scores is not None else 0.0
            if sort:
                key = _hit_sort_key(sort, seg, d, sc, ms) + (i, d)
            else:
                key = (-sc, i, d)
            rows.append((key, i, d, sc))
    rows.sort(key=lambda r: r[0])
    page = rows[from_: from_ + size]
    hits = []
    max_score = None
    for _, i, d, sc in page:
        seg = segments[i]
        hit = {
            "_index": (seg_meta[i].get("index") if seg_meta else "_na_"),
            "_id": seg.doc_ids[d],
            "_score": sc if not sort else None,
            "_source": json.loads(seg.sources[d]),
        }
        if sort:
            hit["sort"] = list(_hit_sort_values(sort, seg, d, sc, ms))
        if not sort and (max_score is None or sc > max_score):
            max_score = sc
        hits.append(hit)
    return {
        "hits": {
            "total": {"value": total, "relation": "eq"},
            "max_score": max_score,
            "hits": hits,
        }
    }


def _hit_sort_values(sort, seg, doc, score, ms) -> tuple:
    out = []
    for spec in sort:
        if isinstance(spec, str):
            fname = spec
        else:
            fname = next(iter(spec))
        if fname == "_score":
            out.append(score)
            continue
        if fname == "_doc":
            out.append(doc)
            continue
        vals, present = _seg_numeric(seg, fname, ms)
        if vals is not None and present[doc]:
            v = vals[doc]
            out.append(int(v) if float(v).is_integer() else float(v))
            continue
        kf = seg.keyword_fields.get(fname)
        if kf is not None and kf.first_ord[doc] >= 0:
            out.append(kf.ord_values[int(kf.first_ord[doc])])
            continue
        out.append(None)
    return tuple(out)


class _RevStr:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return self.v > other.v

    def __eq__(self, other):
        return isinstance(other, _RevStr) and self.v == other.v


def _hit_sort_key(sort, seg, doc, score, ms) -> tuple:
    vals = _hit_sort_values(sort, seg, doc, score, ms)
    key = []
    for spec, v in zip(sort, vals):
        if isinstance(spec, str):
            order = "desc" if spec == "_score" else "asc"
        else:
            body = next(iter(spec.values()))
            order = body.get("order", "asc") if isinstance(body, dict) else body
        desc = order == "desc"
        if v is None:
            key.append((1, 0))
        elif isinstance(v, str):
            key.append((0, _RevStr(v) if desc else v))
        else:
            key.append((0, -v if desc else v))
    return tuple(key)


def _scripted_metric(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    from opensearch_tpu.script.painless import DocView, Evaluator
    from opensearch_tpu.script.service import default_script_service as svc

    params = conf.get("params") or {}
    init_s = conf.get("init_script")
    map_s = conf.get("map_script")
    combine_s = conf.get("combine_script")
    reduce_s = conf.get("reduce_script")
    if map_s is None:
        raise ParsingException("scripted_metric requires map_script")
    scores = ext.get("scores")
    states = []
    for i, seg in enumerate(segments):
        state: dict = {}
        if init_s:
            ast, p = svc.compile(init_s)
            Evaluator({"params": {**params, **p}, "state": state}).run(ast)
        map_ast, map_p = svc.compile(map_s)
        seg_scores = scores[i] if scores is not None and i < len(scores) else None
        for d in np.nonzero(masks[i])[0].tolist():
            env = {
                "params": {**params, **map_p},
                "state": state,
                "doc": DocView(seg, d, ms),
                "_score": float(seg_scores[d]) if seg_scores is not None else 0.0,
            }
            Evaluator(env).run(map_ast)
        if combine_s:
            ast, p = svc.compile(combine_s)
            state = Evaluator({"params": {**params, **p}, "state": state}).run(ast)
        states.append(state)
    if reduce_s:
        ast, p = svc.compile(reduce_s)
        value = Evaluator({"params": {**params, **p}, "states": states}).run(ast)
    else:
        value = states
    return {"value": value}


def _matrix_stats(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    fields = conf.get("fields") or []
    if not fields:
        raise ParsingException("matrix_stats requires fields")
    cols = {}
    present_cols = {}
    for f in fields:
        vals_parts, pres_parts = [], []
        for i, seg in enumerate(segments):
            vv, vp = _seg_numeric(seg, f, ms)
            n = seg.n_docs
            if vv is None:
                vals_parts.append(np.zeros(n))
                pres_parts.append(np.zeros(n, bool))
            else:
                vals_parts.append(vv.astype(np.float64))
                pres_parts.append(masks[i] & vp)
        cols[f] = np.concatenate(vals_parts) if vals_parts else np.zeros(0)
        present_cols[f] = (
            np.concatenate(pres_parts) if pres_parts else np.zeros(0, bool)
        )
    out_fields = []
    doc_count = 0
    for f in fields:
        m = present_cols[f]
        v = cols[f][m]
        n = len(v)
        doc_count = max(doc_count, n)
        if n == 0:
            continue
        mean = float(v.mean())
        var = float(v.var(ddof=1)) if n > 1 else 0.0
        std = math.sqrt(var)
        centered = v - mean
        skew = (
            float((centered**3).mean()) / (std**3) if n > 2 and std > 0 else 0.0
        )
        kurt = (
            float((centered**4).mean()) / (var**2) if n > 3 and var > 0 else 0.0
        )
        cov_row, corr_row = {}, {}
        for g in fields:
            both = present_cols[f] & present_cols[g]
            nb = int(both.sum())
            if nb < 2:
                cov_row[g] = 0.0
                corr_row[g] = 0.0
                continue
            a = cols[f][both]
            b = cols[g][both]
            cov = float(np.cov(a, b, ddof=1)[0, 1])
            cov_row[g] = cov
            sa, sb = a.std(ddof=1), b.std(ddof=1)
            corr_row[g] = cov / (sa * sb) if sa > 0 and sb > 0 else 0.0
        out_fields.append({
            "name": f,
            "count": n,
            "mean": mean,
            "variance": var,
            "skewness": skew,
            "kurtosis": kurt,
            "covariance": cov_row,
            "correlation": corr_row,
        })
    return {"doc_count": doc_count, "fields": out_fields}


# -- buckets ----------------------------------------------------------------


def _seg_key_values(seg, field, ms):
    """Per-doc scalar key (first value) + presence for terms-like bucketing."""
    kf = seg.keyword_fields.get(field)
    if kf is not None:
        present = kf.first_ord >= 0
        return kf, present, "keyword"
    vals, pres = _seg_numeric(seg, field, ms)
    if vals is not None:
        return vals, pres, "numeric"
    return None, np.zeros(seg.n_docs, bool), "none"


def _multi_terms(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    from opensearch_tpu.search.aggs import _KeyOrd, _iso_ms

    terms_conf = conf.get("terms") or []
    fields = [t["field"] for t in terms_conf]
    missings = [t.get("missing") for t in terms_conf]
    if len(fields) < 2:
        raise ParsingException("multi_terms requires at least 2 terms sources")
    size = int(conf.get("size", 10))
    min_doc_count = int(conf.get("min_doc_count", 1))
    if ext and ext.get("partial"):
        size = int(conf.get("shard_size", size + (size >> 1) + 10))

    # per-component rendering kind (boolean -> JSON true/false,
    # date -> ISO string, like MultiTermsAggregator's per-source formats)
    kinds = []
    for f in fields:
        mapper = ms.field_mapper(f)
        if mapper is not None and mapper.type == "boolean":
            kinds.append("boolean")
        elif mapper is not None and mapper.type == "date":
            kinds.append("date")
        else:
            kinds.append("value")

    # coerce per-source `missing` values to the source's kind up-front so
    # key tuples stay type-uniform (mixed str/float slots break the sort)
    coerced_missing: list = []
    for m_, kind in zip(missings, kinds):
        if m_ is None:
            coerced_missing.append(None)
        elif kind == "boolean":
            coerced_missing.append(1 if m_ in (True, "true", 1) else 0)
        elif kind == "date" and isinstance(m_, str):
            coerced_missing.append(int(parse_date_millis(m_)))
        else:
            coerced_missing.append(m_)

    counts: dict[tuple, int] = {}
    doc_lists: dict[tuple, list] = {}
    for i, seg in enumerate(segments):
        per_field = [_seg_key_values(seg, f, ms) for f in fields]
        docs = np.nonzero(masks[i])[0]
        for d in docs.tolist():
            key_parts = []
            ok = True
            for fi, (src, present, kind) in enumerate(per_field):
                if kind == "none" or not present[d]:
                    if coerced_missing[fi] is not None:
                        key_parts.append(coerced_missing[fi])
                        continue
                    ok = False
                    break
                if kind == "keyword":
                    key_parts.append(src.ord_values[int(src.first_ord[d])])
                else:
                    v = src[d]
                    key_parts.append(int(v) if float(v).is_integer() else float(v))
            if not ok:
                continue
            key = tuple(key_parts)
            counts[key] = counts.get(key, 0) + 1
            doc_lists.setdefault(key, []).append((i, d))

    if min_doc_count > 0:
        counts = {k: c for k, c in counts.items() if c >= min_doc_count}

    # order: single dict or list of {"_count"|"_key"|"<agg-path>": dir}
    order_conf = conf.get("order", {"_count": "desc"})
    order_specs = (list(order_conf.items()) if isinstance(order_conf, dict)
                   else [next(iter(o.items())) for o in order_conf])
    sub_results: dict[tuple, dict] = {}

    def bucket_sub(key) -> dict:
        if key not in sub_results:
            bucket_masks = [np.zeros(s.n_docs, bool) for s in segments]
            for i, d in doc_lists.get(key, []):
                bucket_masks[i][d] = True
            sub_results[key] = _sub_aggs(sub, segments, ms, bucket_masks,
                                         filter_fn, ext)
        return sub_results[key]

    def sort_key(kv):
        key, count = kv
        parts = []
        for okey, odir in order_specs:
            desc = odir == "desc"
            if okey == "_count":
                parts.append(-count if desc else count)
            elif okey == "_key":
                parts.append(tuple(_KeyOrd(k, desc) for k in key))
            else:
                name, _, prop = okey.partition(".")
                result = (bucket_sub(key) if sub else {}).get(name)
                if result is None:
                    raise ParsingException(
                        f"multi_terms order references unknown agg [{okey}]")
                v = result.get(prop or "value")
                v = v if v is not None else float("-inf")
                parts.append(-v if desc else v)
        parts.append(tuple(_KeyOrd(k, False) for k in key))
        return tuple(parts)

    items = sorted(counts.items(), key=sort_key)
    top = items[:size]
    other = sum(c for _, c in items[size:])

    def render(k, kind):
        if kind == "boolean":
            return bool(k)
        if kind == "date" and not isinstance(k, str):
            return _iso_ms(int(k))
        return k

    def render_str(k, kind):
        if kind == "boolean":
            return "true" if k else "false"
        if kind == "date" and not isinstance(k, str):
            return _iso_ms(int(k))
        return str(k)

    buckets = []
    for key, count in top:
        bucket = {
            "key": [render(k, kind) for k, kind in zip(key, kinds)],
            "key_as_string": "|".join(
                render_str(k, kind) for k, kind in zip(key, kinds)),
            "doc_count": count,
        }
        if sub:
            bucket.update(bucket_sub(key))
        buckets.append(bucket)
    return {
        "doc_count_error_upper_bound": 0,
        "sum_other_doc_count": other,
        "buckets": buckets,
    }


def _rare_terms(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    field = conf["field"]
    max_doc_count = int(conf.get("max_doc_count", 1))
    counts: dict[Any, int] = {}
    for i, seg in enumerate(segments):
        kf = seg.keyword_fields.get(field)
        if kf is not None:
            entry_mask = masks[i][kf.mv_docs]
            seg_counts = np.bincount(kf.mv_ords[entry_mask], minlength=len(kf.ord_values))
            for o in np.nonzero(seg_counts)[0]:
                key = kf.ord_values[int(o)]
                counts[key] = counts.get(key, 0) + int(seg_counts[o])
        else:
            vals = _field_values(seg, field, masks[i], ms)
            uniq, c = np.unique(vals, return_counts=True)
            for v, n in zip(uniq.tolist(), c.tolist()):
                counts[v] = counts.get(v, 0) + n
    rare = [(k, c) for k, c in counts.items() if c <= max_doc_count]
    rare.sort(key=lambda kv: (kv[1], str(kv[0])))
    buckets = []
    for key, count in rare:
        bucket = {"key": key, "doc_count": count}
        if sub:
            bucket_masks = _value_masks(segments, field, key, masks, ms)
            bucket.update(_sub_aggs(sub, segments, ms, bucket_masks, filter_fn, ext))
        buckets.append(bucket)
    out = {"buckets": buckets}
    if ext and ext.get("partial"):
        # a term rare here may be common on another node: ship the FULL
        # local counts so the coordinator filter sees global totals
        from opensearch_tpu.search.aggs import MAX_PARTIAL_VALUES

        if len(counts) > MAX_PARTIAL_VALUES:
            raise IllegalArgumentException(
                f"rare_terms over [{len(counts)}] terms exceeds the "
                f"cross-node exact-merge cap [{MAX_PARTIAL_VALUES}]"
            )
        out["_p_counts"] = [[k, c] for k, c in counts.items()]
    return out


def _significant_terms(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    field = conf["field"]
    size = int(conf.get("size", 10))
    min_doc_count = int(conf.get("min_doc_count", 3))
    fg_counts: dict[Any, int] = {}
    bg_counts: dict[Any, int] = {}
    fg_total = 0
    bg_total = 0
    for i, seg in enumerate(segments):
        fg_total += int(masks[i].sum())
        bg_total += int(seg.live.sum())
        kf = seg.keyword_fields.get(field)
        if kf is None:
            continue
        fg_entry = masks[i][kf.mv_docs]
        bg_entry = seg.live[kf.mv_docs]
        fg_c = np.bincount(kf.mv_ords[fg_entry], minlength=len(kf.ord_values))
        bg_c = np.bincount(kf.mv_ords[bg_entry], minlength=len(kf.ord_values))
        for o in np.nonzero(bg_c)[0]:
            key = kf.ord_values[int(o)]
            bg_counts[key] = bg_counts.get(key, 0) + int(bg_c[o])
            if fg_c[o]:
                fg_counts[key] = fg_counts.get(key, 0) + int(fg_c[o])
    scored = []
    for key, fg in fg_counts.items():
        if fg < min_doc_count or fg_total == 0:
            continue
        bg = bg_counts.get(key, fg)
        fg_pct = fg / fg_total
        bg_pct = bg / bg_total if bg_total else 0.0
        if fg_pct <= bg_pct or bg_pct == 0:
            continue
        # JLH: (fg% - bg%) * (fg% / bg%)
        score = (fg_pct - bg_pct) * (fg_pct / bg_pct)
        scored.append((score, key, fg, bg))
    scored.sort(key=lambda t: (-t[0], str(t[1])))
    buckets = []
    for score, key, fg, bg in scored[:size]:
        bucket = {"key": key, "doc_count": fg, "score": score, "bg_count": bg}
        if sub:
            bucket_masks = _value_masks(segments, field, key, masks, ms)
            bucket.update(_sub_aggs(sub, segments, ms, bucket_masks, filter_fn, ext))
        buckets.append(bucket)
    return {"doc_count": fg_total, "bg_count": bg_total, "buckets": buckets}


def _sampler(conf, sub, segments, ms, masks, filter_fn, ext, diversify=False) -> dict:
    shard_size = int(conf.get("shard_size", 100))
    scores = ext.get("scores")
    rows = []
    for i, seg in enumerate(segments):
        seg_scores = scores[i] if scores is not None and i < len(scores) else None
        for d in np.nonzero(masks[i])[0].tolist():
            sc = float(seg_scores[d]) if seg_scores is not None else 0.0
            rows.append((-sc, i, d))
    rows.sort()
    sel_masks = [np.zeros(s.n_docs, bool) for s in segments]
    taken = 0
    seen_values: dict[Any, int] = {}
    max_per_value = int(conf.get("max_docs_per_value", 1)) if diversify else None
    div_field = conf.get("field") if diversify else None
    for _, i, d in rows:
        if taken >= shard_size:
            break
        if diversify and div_field:
            seg = segments[i]
            key = None
            kf = seg.keyword_fields.get(div_field)
            if kf is not None and kf.first_ord[d] >= 0:
                key = kf.ord_values[int(kf.first_ord[d])]
            else:
                vals, pres = _seg_numeric(seg, div_field, ms)
                if vals is not None and pres[d]:
                    key = float(vals[d])
            if key is not None:
                if seen_values.get(key, 0) >= max_per_value:
                    continue
                seen_values[key] = seen_values.get(key, 0) + 1
        sel_masks[i][d] = True
        taken += 1
    out = {"doc_count": taken}
    out.update(_sub_aggs(sub, segments, ms, sel_masks, filter_fn, ext))
    return out


def _diversified_sampler(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    return _sampler(conf, sub, segments, ms, masks, filter_fn, ext, diversify=True)


def _adjacency_matrix(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    named = conf.get("filters") or {}
    sep = conf.get("separator", "&")
    names = sorted(named)
    f_masks = {
        name: _run_filter(filter_fn, named[name], segments, masks) for name in names
    }
    buckets = []
    for idx, name in enumerate(names):
        count = int(sum(m.sum() for m in f_masks[name]))
        if count > 0:
            bucket = {"key": name, "doc_count": count}
            if sub:
                bucket.update(
                    _sub_aggs(sub, segments, ms, f_masks[name], filter_fn, ext)
                )
            buckets.append(bucket)
        for name2 in names[idx + 1:]:
            inter = [a & b for a, b in zip(f_masks[name], f_masks[name2])]
            count2 = int(sum(m.sum() for m in inter))
            if count2 > 0:
                bucket = {"key": f"{name}{sep}{name2}", "doc_count": count2}
                if sub:
                    bucket.update(_sub_aggs(sub, segments, ms, inter, filter_fn, ext))
                buckets.append(bucket)
    buckets.sort(key=lambda b: b["key"])
    return {"buckets": buckets}


def _date_field_out_fmt(ms_service, field, conf) -> str | None:
    """Output/parse format for date_range values: agg-level `format` wins,
    else the FIELD's mapping format (first alternative), else default."""
    if conf.get("format"):
        return str(conf["format"])
    mapper = ms_service.field_mapper(field) if ms_service else None
    fmt = getattr(mapper, "format", None)
    if fmt:
        return str(fmt).split("||")[0]
    return None


def _parse_date_by_fmt(v, fmt: str | None) -> int:
    """-> epoch millis; epoch_second-formatted fields read bare numbers as
    SECONDS (the reference resolves numeric input through the field's
    DateFormatter)."""
    if fmt == "epoch_second" and (
            isinstance(v, (int, float)) or str(v).lstrip("-").isdigit()):
        return int(v) * 1000
    return parse_date_math(v)


def _format_date_by_fmt(ms_val: float, fmt: str | None) -> str:
    if fmt == "epoch_second":
        return str(int(ms_val) // 1000)
    from opensearch_tpu.search.fetch import _format_date_ms

    if fmt in (None, "strict_date_optional_time", "date_optional_time"):
        return _format_date_ms(int(ms_val), None)
    return str(_format_date_ms(int(ms_val), fmt))


def _date_range(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    field = conf["field"]
    ranges = conf["ranges"]
    keyed = bool(conf.get("keyed", False))
    fmt = _date_field_out_fmt(ms, field, conf)
    missing_raw = conf.get("missing")
    missing_ms = _parse_date_by_fmt(missing_raw, fmt) \
        if missing_raw is not None else None
    entries = []
    for r in ranges:
        frm = _parse_date_by_fmt(r["from"], fmt) \
            if r.get("from") is not None else None
        to = _parse_date_by_fmt(r["to"], fmt) \
            if r.get("to") is not None else None
        count = 0
        bucket_masks = []
        for i, seg in enumerate(segments):
            vals, pres = _seg_numeric(seg, field, ms)
            if vals is None:
                vals = np.zeros(seg.n_docs)
                pres = np.zeros(seg.n_docs, bool)
            m = masks[i] & pres
            if frm is not None:
                m = m & (vals >= frm)
            if to is not None:
                m = m & (vals < to)
            if missing_ms is not None:
                # docs without the field take the substitute value
                m_miss = masks[i] & ~pres
                if (frm is None or missing_ms >= frm) and \
                        (to is None or missing_ms < to):
                    m = m | m_miss
            bucket_masks.append(m)
            count += int(m.sum())
        key = r.get("key")
        if key is None:
            key = (f"{_format_date_by_fmt(frm, fmt) if frm is not None else '*'}"
                   f"-{_format_date_by_fmt(to, fmt) if to is not None else '*'}")
        bucket: dict[str, Any] = {"key": key, "doc_count": count}
        if frm is not None:
            bucket["from"] = float(frm)
            bucket["from_as_string"] = _format_date_by_fmt(frm, fmt)
        if to is not None:
            bucket["to"] = float(to)
            bucket["to_as_string"] = _format_date_by_fmt(to, fmt)
        if sub:
            bucket.update(_sub_aggs(sub, segments, ms, bucket_masks, filter_fn, ext))
        entries.append((frm, to, bucket))
    # InternalDateRange sorts buckets by (from asc nulls-first, to asc)
    entries.sort(key=lambda e: (
        e[0] if e[0] is not None else float("-inf"),
        e[1] if e[1] is not None else float("inf"),
    ))
    buckets = [b for _f, _t, b in entries]
    if keyed:
        return {"buckets": {b["key"]: {k: v for k, v in b.items() if k != "key"}
                            for b in buckets}}
    return {"buckets": buckets}


# -- composite --------------------------------------------------------------


def _composite(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    sources = conf.get("sources") or []
    if not sources:
        # both message forms appear across reference versions; the suite
        # greps for either
        raise ParsingException(
            "Required [sources]: Composite [sources] cannot be null or "
            "empty")
    size = int(conf.get("size", 10))
    after = conf.get("after")
    specs = []  # (name, type, conf)
    for s in sources:
        if len(s) != 1:
            raise ParsingException("each composite source must have one name")
        name = next(iter(s))
        body = s[name]
        typ = next(iter(body))
        if typ not in ("terms", "histogram", "date_histogram"):
            raise ParsingException(f"unsupported composite source type [{typ}]")
        specs.append((name, typ, body[typ]))

    counts: dict[tuple, int] = {}
    doc_lists: dict[tuple, list] = {}
    for i, seg in enumerate(segments):
        per_src = []
        for name, typ, sconf in specs:
            field = sconf["field"]
            # composite buckets EVERY value of a multi-valued field
            # (CompositeValuesSource iterates all ords per doc); the
            # keyword CSR (mv_offsets into mv_ords) gives per-doc slices
            kf = seg.keyword_fields.get(field)
            per_src.append(
                (_seg_key_values(seg, field, ms), typ, sconf, kf))
        import itertools as _it

        for d in np.nonzero(masks[i])[0].tolist():
            # list of alternatives per source; None = missing_bucket slot
            parts_options: list[list] = []
            ok = True
            for (src, present, kind), typ, sconf, kf in per_src:
                if not present[d]:
                    if sconf.get("missing_bucket"):
                        parts_options.append([None])
                        continue
                    ok = False
                    break
                if kind == "keyword":
                    if kf is not None:
                        s_, e_ = (int(kf.mv_offsets[d]),
                                  int(kf.mv_offsets[d + 1]))
                        vals = [kf.ord_values[int(o)]
                                for o in kf.mv_ords[s_:e_]]
                    else:
                        vals = []
                else:
                    nf = seg.numeric_fields.get(sconf["field"])
                    if nf is not None and nf.mv_offsets is not None:
                        # every value of a multi-valued numeric buckets
                        vals = [float(x) for x in nf.doc_values(d)]
                    else:
                        vals = [float(src[d])]
                opts = []
                for v in vals:
                    if typ == "histogram":
                        interval = float(sconf["interval"])
                        v = math.floor(v / interval) * interval
                    elif typ == "date_histogram":
                        from opensearch_tpu.search.aggs import (
                            _CALENDAR_FIXED,
                        )

                        iv = str(sconf.get("fixed_interval") or sconf.get("calendar_interval") or sconf.get("interval"))
                        iv = _CALENDAR_FIXED.get(iv, iv)
                        off = float(parse_time_millis(
                            sconf.get("offset", 0)))
                        if iv in _CALENDAR_UNITS:
                            v = int(_calendar_keys(np.asarray([v]), iv)[0])
                        else:
                            interval = float(parse_time_millis(iv))
                            v = int(math.floor((v - off) / interval)
                                    * interval + off)
                    elif kind == "numeric" and float(v).is_integer():
                        v = int(v)
                    if v not in opts:
                        opts.append(v)
                parts_options.append(opts)
            if not ok:
                continue
            for combo in _it.product(*parts_options):
                key = tuple(combo)
                counts[key] = counts.get(key, 0) + 1
                doc_lists.setdefault(key, []).append((i, d))

    orders = [
        -1 if (spec[2].get("order", "asc") == "desc") else 1 for spec in specs
    ]

    missing_orders = [spec[2].get("missing_order", "first")
                      for spec in specs]

    def key_sortable(key: tuple) -> tuple:
        parts = []
        for v, o, mo in zip(key, orders, missing_orders):
            if v is None:
                # missing buckets sort first unless missing_order=last
                parts.append((2 if mo == "last" else -1, 0))
            elif isinstance(v, str):
                parts.append((0, _RevStr(v) if o < 0 else v))
            else:
                parts.append((1, -v if o < 0 else v))
        return tuple(parts)

    ordered = sorted(counts, key=key_sortable)
    if after is not None:
        parts = []
        for name, typ, sconf in specs:
            v = after.get(name)
            # a formatted after value round-trips back to epoch ms
            if typ == "date_histogram" and isinstance(v, str):
                try:
                    v = int(parse_date_millis(v))
                except ValueError:
                    pass
            parts.append(v)
        cutoff = key_sortable(tuple(parts))
        ordered = [k for k in ordered if key_sortable(k) > cutoff]
    page = ordered[:size]

    _NAMED_FORMATS = {
        "strict_date": "yyyy-MM-dd", "date": "yyyy-MM-dd",
        "basic_date": "yyyyMMdd",
        "strict_date_time": "yyyy-MM-dd'T'HH:mm:ss.SSSZ",
    }

    def render_part(v, spec):
        _name, typ, sconf = spec
        if v is None or typ != "date_histogram":
            return v
        fmt = sconf.get("format")
        if not fmt:
            return v
        if fmt == "epoch_millis":
            return str(int(v))
        from opensearch_tpu.search.aggs import _iso_ms

        if fmt == "iso8601":
            return _iso_ms(int(v))
        from opensearch_tpu.search.fetch import _JODA_MAP

        py_fmt = _NAMED_FORMATS.get(str(fmt), str(fmt))
        for jd, st in _JODA_MAP:
            py_fmt = py_fmt.replace(jd, st)
        py_fmt = py_fmt.replace("'T'", "T").replace("SSS", "{ms}") \
            .replace("Z", "Z")
        kdt = _dt.datetime.fromtimestamp(v / 1000, _dt.timezone.utc)
        out_s = kdt.strftime(py_fmt)
        return out_s.replace("{ms}", f"{int(v) % 1000:03d}")

    def render_key(key) -> dict:
        return {spec[0]: render_part(v, spec)
                for spec, v in zip(specs, key)}

    buckets = []
    for key in page:
        bucket = {
            "key": render_key(key),
            "doc_count": counts[key],
        }
        if sub:
            bucket_masks = [np.zeros(s.n_docs, bool) for s in segments]
            for i, d in doc_lists[key]:
                bucket_masks[i][d] = True
            bucket.update(_sub_aggs(sub, segments, ms, bucket_masks, filter_fn, ext))
        buckets.append(bucket)
    out: dict[str, Any] = {"buckets": buckets}
    if page:
        out["after_key"] = render_key(page[-1])
    return out


# -- auto_date_histogram ----------------------------------------------------

_AUTO_LADDER_MS = [
    ("1s", 1000), ("5s", 5000), ("10s", 10_000), ("30s", 30_000),
    ("1m", 60_000), ("5m", 300_000), ("10m", 600_000), ("30m", 1_800_000),
    ("1h", 3_600_000), ("3h", 10_800_000), ("12h", 43_200_000),
    ("1d", 86_400_000), ("7d", 604_800_000), ("30d", 2_592_000_000),
    ("90d", 7_776_000_000), ("365d", 31_536_000_000),
]


def _auto_date_histogram(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    field = conf["field"]
    target = int(conf.get("buckets", 10))
    all_vals = _collect(segments, ms, masks, field)
    if len(all_vals) == 0:
        return {"buckets": [], "interval": "1s"}
    lo, hi = float(all_vals.min()), float(all_vals.max())
    chosen, interval = _AUTO_LADDER_MS[-1]
    for name, iv in _AUTO_LADDER_MS:
        if (math.floor(hi / iv) - math.floor(lo / iv) + 1) <= target:
            chosen, interval = name, iv
            break
    # multi-day intervals anchor at the first DAY-rounded data point, not
    # at the epoch (the reference's RoundingInfo innerIntervals: values
    # round to the base unit, then group into interval-sized runs)
    day = 86_400_000
    if interval > day and interval % day == 0:
        anchor = math.floor(lo / day) * day
    else:
        anchor = 0.0
    key_counts: dict[float, int] = {}
    per_seg_keys, per_seg_docs = [], []
    for i, seg in enumerate(segments):
        vals, pres = _seg_numeric(seg, field, ms)
        if vals is None:
            per_seg_keys.append(np.zeros(0))
            per_seg_docs.append(np.zeros(0, np.int64))
            continue
        m = masks[i] & pres
        docs = np.nonzero(m)[0]
        keys = (np.floor((vals[docs].astype(np.float64) - anchor)
                         / interval) * interval + anchor)
        per_seg_keys.append(keys)
        per_seg_docs.append(docs)
        uniq, c = np.unique(keys, return_counts=True)
        for k_, n_ in zip(uniq.tolist(), c.tolist()):
            key_counts[k_] = key_counts.get(k_, 0) + n_
    from opensearch_tpu.search.aggs import _iso_ms

    buckets = []
    for key in sorted(key_counts):
        bucket: dict[str, Any] = {
            "key": int(key),
            "key_as_string": _iso_ms(int(key)),
            "doc_count": key_counts[key],
        }
        if sub:
            bucket_masks = []
            for i, seg in enumerate(segments):
                bm = np.zeros(seg.n_docs, bool)
                bm[per_seg_docs[i][per_seg_keys[i] == key]] = True
                bucket_masks.append(bm)
            bucket.update(_sub_aggs(sub, segments, ms, bucket_masks, filter_fn, ext))
        buckets.append(bucket)
    return {"buckets": buckets, "interval": chosen}


def _significant_text(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    """significant_text (bucket/terms/SignificantTextAggregationBuilder):
    significant_terms over a text field's analyzed terms — foreground =
    matched docs' postings, background = all live docs'. JLH scoring like
    _significant_terms."""
    field = conf["field"]
    size = int(conf.get("size", 10))
    min_doc_count = int(conf.get("min_doc_count", 3))
    dedup = bool(conf.get("filter_duplicate_text", False))
    fg_counts: dict[str, int] = {}
    bg_counts: dict[str, int] = {}
    fg_total = 0
    bg_total = 0
    seen_shingles: set = set()
    for i, seg in enumerate(segments):
        fg_total += int(masks[i].sum())
        bg_total += int(seg.live.sum())
        tf = seg.text_fields.get(field)
        if tf is None:
            continue
        for tid, term in enumerate(tf.terms):
            off = int(tf.term_offsets[tid])
            end = int(tf.term_offsets[tid + 1])
            docs = tf.postings_docs[off:end]
            bg = int(seg.live[docs].sum())
            if bg:
                bg_counts[term] = bg_counts.get(term, 0) + bg
            if not dedup:
                fg = int(masks[i][docs].sum())
                if fg:
                    fg_counts[term] = fg_counts.get(term, 0) + fg
        if dedup:
            # filter_duplicate_text: prune tokens inside any 6-gram window
            # already seen in an earlier foreground doc (Lucene
            # DeDuplicatingTokenFilter's DuplicateSequenceSpotter, window 6)
            for fg_c in _dedup_fg_counts(tf, masks[i], seen_shingles):
                fg_counts[fg_c] = fg_counts.get(fg_c, 0) + 1
    scored = []
    for key, fg in fg_counts.items():
        if fg < min_doc_count or fg_total == 0:
            continue
        bg = bg_counts.get(key, fg)
        fg_pct = fg / fg_total
        bg_pct = bg / bg_total if bg_total else 0.0
        if fg_pct <= bg_pct or bg_pct == 0:
            continue
        score = (fg_pct - bg_pct) * (fg_pct / bg_pct)  # JLH
        scored.append((score, key, fg, bg))
    scored.sort(key=lambda t: (-t[0], str(t[1])))
    buckets = [
        {"key": key, "doc_count": fg, "score": score, "bg_count": bg}
        for score, key, fg, bg in scored[:size]
    ]
    return {"doc_count": fg_total, "bg_count": bg_total, "buckets": buckets}


def _dedup_fg_counts(tf, mask, seen_shingles: set):
    """Yields one term per (doc, term) foreground count surviving the
    duplicate-6-gram prune. Rebuilds each doc's token stream from position
    postings."""
    W = 6
    for d in np.nonzero(mask)[0]:
        d = int(d)
        seq: dict[int, str] = {}
        for tid, term in enumerate(tf.terms):
            for pos in tf.term_positions(term, d):
                seq[int(pos)] = term
        ordered = [seq[p] for p in sorted(seq)]
        pruned = [False] * len(ordered)
        if len(ordered) >= W:
            for s in range(len(ordered) - W + 1):
                gram = tuple(ordered[s:s + W])
                if gram in seen_shingles:
                    for j in range(s, s + W):
                        pruned[j] = True
                else:
                    seen_shingles.add(gram)
        elif ordered:
            gram = tuple(ordered)
            if gram in seen_shingles:
                pruned = [True] * len(ordered)
            else:
                seen_shingles.add(gram)
        yield from {t for t, pr in zip(ordered, pruned) if not pr}


def _ip_range(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    """ip_range (bucket/range/IpRangeAggregationBuilder): ranges/CIDR masks
    over ip columns (stored as keyword ordinals here)."""
    import ipaddress

    field = conf["field"]
    ranges = conf.get("ranges")
    if not isinstance(ranges, list) or not ranges:
        raise ParsingException("[ip_range] requires [ranges]")
    keyed = bool(conf.get("keyed", False))

    def ip_int(v):
        a = ipaddress.ip_address(str(v))
        # the reference compares 16-byte IPv6 forms; v4 sorts at its
        # v4-mapped position (::ffff:a.b.c.d), so ::1 < any v4 address
        if a.version == 4:
            return (0xFFFF << 32) | int(a)
        return int(a)

    # per-segment int value per doc (first value)
    seg_vals = []
    for seg in segments:
        kf = seg.keyword_fields.get(field)
        if kf is None:
            seg_vals.append(None)
            continue
        ord_ints = [ip_int(v) if v else None for v in kf.ord_values]
        vals = np.full(seg.n_docs, -1, dtype=object)
        for d in range(seg.n_docs):
            o = int(kf.first_ord[d])
            vals[d] = ord_ints[o] if o >= 0 else None
        seg_vals.append(vals)

    buckets = []
    for r in ranges:
        frm = to = None
        key = r.get("key")
        mask_from_str = mask_to_str = None
        if "mask" in r:
            net = ipaddress.ip_network(str(r["mask"]), strict=False)
            frm = ip_int(net.network_address)
            to = ip_int(net.broadcast_address) + 1
            if key is None:
                key = str(r["mask"])
            # mask buckets report their bounds as addresses: from = network
            # address (omitted when ::), to = broadcast+1 (exclusive)
            if int(net.network_address) != 0:
                mask_from_str = str(net.network_address)
            upper = int(net.broadcast_address) + 1
            if net.version == 4:
                if upper < (1 << 32):
                    mask_to_str = str(ipaddress.IPv4Address(upper))
            elif upper < (1 << 128):
                mask_to_str = str(ipaddress.IPv6Address(upper))
        else:
            if r.get("from") is not None:
                frm = ip_int(r["from"])
            if r.get("to") is not None:
                to = ip_int(r["to"])
        count = 0
        bucket_masks = []
        for i, seg in enumerate(segments):
            vals = seg_vals[i]
            if vals is None:
                bucket_masks.append(np.zeros(seg.n_docs, bool))
                continue
            m = masks[i].copy()
            for d in np.nonzero(m)[0]:
                v = vals[int(d)]
                if v is None or (frm is not None and v < frm) \
                        or (to is not None and v >= to):
                    m[d] = False
            bucket_masks.append(m)
            count += int(m.sum())
        bucket: dict[str, Any] = {"doc_count": count}
        if "mask" in r:
            bkey = key
        else:
            bkey = key or (f"{r.get('from', '*')}-{r.get('to', '*')}")
        bucket["key"] = bkey
        if "mask" in r:
            if mask_from_str is not None:
                bucket["from"] = mask_from_str
            if mask_to_str is not None:
                bucket["to"] = mask_to_str
        else:
            if r.get("from") is not None:
                bucket["from"] = str(r["from"])
            if r.get("to") is not None:
                bucket["to"] = str(r["to"])
        if sub:
            bucket.update(_sub_aggs(sub, segments, ms, bucket_masks,
                                    filter_fn, ext))
        buckets.append(bucket)
    if keyed:
        return {"buckets": {b["key"]: {k: v for k, v in b.items()
                                       if k != "key"} for b in buckets}}
    return {"buckets": buckets}


# -- geo aggregations (bucket/geogrid + metric geo aggs) ---------------------
# Cell ids and distances are integer/float array ops over the synthetic
# {field}#lat/#lon columns — the naturally-vectorizable OLAP shape
# (GeoHashGridAggregator / GeoTileGridAggregator / GeoDistanceAggregator /
# GeoBoundsAggregator / GeoCentroidAggregator).


def _geo_latlon(segments, field):
    """Per-segment (lat, lon, present) float arrays, or None entries when
    the segment lacks the field's columns."""
    out = []
    for seg in segments:
        lat_f = seg.numeric_fields.get(f"{field}#lat")
        lon_f = seg.numeric_fields.get(f"{field}#lon")
        if lat_f is None or lon_f is None:
            out.append(None)
            continue
        out.append((
            lat_f.values_f64[:seg.n_docs],
            lon_f.values_f64[:seg.n_docs],
            lat_f.present[:seg.n_docs],
        ))
    return out


def _geo_distance_agg(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    from opensearch_tpu.search.executor import (
        _haversine_m,
        _parse_geo_origin,
    )

    field = conf["field"]
    origin = conf.get("origin")
    if origin is None:
        raise ParsingException("[geo_distance] requires [origin]")
    ranges = conf.get("ranges")
    if not isinstance(ranges, list) or not ranges:
        raise ParsingException("[geo_distance] requires [ranges]")
    o_lat, o_lon = _parse_geo_origin(origin)
    keyed = bool(conf.get("keyed", False))
    # from/to are in `unit` (default meters); distances compare in meters
    # (GeoDistanceAggregationBuilder + DistanceUnit)
    unit_m = {
        "mm": 0.001, "cm": 0.01, "m": 1.0, "km": 1000.0,
        "in": 0.0254, "ft": 0.3048, "yd": 0.9144,
        "mi": 1609.344, "nmi": 1852.0, "NM": 1852.0,
    }.get(str(conf.get("unit", "m")), 1.0)
    cols = _geo_latlon(segments, field)

    # per-segment distance array (NaN = absent)
    dists = []
    for i, seg in enumerate(segments):
        if cols[i] is None:
            dists.append(None)
            continue
        lat, lon, present = cols[i]
        d = _haversine_m(o_lat, o_lon, lat, lon)
        dists.append(np.where(present, d, np.nan))

    buckets = []
    for r in ranges:
        frm = float(r["from"]) if r.get("from") is not None else None
        to = float(r["to"]) if r.get("to") is not None else None
        key = r.get("key")
        if key is None:
            key = (f"{frm if frm is not None else '*'}-"
                   f"{to if to is not None else '*'}")
        bucket_masks = []
        count = 0
        for i, seg in enumerate(segments):
            if dists[i] is None:
                bucket_masks.append(np.zeros(seg.n_docs, bool))
                continue
            d = dists[i]
            m = masks[i] & ~np.isnan(d)
            if frm is not None:
                m = m & (d >= frm * unit_m)
            if to is not None:
                m = m & (d < to * unit_m)
            bucket_masks.append(m)
            count += int(m.sum())
        bucket = {"key": key, "doc_count": count}
        if frm is not None:
            bucket["from"] = frm
        if to is not None:
            bucket["to"] = to
        bucket.update(_sub_aggs(sub, segments, ms, bucket_masks, filter_fn,
                                ext))
        buckets.append(bucket)
    if keyed:
        return {"buckets": {b.pop("key"): b for b in buckets}}
    return {"buckets": buckets}


_GEOHASH32 = "0123456789bcdefghjkmnpqrstuvwxyz"


def _geohash_cells(lat: np.ndarray, lon: np.ndarray,
                   precision: int) -> np.ndarray:
    """Vectorized geohash encode: 5*precision bisection steps as array ops
    (the bit-interleave of GeoHashUtils.longEncode), then one decode pass
    from packed int64 cell ids to strings."""
    nbits = 5 * precision
    packed = np.zeros(lat.shape, np.int64)
    lat_lo = np.full(lat.shape, -90.0)
    lat_hi = np.full(lat.shape, 90.0)
    lon_lo = np.full(lat.shape, -180.0)
    lon_hi = np.full(lat.shape, 180.0)
    for b in range(nbits):
        if b % 2 == 0:  # even bit: longitude
            mid = (lon_lo + lon_hi) / 2
            hi_half = lon >= mid
            lon_lo = np.where(hi_half, mid, lon_lo)
            lon_hi = np.where(hi_half, lon_hi, mid)
        else:
            mid = (lat_lo + lat_hi) / 2
            hi_half = lat >= mid
            lat_lo = np.where(hi_half, mid, lat_lo)
            lat_hi = np.where(hi_half, lat_hi, mid)
        packed = (packed << 1) | hi_half.astype(np.int64)
    cells = np.empty(lat.shape, object)
    shifts = [(precision - 1 - i) * 5 for i in range(precision)]
    for idx in range(lat.size):
        v = int(packed[idx])
        cells[idx] = "".join(
            _GEOHASH32[(v >> s) & 0x1F] for s in shifts)
    return cells


def _geotile_cells(lat: np.ndarray, lon: np.ndarray,
                   zoom: int) -> np.ndarray:
    """Vectorized web-mercator tile keys "z/x/y"
    (GeoTileUtils.longEncode)."""
    n = 1 << zoom
    x = np.clip(((lon + 180.0) / 360.0 * n).astype(np.int64), 0, n - 1)
    lat_r = np.radians(np.clip(lat, -85.05112878, 85.05112878))
    y_frac = (1.0 - np.log(np.tan(lat_r) + 1.0 / np.cos(lat_r))
              / np.pi) / 2.0
    y = np.clip((y_frac * n).astype(np.int64), 0, n - 1)
    cells = np.empty(lat.shape, object)
    for idx in range(lat.size):
        cells[idx] = f"{zoom}/{x[idx]}/{y[idx]}"
    return cells


def _geo_grid_agg(conf, sub, segments, ms, masks, filter_fn, ext,
                  cells_fn, default_precision) -> dict:
    field = conf["field"]
    precision = int(conf.get("precision", default_precision))
    size = int(conf.get("size", 10_000))
    cols = _geo_latlon(segments, field)

    # one vectorized cell-id pass per segment, then a bucket per distinct
    # cell (masks by array equality, no per-doc Python)
    seg_cells = []
    counts: dict[str, int] = {}
    for i, seg in enumerate(segments):
        if cols[i] is None:
            seg_cells.append(None)
            continue
        lat, lon, present = cols[i]
        m = masks[i] & present
        cells = np.empty(seg.n_docs, object)
        if m.any():
            cells[m] = cells_fn(lat[m], lon[m], precision)
        seg_cells.append((cells, m))
        uniq, cnt = np.unique(cells[m].astype(str), return_counts=True)
        for k, c in zip(uniq, cnt):
            counts[str(k)] = counts.get(str(k), 0) + int(c)
    ordered = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[:size]
    buckets = []
    for key, count in ordered:
        bucket_masks = []
        for i, seg in enumerate(segments):
            if seg_cells[i] is None:
                bucket_masks.append(np.zeros(seg.n_docs, bool))
                continue
            cells, m = seg_cells[i]
            bucket_masks.append(m & (cells == key))
        bucket = {"key": key, "doc_count": count}
        bucket.update(_sub_aggs(sub, segments, ms, bucket_masks, filter_fn,
                                ext))
        buckets.append(bucket)
    return {"buckets": buckets}


def _geohash_grid(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    return _geo_grid_agg(conf, sub, segments, ms, masks, filter_fn, ext,
                         _geohash_cells, default_precision=5)


def _geotile_grid(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    return _geo_grid_agg(conf, sub, segments, ms, masks, filter_fn, ext,
                         _geotile_cells, default_precision=7)


def _geo_bounds(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    field = conf["field"]
    cols = _geo_latlon(segments, field)
    lats, lons = [], []
    for i, seg in enumerate(segments):
        if cols[i] is None:
            continue
        lat, lon, present = cols[i]
        m = masks[i] & present
        lats.append(lat[m])
        lons.append(lon[m])
    lat_all = np.concatenate(lats) if lats else np.zeros(0)
    lon_all = np.concatenate(lons) if lons else np.zeros(0)
    if lat_all.size == 0:
        return {}
    return {"bounds": {
        "top_left": {"lat": float(lat_all.max()),
                     "lon": float(lon_all.min())},
        "bottom_right": {"lat": float(lat_all.min()),
                         "lon": float(lon_all.max())},
    }}


def _geo_centroid(conf, sub, segments, ms, masks, filter_fn, ext) -> dict:
    field = conf["field"]
    cols = _geo_latlon(segments, field)
    lats, lons = [], []
    for i, seg in enumerate(segments):
        if cols[i] is None:
            continue
        lat, lon, present = cols[i]
        m = masks[i] & present
        lats.append(lat[m])
        lons.append(lon[m])
    lat_all = np.concatenate(lats) if lats else np.zeros(0)
    lon_all = np.concatenate(lons) if lons else np.zeros(0)
    if lat_all.size == 0:
        return {"count": 0}
    return {
        "location": {"lat": float(lat_all.mean()),
                     "lon": float(lon_all.mean())},
        "count": int(lat_all.size),
    }


EXTENSION_AGGS.update({
    "geo_distance": _geo_distance_agg,
    "geohash_grid": _geohash_grid,
    "geotile_grid": _geotile_grid,
    "geo_bounds": _geo_bounds,
    "geo_centroid": _geo_centroid,
})


EXTENSION_AGGS.update({
    "significant_text": _significant_text,
    "ip_range": _ip_range,
    "extended_stats": _extended_stats,
    "percentiles": _percentiles,
    "percentile_ranks": _percentile_ranks,
    "median_absolute_deviation": _median_absolute_deviation,
    "weighted_avg": _weighted_avg,
    "top_hits": _top_hits,
    "scripted_metric": _scripted_metric,
    "matrix_stats": _matrix_stats,
    "multi_terms": _multi_terms,
    "rare_terms": _rare_terms,
    "significant_terms": _significant_terms,
    "sampler": _sampler,
    "diversified_sampler": _diversified_sampler,
    "adjacency_matrix": _adjacency_matrix,
    "date_range": _date_range,
    "composite": _composite,
    "auto_date_histogram": _auto_date_histogram,
})
