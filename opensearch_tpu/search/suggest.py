"""Suggesters: term, phrase, completion.

The analog of the reference's suggest subsystem (SURVEY.md §2.2 "Search,
per-shard": search/suggest/ — 52 files: TermSuggester (edit-distance
candidates from the term dictionary scored by similarity+frequency),
PhraseSuggester (candidate generation + ranking over token sequences),
CompletionSuggester (FST prefix matching)). Host-side compute: the term
dictionaries already live on the host side of each segment
(HostTextField.terms / HostKeywordField.ord_values), so suggestion never
touches the device — same division as the reference, where suggesters run
on Lucene's terms enum, not the scorer.
"""

from __future__ import annotations

from typing import Any

from opensearch_tpu.common.errors import ParsingException


def _damerau_osa(a: str, b: str, cap: int) -> int:
    """Optimal-string-alignment distance with early cap."""
    la, lb = len(a), len(b)
    if abs(la - lb) > cap:
        return cap + 1
    prev2: list[int] = []
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        row_min = cur[0]
        for j in range(1, lb + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if i > 1 and j > 1 and a[i - 1] == b[j - 2] and a[i - 2] == b[j - 1]:
                cur[j] = min(cur[j], prev2[j - 2] + 1)
            row_min = min(row_min, cur[j])
        if row_min > cap:
            return cap + 1
        prev2, prev = prev, cur
    return prev[lb]


def _term_stats(segments: list, field: str) -> dict[str, int]:
    """term -> doc freq across the shard's segments (text or keyword)."""
    freqs: dict[str, int] = {}
    for host, _dev in segments:
        tf = host.text_fields.get(field)
        if tf is not None:
            for term in tf.terms:
                freqs[term] = freqs.get(term, 0) + tf.doc_freq(term)
            continue
        kf = host.keyword_fields.get(field)
        if kf is not None:
            import numpy as np

            counts = np.bincount(
                kf.mv_ords[kf.mv_ords >= 0], minlength=len(kf.ord_values)
            )
            for ord_, value in enumerate(kf.ord_values):
                freqs[value] = freqs.get(value, 0) + int(counts[ord_])
    return freqs


def _suggest_terms_for(
    text: str, freqs: dict[str, int], max_edits: int, size: int,
    prefix_length: int = 1,
) -> list[dict]:
    out = []
    for term, freq in freqs.items():
        if term == text or freq <= 0:
            continue
        if prefix_length and term[:prefix_length] != text[:prefix_length]:
            continue
        dist = _damerau_osa(text, term, max_edits)
        if dist > max_edits:
            continue
        score = 1.0 - dist / max(len(text), len(term), 1)
        out.append({"text": term, "score": round(score, 6), "freq": freq})
    out.sort(key=lambda o: (-o["score"], -o["freq"], o["text"]))
    return out[:size]


def _analyze_token(token: str, field: str, mapper_services: list) -> str | None:
    """Analyzed form of one raw token via the field's analyzer (None when
    the analyzer eats it, e.g. a stopword); falls back to lowercasing for
    unmapped / non-text fields so keyword corpora still work."""
    for ms in mapper_services:
        mapper = ms.field_mapper(field)
        if mapper is not None:
            if mapper.type != "text":
                return token
            terms = ms.analyze_query_text(field, token)
            return terms[0] if terms else None
    return token.lower()


def compute_suggest(
    suggest_body: dict, shards_segments: list[list], mapper_services: list,
) -> dict[str, Any]:
    """suggest_body: {name: {text, term|phrase|completion: {...}}}.

    shards_segments[i] is shard i's [(host, dev), ...]; suggestions reduce
    over all shards (doc-freq summed), like the coordinator's suggest
    reduce (search/suggest/Suggest.java group-and-merge)."""
    global_text = suggest_body.get("text")
    out: dict[str, Any] = {}
    all_segments = [seg for segs in shards_segments for seg in segs]
    for name, conf in suggest_body.items():
        if name == "text":
            continue
        if not isinstance(conf, dict):
            raise ParsingException(f"suggestion [{name}] must be an object")
        kinds = [k for k in ("term", "phrase", "completion") if k in conf]
        if len(kinds) != 1:
            raise ParsingException(
                f"suggestion [{name}] requires exactly one of "
                "[term, phrase, completion]"
            )
        kind = kinds[0]
        sconf = conf[kind] or {}
        text = conf.get("text", global_text)
        if text is None and kind != "completion":
            raise ParsingException(f"suggestion [{name}] requires [text]")
        if kind == "completion":
            text = conf.get("prefix", text)
            if text is None:
                raise ParsingException(
                    f"completion suggestion [{name}] requires [prefix]"
                )
        field = sconf.get("field")
        if not field:
            raise ParsingException(f"suggestion [{name}] requires [field]")
        size = int(sconf.get("size", 5))
        if kind == "term":
            out[name] = _term_suggest(
                text, field, sconf, size, all_segments, mapper_services
            )
        elif kind == "phrase":
            out[name] = _phrase_suggest(
                text, field, sconf, size, all_segments, mapper_services
            )
        else:
            out[name] = _completion_suggest(text, field, size, all_segments)
    return out


def _term_suggest(text, field, sconf, size, segments,
                  mapper_services=()) -> list[dict]:
    max_edits = min(int(sconf.get("max_edits", 2)), 2)
    prefix_length = int(sconf.get("prefix_length", 1))
    freqs = _term_stats(segments, field)
    entries = []
    offset = 0
    for token in str(text).split():
        analyzed = _analyze_token(token, field, mapper_services)
        options = (
            _suggest_terms_for(analyzed, freqs, max_edits, size, prefix_length)
            if analyzed is not None else []
        )
        # suggest_mode=missing (default): only suggest for unknown terms
        mode = sconf.get("suggest_mode", "missing")
        if (mode == "missing" and analyzed is not None
                and freqs.get(analyzed, 0) > 0):
            options = []
        entries.append({
            "text": token, "offset": offset, "length": len(token),
            "options": options,
        })
        offset += len(token) + 1
    return entries


def _phrase_suggest(text, field, sconf, size, segments,
                    mapper_services=()) -> list[dict]:
    """Greedy best-correction-per-token phrase candidates."""
    freqs = _term_stats(segments, field)
    raw = str(text).split()
    tokens = [
        t for t in (
            _analyze_token(tok, field, mapper_services) for tok in raw
        ) if t is not None
    ]
    per_token: list[list[tuple[str, float]]] = []
    for tok in tokens:
        if freqs.get(tok, 0) > 0:
            per_token.append([(tok, 1.0)])
            continue
        cands = _suggest_terms_for(tok, freqs, 2, 3)
        per_token.append(
            [(c["text"], c["score"]) for c in cands] or [(tok, 0.1)]
        )
    # beam over per-token candidates (width = size)
    beams: list[tuple[list[str], float]] = [([], 1.0)]
    for cands in per_token:
        beams = [
            (path + [w], score * s)
            for path, score in beams
            for w, s in cands
        ]
        beams.sort(key=lambda b: -b[1])
        beams = beams[: max(size, 5)]
    options = []
    seen = set()
    for path, score in beams:
        phrase = " ".join(path)
        if phrase == " ".join(tokens) or phrase in seen:
            continue
        seen.add(phrase)
        options.append({"text": phrase, "score": round(score, 6)})
    return [{
        "text": text, "offset": 0, "length": len(str(text)),
        "options": options[:size],
    }]


def _completion_suggest(prefix, field, size, segments) -> list[dict]:
    """Prefix match over completion inputs, ranked by (-weight, text) like
    the reference FST suggester (weight defaults to 1 when unset)."""
    prefix_l = str(prefix).lower()
    matches: dict[str, int] = {}
    for host, _dev in segments:
        kf = host.keyword_fields.get(field)
        values: list[str] = []
        if kf is not None:
            values = kf.ord_values
        else:
            tf = host.text_fields.get(field)
            if tf is not None:
                values = tf.terms
        weights = host.completion_weights.get(field, {})
        for v in values:
            if v.lower().startswith(prefix_l):
                w = int(weights.get(v, 1))
                matches[v] = max(matches.get(v, 0), w)
    ranked = sorted(matches.items(), key=lambda kv: (-kv[1], kv[0]))
    return [{
        "text": prefix, "offset": 0, "length": len(str(prefix)),
        "options": [
            {"text": v, "_id": None, "_index": None, "score": float(w)}
            for v, w in ranked[:size]
        ],
    }]
