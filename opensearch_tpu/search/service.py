"""Search service: query phase -> reduce -> fetch phase -> response.

The single-host analog of the coordinator pipeline (SURVEY.md §3.2):
TransportSearchAction fan-out → per-shard QueryPhase →
SearchPhaseController.reducedQueryPhase (merge top docs + aggs) →
FetchSearchPhase (fetch only winning doc ids) → final SearchResponse merge.

Here the per-shard query phase runs the device executor; the reduce is a
host merge with the exact OpenSearch tie-break (score desc, shard asc, doc
asc); aggregations reduce across all shards' segments in one pass. The
multi-chip path (parallel/) replaces the host merge with an on-device
all_gather + top_k over the mesh.
"""

from __future__ import annotations

import fnmatch
import json
import logging
import time
from typing import Any

import numpy as np

from opensearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
)
from opensearch_tpu.index.shard import IndexShard
from opensearch_tpu.search import fetch, profile as search_profile, query_dsl

logger = logging.getLogger(__name__)
from opensearch_tpu.search.aggs import compute_aggs
from opensearch_tpu.search.executor import (
    SegmentExecutor,
    ShardContext,
    ShardQueryResult,
    _sort_key_fn,
    _sort_spec,
    _StrKey,
    execute_query_phase,
)

DEFAULT_SIZE = 10


def pack_shard_doc(shard_idx: int, segment: int, doc: int) -> int:
    """_shard_doc PIT tiebreak value: (shard, segment, doc) packed into one
    orderable int that round-trips through search_after cursors.

    Bit layout 13/13/27 (shard/segment/doc): doc must clear 2^21 (~2.1M
    docs/segment corpora are in BASELINE scope) and the TOTAL must stay
    under 2^53 so float64 JSON clients echo the cursor exactly — 32 shards
    at a 48-bit shard shift would already cross 2^53.
    """
    return (shard_idx << 40) | (segment << 27) | doc


def _sort_has_score(sort) -> bool:
    return any(
        (spec if isinstance(spec, str) else next(iter(spec), None)) == "_score"
        for spec in (sort or [])
    )


def search(
    shards: list[IndexShard],
    body: dict | None,
    acquired: list | None = None,
    phase_results_config: dict | None = None,
    shard_filters: list | None = None,
    task=None,
    partial: bool = False,
    shard_numbers: list[int] | None = None,
    index_boosts: dict | None = None,
    precomputed_results: list | None = None,
) -> dict[str, Any]:
    """Run one search over `shards`. `acquired` optionally pins the searcher
    snapshots to use, one per shard in order — the scroll/PIT path
    (ReaderContext.java:64 analog: the context owns the snapshots, so pages
    see one immutable point-in-time view regardless of refreshes).

    `partial=True` produces a per-NODE wire partial for the cluster
    coordinator (QuerySearchResult analog): hits carry a `_tb` tie-break
    triple [global_shard, segment, doc] (global shard numbers supplied via
    `shard_numbers`), aggregations carry `_p_*` reduce extras, and pipeline
    aggregations are deferred to the coordinator's final reduce
    (search/reduce.py — InternalAggregations.reduce:162 semantics)."""
    t0 = time.monotonic()
    body = body or {}
    known_keys = {
        "query", "size", "from", "sort", "_source", "aggs", "aggregations",
        "track_total_hits", "min_score", "search_after", "timeout", "version",
        "seq_no_primary_term", "stored_fields", "explain", "highlight",
        "docvalue_fields", "fields", "script_fields", "suggest", "profile",
        "rescore", "collapse", "slice", "indices_boost",
        "include_named_queries_score", "pre_filter_shard_size",
        "stats",  # per-request stat groups (surfaced by indices.stats)
    }
    unknown = set(body) - known_keys
    if unknown:
        raise ParsingException(f"unknown search request keys {sorted(unknown)}")

    node = query_dsl.parse_query(body.get("query"))
    if body.get("slice") is not None:
        # sliced scroll: partition the doc space by murmur3(_id) % max
        # (search/slice/SliceBuilder.java)
        sl = body["slice"]
        sl_max = int(sl.get("max", 1))
        sl_id = int(sl.get("id", 0))
        if not 0 <= sl_id < sl_max:
            raise ParsingException(
                f"[slice.id] must be in [0, {sl_max}) but was {sl_id}"
            )
        node = query_dsl.BoolQuery(
            must=[node],
            filter=[query_dsl.SliceQuery(id=sl_id, max=sl_max)],
        )
    size = int(body.get("size", DEFAULT_SIZE))
    from_ = int(body.get("from", 0))
    sort = body.get("sort")
    if isinstance(sort, (str, dict)):
        sort = [sort]
    aggs_body = body.get("aggs") or body.get("aggregations")
    if aggs_body:
        from opensearch_tpu.search.aggs_pipeline import (
            validate_pipeline_aggs,
        )

        validate_pipeline_aggs(aggs_body)
    min_score = body.get("min_score")
    search_after = body.get("search_after")
    if search_after is not None and not sort:
        raise ParsingException("[search_after] requires [sort] to be set")
    if search_after is not None and from_ > 0:
        raise ParsingException(
            "[from] parameter must be set to 0 when [search_after] is used"
        )
    track_total = body.get("track_total_hits", True)

    # per-shard alias filters (the aliasFilter of ShardSearchRequest):
    # parse each distinct filter body once, AND it into that shard's query
    filter_nodes: list = [None] * len(shards)
    if shard_filters:
        parsed_cache: dict[int, Any] = {}
        for i, f in enumerate(shard_filters[: len(shards)]):
            if f is not None:
                key = id(f)
                if key not in parsed_cache:
                    parsed_cache[key] = query_dsl.parse_query(f)
                filter_nodes[i] = parsed_cache[key]

    def _shard_node(base: Any, shard_i: int) -> Any:
        f = filter_nodes[shard_i]
        if f is None:
            return base
        return query_dsl.BoolQuery(must=[base], filter=[f])

    want_profile = bool(body.get("profile"))
    shard_query_ns: list[int] = []
    # one deep profiler per shard (search/profile.ShardProfiler): operator
    # tree + device kernel time + transfer bytes + retrace flag
    shard_profilers: list = []
    skipped_shards = 0

    # set when the shard-mesh device path ran: the flat device-merged rows
    # (so the host re-sort below can be skipped) and launch attribution
    mesh_premerged: list | None = None
    mesh_launch: dict | None = None

    fetch_k = from_ + size
    if body.get("rescore") is not None:
        # the query phase must collect the full rescore window
        stages = body["rescore"]
        stages = stages if isinstance(stages, list) else [stages]
        for stage in stages:
            if isinstance(stage, dict):
                fetch_k = max(fetch_k, int(stage.get("window_size", 10)))
    if isinstance(node, query_dsl.HybridQuery):
        # hybrid query phase: one pass per sub-query, then the phase-results
        # processor fuses scores GLOBALLY across shards before fetch (the
        # SearchPhaseResultsProcessor slot, search/pipeline/)
        if sort:
            raise ParsingException("[sort] is not supported with [hybrid] query")
        if search_after is not None:
            raise ParsingException(
                "[search_after] is not supported with [hybrid] query"
            )
        from opensearch_tpu.search import pipeline as pipeline_mod

        shard_snaps = []
        per_shard_subs = []
        for shard_i, shard in enumerate(shards):
            if task is not None:
                task.ensure_not_cancelled()
            snapshot = (
                acquired[shard_i] if acquired is not None
                else shard.acquire_searcher()
            )
            prof = search_profile.ShardProfiler() if want_profile else None
            t_q = time.perf_counter_ns()
            with search_profile.profiling(prof):
                per_shard_subs.append([
                    execute_query_phase(
                        snapshot,
                        shard.mapper_service,
                        _shard_node(sub, shard_i),
                        size=fetch_k,
                        need_masks=aggs_body is not None,
                        min_score=(
                            float(min_score) if min_score is not None else None
                        ),
                    )
                    for sub in node.queries
                ])
            if want_profile:
                shard_query_ns.append(time.perf_counter_ns() - t_q)
                shard_profilers.append(prof)
            shard_snaps.append((shard, snapshot))
        fused = pipeline_mod.fuse_hybrid_results(
            per_shard_subs, phase_results_config, fetch_k
        )
        per_shard_results = [
            (shard, snap, res)
            for (shard, snap), res in zip(shard_snaps, fused)
        ]
    else:
        # a batched msearch dispatch may have already run the query phase
        # for this body (one device launch for B queries — see
        # try_batched_knn_msearch); inject its per-shard results and skip
        # straight to reduce/fetch
        per_shard_results = precomputed_results
        if per_shard_results is None:
            mesh_out = _try_distributed_query_phase(
                shards, acquired, node,
                sort=sort, search_after=search_after, aggs_body=aggs_body,
                min_score=min_score, filter_nodes=filter_nodes,
                want_profile=want_profile, fetch_k=fetch_k, task=task,
            )
            if mesh_out is not None:
                per_shard_results, mesh_premerged, mesh_launch = mesh_out
                if want_profile:
                    # per-shard attribution of the ONE sharded launch: each
                    # shard profiler carries its share of the fenced wall
                    # and the shared launch_id (profile.py)
                    desc = search_profile.describe_node(node)
                    qbytes = 4 * len(getattr(node, "vector", ()))
                    # a coalesced launch served `merged` queries: this
                    # query's share of the fenced wall is wall/merged (the
                    # executor path applies the same split via
                    # BatchOutcome.kernel_share_ns) — attributing the full
                    # wall to every member would read as merged x the real
                    # device time
                    query_wall_ns = (mesh_launch["wall_ns"]
                                     // max(mesh_launch.get("merged", 1), 1))
                    for _ in per_shard_results:
                        prof = search_profile.ShardProfiler()
                        prof.record_sharded_launch(
                            type(node).__name__, desc,
                            name="shard_mesh_knn",
                            launch_id=mesh_launch["launch_id"],
                            shards=mesh_launch["shards"],
                            wall_ns=query_wall_ns,
                            transfer_bytes=qbytes,
                            retraced=mesh_launch["retraced"],
                        )
                        shard_profilers.append(prof)
                        shard_query_ns.append(
                            query_wall_ns
                            // max(mesh_launch["shards"], 1)
                        )
        if per_shard_results is None:
            per_shard_results = []
            for shard_i, shard in enumerate(shards):
                # cooperative cancellation at the phase boundary — between
                # device program launches (TaskCancellationService model)
                if task is not None:
                    task.ensure_not_cancelled()
                snapshot = acquired[shard_i] if acquired is not None else shard.acquire_searcher()
                # can_match pre-filter (CanMatchPreFilterSearchPhase): skip
                # shards whose segment min/max PROVE no doc matches
                from opensearch_tpu.search import phases

                prof = (search_profile.ShardProfiler()
                        if want_profile else None)
                t_rw = time.perf_counter_ns()
                matched = phases.can_match(
                    snapshot, shard.mapper_service, _shard_node(node, shard_i)
                )
                if prof is not None:
                    # can_match is this engine's rewrite step
                    prof.rewrite_ns += time.perf_counter_ns() - t_rw
                if not matched:
                    n_segs = len(snapshot.segments)
                    result = ShardQueryResult(
                        hits=[], total=0, max_score=None,
                        masks=[
                            np.zeros(h.n_docs, bool)
                            for h, _d in snapshot.segments
                        ] if aggs_body is not None else [],
                        score_arrays=[
                            np.zeros(h.n_docs, np.float32)
                            for h, _d in snapshot.segments
                        ] if aggs_body is not None else [],
                    )
                    skipped_shards += 1
                    if want_profile:
                        shard_query_ns.append(0)
                        shard_profilers.append(prof)
                    per_shard_results.append((shard, snapshot, result))
                    continue
                t_q = time.perf_counter_ns()
                with search_profile.profiling(prof):
                    result = execute_query_phase(
                        snapshot,
                        shard.mapper_service,
                        _shard_node(node, shard_i),
                        # search_after cursors can reach arbitrarily deep into a
                        # shard; fall back to all matching docs per shard
                        size=snapshot.max_doc if search_after is not None else fetch_k,
                        sort=sort,
                        need_masks=aggs_body is not None,
                        min_score=float(min_score) if min_score is not None else None,
                    )
                if want_profile:
                    shard_query_ns.append(time.perf_counter_ns() - t_q)
                    shard_profilers.append(prof)
                per_shard_results.append((shard, snapshot, result))

    # ---- reduce phase (SearchPhaseController analog) ----
    if index_boosts is None and isinstance(body.get("indices_boost"), dict):
        index_boosts = body["indices_boost"]
    if index_boosts:
        # indices_boost: per-index score multiplier applied before the
        # cross-shard merge (SearchService applies it as a query-level
        # boost on each shard)
        for shard, _snapshot, result in per_shard_results:
            factor = index_boosts.get(shard.shard_id.index)
            if factor is None or factor == 1.0:
                continue
            for h in result.hits:
                h.score *= factor
            if result.max_score is not None:
                result.max_score *= factor
    merged = []
    total = 0
    max_score = None
    for shard_idx, (shard, snapshot, result) in enumerate(per_shard_results):
        total += result.total
        if result.max_score is not None and (
            max_score is None or result.max_score > max_score
        ):
            max_score = result.max_score
        for h in result.hits:
            merged.append((shard_idx, h))
    if sort:
        # _shard_doc: the global PIT tiebreak value (shard, segment, doc)
        # packed into one int so cursors round-trip through search_after
        for i, spec in enumerate(sort):
            fname = spec if isinstance(spec, str) else next(iter(spec), None)
            if fname != "_shard_doc":
                continue
            for shard_idx, h in merged:
                packed = pack_shard_doc(shard_idx, h.segment, h.doc)
                while len(h.sort_values) <= i:
                    h.sort_values.append(None)
                h.sort_values[i] = packed
    used_premerged = False
    if not sort:
        if mesh_premerged is not None and not index_boosts:
            # the device launch already merged: its row order is exactly
            # (-score, shard asc, segment asc, doc asc) — the host re-sort
            # is redundant work (search/reduce.py applies the same skip at
            # the cross-node layer via the _premerged flag)
            merged = mesh_premerged
            used_premerged = True
        else:
            merged.sort(
                key=lambda sh: (-sh[1].score, sh[0], sh[1].segment, sh[1].doc)
            )
    else:
        key_fn = _sort_key_fn(sort)
        merged.sort(key=lambda sh: key_fn(sh[1]))
        if search_after is not None:
            ms_view = _MultiMapperView([s.mapper_service for s in shards]) \
                if shards else None
            cursor = _search_after_key(
                sort,
                _coerce_search_after(sort, search_after, ms_view)
                if ms_view is not None else search_after,
            )
            merged = [
                sh for sh in merged if _sort_values_key(sort, sh[1]) > cursor
            ]
    collapse_values: list | None = None
    collapse_field: str | None = None
    collapse_inner: list | None = None
    if body.get("rescore") is not None or body.get("collapse") is not None:
        from opensearch_tpu.search import phases

        # these phases re-rank/regroup AFTER the device merge: the page no
        # longer follows the canonical (-score, _tb) order, so the
        # coordinator must re-sort (never stream-merge) these partials
        used_premerged = False
        if body.get("rescore") is not None:
            if sort:
                raise ParsingException(
                    "[rescore] cannot be used with a [sort]"
                )
            merged = phases.apply_rescore(
                body["rescore"], merged, per_shard_results, shards
            )
        if body.get("collapse") is not None:
            (merged, collapse_field, collapse_values,
             collapse_inner) = phases.apply_collapse(
                body["collapse"], merged, per_shard_results
            )
    page = merged[from_ : from_ + size]

    # ---- fetch phase (only winning docs; sub-phase chain in fetch.py) ----
    fields_specs = body.get("fields")
    stored_specs = body.get("stored_fields")
    if isinstance(stored_specs, str):
        stored_specs = [stored_specs]
    stored_none = stored_specs == ["_none_"]
    if stored_none:
        stored_specs = None
    if fields_specs:
        for sh in shards:
            if not sh.mapper_service._source_enabled:
                raise IllegalArgumentException(
                    f"Unable to retrieve the requested [fields] since "
                    f"_source is disabled in the mappings for index "
                    f"[{sh.shard_id.index}]"
                )
        for spec in fields_specs:
            if isinstance(spec, dict) and spec.get("format"):
                fname = spec.get("field", "")
                for sh in shards:
                    m = sh.mapper_service.field_mapper(fname)
                    if m is not None and m.type not in ("date",):
                        raise IllegalArgumentException(
                            f"Field [{fname}] of type "
                            f"[{m.original_type or m.type}] doesn't "
                            f"support formats."
                        )
    # stored_fields without an explicit _source suppresses _source in hits
    # (RestSearchAction's storedFieldsContext default)
    _src_spec = body.get(
        "_source",
        True if (stored_specs is None and not stored_none)
        or (stored_specs and "_source" in stored_specs) else False,
    )
    source_filter = _source_filter(_src_spec)
    highlight_conf = body.get("highlight")
    docvalue_specs = body.get("docvalue_fields")
    want_explain = bool(body.get("explain"))
    want_version = bool(body.get("version"))
    want_seqno = bool(body.get("seq_no_primary_term"))
    script_fields = body.get("script_fields") or {}
    compiled_scripts = {}
    if script_fields:
        from opensearch_tpu.script import default_script_service

        for sf_name, sf_conf in script_fields.items():
            compiled_scripts[sf_name] = default_script_service.compile(
                (sf_conf or {}).get("script") or {}
            )
    preds_by_field: dict = {}
    if highlight_conf:
        ms_for_hl = _MultiMapperView([s.mapper_service for s in shards])
        preds_by_field = fetch.field_term_predicates(node, ms_for_hl)
    # named queries (matched_queries): collect from the main tree and any
    # rescore stages; evaluated per (shard, segment) lazily below
    named_nodes = [n for n in query_dsl.iter_query_nodes(node) if n.name]
    for stage in (body.get("rescore") if isinstance(body.get("rescore"), list)
                  else [body["rescore"]] if body.get("rescore") else []):
        rq = ((stage or {}).get("query") or {}).get("rescore_query")
        if rq is not None:
            try:
                rnode = query_dsl.parse_query(rq)
            except ParsingException:
                continue
            named_nodes.extend(
                n for n in query_dsl.iter_query_nodes(rnode) if n.name
            )
    include_nq_scores = str(
        body.get("include_named_queries_score", "false")
    ).lower() in ("true", "")
    named_cache: dict = {}
    # fetch-phase sub-phase profiler: times source load / highlight /
    # stored+doc-value fields per shard, the way the operator tree covers
    # the query phase (profile.shards[*].fetch)
    fetch_prof = (search_profile.FetchProfiler(len(per_shard_results))
                  if want_profile else None)
    _now_ns = time.perf_counter_ns
    hits_json = []
    for page_i, (shard_idx, h) in enumerate(page):
        shard, snapshot, _ = per_shard_results[shard_idx]
        host = snapshot.segments[h.segment][0]
        ms = shard.mapper_service
        if fetch_prof is not None:
            fetch_prof.hit(shard_idx)
        doc_id = host.doc_ids[h.doc]
        hit: dict[str, Any] = {
            "_index": shard.shard_id.index,
            "_id": doc_id,
            "_score": h.score if (not sort or _sort_has_score(sort)) else None,
        }
        if stored_none:
            # stored_fields: _none_ drops per-hit metadata (_id/_source)
            hit.pop("_id", None)
        doc_routing = host.doc_routings[h.doc] if host.doc_routings else None
        if doc_routing is not None:
            hit["_routing"] = doc_routing
        ig = host.keyword_fields.get("_ignored")
        if ig is not None:
            s_, e_ = int(ig.mv_offsets[h.doc]), int(ig.mv_offsets[h.doc + 1])
            if e_ > s_:
                hit["_ignored"] = sorted(
                    ig.ord_values[int(o)] for o in ig.mv_ords[s_:e_]
                )
        _t0 = _now_ns() if fetch_prof is not None else 0
        raw_source = json.loads(host.sources[h.doc])
        src = source_filter(raw_source)
        if src is not None:
            hit["_source"] = src
        if fetch_prof is not None:
            fetch_prof.add(shard_idx, "load_source", _t0)
        if sort:
            hit["sort"] = h.sort_values
        if docvalue_specs:
            _t0 = _now_ns() if fetch_prof is not None else 0
            dv = fetch.docvalue_fields_for_doc(docvalue_specs, host, h.doc, ms)
            if dv:
                hit.setdefault("fields", {}).update(dv)
            if fetch_prof is not None:
                fetch_prof.add(shard_idx, "docvalue_fields", _t0)
        if fields_specs:
            _t0 = _now_ns() if fetch_prof is not None else 0
            fv = fetch.fields_option_for_doc(fields_specs, raw_source, host, h.doc, ms)
            if fv:
                hit.setdefault("fields", {}).update(fv)
            if fetch_prof is not None:
                fetch_prof.add(shard_idx, "fields", _t0)
        if stored_specs:
            # explicitly stored fields surface under "fields" (stored-field
            # loading reads the segment columns in this engine)
            _t0 = _now_ns() if fetch_prof is not None else 0
            for sf in stored_specs:
                if sf in ("_source", "_id", "_routing", "*"):
                    continue
                m_sf = ms.field_mapper(sf)
                if m_sf is None or not m_sf.store:
                    continue
                vals = fetch._doc_column_values(host, h.doc, sf, ms, None)
                if vals:
                    hit.setdefault("fields", {})[sf] = vals
            if fetch_prof is not None:
                fetch_prof.add(shard_idx, "stored_fields", _t0)
        if highlight_conf:
            _t0 = _now_ns() if fetch_prof is not None else 0
            hl = fetch.compute_highlight(highlight_conf, preds_by_field, raw_source, ms)
            if hl:
                hit["highlight"] = hl
            if fetch_prof is not None:
                fetch_prof.add(shard_idx, "highlight", _t0)
        if script_fields:
            from opensearch_tpu.script import default_script_service

            _t0 = _now_ns() if fetch_prof is not None else 0
            for sf_name, (ast, sf_params) in compiled_scripts.items():
                val = default_script_service.field(
                    ast, sf_params, host, h.doc, ms, source=raw_source
                )
                hit.setdefault("fields", {})[sf_name] = (
                    val if isinstance(val, list) else [val]
                )
            if fetch_prof is not None:
                fetch_prof.add(shard_idx, "script_fields", _t0)
        if want_explain:
            _t0 = _now_ns() if fetch_prof is not None else 0
            hit["_explanation"] = fetch.explain_for_hit(h.score, node)
            if fetch_prof is not None:
                fetch_prof.add(shard_idx, "explain", _t0)
        if want_version or want_seqno:
            # read from the pinned snapshot's seal-time doc-values, not the
            # live version_map — scroll/PIT hits must report the version of
            # the _source they carry
            if want_version:
                hit["_version"] = int(host.doc_versions[h.doc])
            if want_seqno:
                hit["_seq_no"] = int(host.doc_seq_nos[h.doc])
                hit["_primary_term"] = 1
        if named_nodes:
            mq: dict[str, float] = {}
            for nn in named_nodes:
                key = (shard_idx, h.segment, id(nn))
                if key not in named_cache:
                    ctx_n = ShardContext(snapshot, ms)
                    dev = snapshot.segments[h.segment][1]
                    r = SegmentExecutor(ctx_n, host, dev).execute(nn)
                    named_cache[key] = (
                        np.asarray(r.mask), np.asarray(r.scores)
                    )
                n_mask, n_scores = named_cache[key]
                if h.doc < len(n_mask) and n_mask[h.doc]:
                    mq[nn.name] = float(n_scores[h.doc])
            if mq:
                hit["matched_queries"] = (
                    mq if include_nq_scores else sorted(mq)
                )
        if collapse_field is not None:
            value = collapse_values[from_ + page_i]
            hit.setdefault("fields", {})[collapse_field] = [value]
            inner_map = (collapse_inner[from_ + page_i]
                         if collapse_inner else None)
            if inner_map:
                ih_json: dict[str, Any] = {}
                for name, g in inner_map.items():
                    sub_hits = []
                    best = None
                    for s_i, h_ in g["hits"]:
                        sh_shard, sh_snap, _ = per_shard_results[s_i]
                        sh_host = sh_snap.segments[h_.segment][0]
                        spec = g["spec"]
                        sub: dict[str, Any] = {
                            "_index": sh_shard.shard_id.index,
                            "_id": sh_host.doc_ids[h_.doc],
                            "_score": h_.score,
                            "_source": json.loads(sh_host.sources[h_.doc]),
                        }
                        if spec.get("version"):
                            sub["_version"] = int(sh_host.doc_versions[h_.doc])
                        if spec.get("seq_no_primary_term"):
                            sub["_seq_no"] = int(sh_host.doc_seq_nos[h_.doc])
                            sub["_primary_term"] = 1
                        if spec.get("fields") or spec.get("docvalue_fields"):
                            fv = fetch.docvalue_fields_for_doc(
                                spec.get("fields")
                                or spec.get("docvalue_fields"),
                                sh_host, h_.doc, sh_shard.mapper_service,
                            )
                            if fv:
                                sub["fields"] = fv
                        if best is None or (h_.score or 0) > best:
                            best = h_.score
                        sub_hits.append(sub)
                    ih_json[name] = {"hits": {
                        "total": {"value": g["total"], "relation": "eq"},
                        "max_score": best,
                        "hits": sub_hits,
                    }}
                hit["inner_hits"] = ih_json
        if partial:
            gshard = (
                shard_numbers[shard_idx] if shard_numbers is not None
                else shard.shard_id.shard
            )
            hit["_tb"] = [gshard, h.segment, h.doc]
        hits_json.append(hit)

    sort_by_score = bool(sort) and _sort_has_score(sort)
    if sort_by_score and max_score is None and merged:
        max_score = max(h.score for _i, h in merged)
    hits_obj: dict[str, Any] = {
        "max_score": max_score if (not sort or sort_by_score) else None,
        "hits": hits_json,
    }
    # track_total_hits: True -> exact; int N -> capped with relation gte;
    # False -> no total object (the reference's contract)
    if track_total is True:
        hits_obj["total"] = {"value": total, "relation": "eq"}
    elif track_total is not False:
        cap = int(track_total)
        hits_obj["total"] = (
            {"value": cap, "relation": "gte"} if total > cap
            else {"value": total, "relation": "eq"}
        )
    response: dict[str, Any] = {
        "took": int((time.monotonic() - t0) * 1000),
        "timed_out": False,
        "_shards": {
            "total": len(shards),
            "successful": len(shards),
            # the reference only PRE-filters (and reports skips) beyond
            # pre_filter_shard_size (default 128); below it can_match runs
            # inside the query phase and skipped stays 0
            "skipped": (skipped_shards
                        if len(shards) >= int(
                            body.get("pre_filter_shard_size", 128) or 128)
                        else 0),
            "failed": 0,
        },
        "hits": hits_obj,
    }

    # ---- aggregations (reduce across every shard's segments) ----
    agg_profiler = None
    if aggs_body:
        all_segments = []
        all_masks = []
        all_scores = []
        seg_meta = []
        seg_ctx: list[tuple[ShardContext, int]] = []  # (shard ctx, seg idx in shard)
        for shard_idx, (shard, snapshot, result) in enumerate(per_shard_results):
            ctx = ShardContext(snapshot, shard.mapper_service)
            for seg_i, (host, dev) in enumerate(snapshot.segments):
                all_segments.append(host)
                all_masks.append(result.masks[seg_i])
                all_scores.append(
                    result.score_arrays[seg_i]
                    if seg_i < len(result.score_arrays) else None
                )
                seg_meta.append({"index": shard.shard_id.index})
                seg_ctx.append((ctx, seg_i))

        def filter_fn(filter_body: dict, flat_idx: int) -> np.ndarray:
            ctx, seg_i = seg_ctx[flat_idx]
            host, dev = ctx.snapshot.segments[seg_i]
            ex = SegmentExecutor(ctx, host, dev)
            f_node = query_dsl.parse_query(filter_body)
            return np.asarray(ex.execute(f_node).mask)

        # multi-index search: resolve field types across every index's
        # mappings (first index to map the field wins, like the reference's
        # field-caps conflict handling)
        mapper_service = _MultiMapperView([s.mapper_service for s in shards])
        # aggregations reduce across every shard's segments in ONE pass, so
        # their collector timings are request-level: a dedicated profiler
        # collects real per-agg wall times for the profile response
        if want_profile:
            agg_profiler = search_profile.ShardProfiler()
        with search_profile.profiling(agg_profiler):
            response["aggregations"] = compute_aggs(
                all_segments, mapper_service, aggs_body, all_masks, filter_fn,
                ext={"scores": all_scores, "seg_meta": seg_meta,
                     "partial": partial},
            )
        # pipeline aggregations run once, at final reduce — for a cluster
        # partial that reduce happens on the coordinator, not here
        if not partial:
            from opensearch_tpu.search.aggs_pipeline import apply_pipeline_aggs

            apply_pipeline_aggs(aggs_body, response["aggregations"])
        # search.max_buckets guard (MultiBucketConsumerService analog):
        # bound coordinator memory for deeply-bucketed aggs
        n_buckets = _count_buckets(response["aggregations"])
        if n_buckets > MAX_BUCKETS:
            raise TooManyBucketsException(n_buckets)

    if body.get("suggest"):
        from opensearch_tpu.search.suggest import compute_suggest

        response["suggest"] = compute_suggest(
            body["suggest"],
            [snap.segments for _, snap, _ in per_shard_results],
            [s.mapper_service for s in shards],
        )

    if partial:
        # stamp the reader generation each shard's result was computed
        # from: one snapshot per shard, acquired once for the whole
        # request. The chaos-soak invariant checker
        # (testing/soak.py) asserts a response never mixes generations for
        # one shard and that generations observed through one serving copy
        # never move backwards.
        response["_generations"] = {
            str(shard_numbers[i] if shard_numbers is not None
                else shard.shard_id.shard): snap.generation
            for i, (shard, snap, _r) in enumerate(per_shard_results)
        }
        if used_premerged:
            # the hits page came straight out of the device merge, already
            # in the canonical (-score, _tb) order: the coordinator's
            # reduce can k-way stream-merge instead of re-sorting
            response["_premerged"] = True

    if want_profile:
        # per-shard deep profile (search/profile.ShardProfiler): the
        # per-operator tree with the TPU-specific fields (device kernel
        # time fenced by block_until_ready, host->device transfer bytes,
        # jit-retrace flag), in the reference's
        # profile.shards[*].searches[*].query[*] response shape
        prof_aggs_body = body.get("aggs") or body.get("aggregations") or {}
        agg_prof = agg_profiler
        profs = shard_profilers or [None] * len(per_shard_results)
        shards_profile = []
        for shard_idx, ((shard, _snap, _r), prof) in enumerate(
            zip(per_shard_results, profs)
        ):
            t_ns = (shard_query_ns[shard_idx]
                    if shard_idx < len(shard_query_ns) else 0)
            query_entries = prof.query_entries() if prof is not None else []
            if not query_entries:
                # can_match-skipped shard (or a precomputed query phase):
                # one zeroed entry keeps the shape uniform
                query_entries = [{
                    "type": type(node).__name__,
                    "description": json.dumps(body.get("query") or {}),
                    "time_in_nanos": t_ns,
                    "breakdown": {
                        "create_weight": 0, "create_weight_count": 0,
                        "build_scorer": 0, "build_scorer_count": 0,
                        "score": t_ns, "score_count": 0,
                        "next_doc": 0, "next_doc_count": 0,
                    },
                    "device_time_in_nanos": 0,
                    "transfer_bytes": 0,
                    "retraced": False,
                }]
            shards_profile.append({
                "id": f"[{shard.shard_id.index}][{shard.shard_id.shard}]",
                # per-fetch-subphase breakdown (source load / highlight /
                # stored+doc-value fields), covering fetch the way the
                # operator tree covers query
                "fetch": (fetch_prof.entry(shard_idx)
                          if fetch_prof is not None else None),
                "searches": [{
                    "query": query_entries,
                    "rewrite_time": prof.rewrite_ns if prof else 0,
                    "collector": [{
                        "name": "SimpleTopDocsCollector",
                        "reason": "search_top_hits",
                        "time_in_nanos": (
                            prof.collect_ns if prof is not None else t_ns
                        ),
                    }],
                }],
                # shard-level TPU rollup (TPU-KNN roofline attribution)
                "tpu": (prof.tpu_summary() if prof is not None else
                        {"device_time_in_nanos": 0, "transfer_bytes": 0,
                         "jit_retrace": False}),
                "aggregations": _agg_profile_entries(
                    prof_aggs_body, response.get("aggregations"),
                    shard.mapper_service,
                    collect_count=sum(int(m.sum()) for m in _r.masks),
                    n_segments=max(len(_r.masks), 1),
                    segments=[h for h, _d in _snap.segments],
                    masks=list(_r.masks),
                    query_body=body.get("query"),
                    agg_times=(agg_prof.agg_times
                               if agg_prof is not None else None),
                ),
            })
        # per-structure device-residency rows for the indices this request
        # touched (telemetry/device_ledger.py): what was resident in HBM —
        # exact columns, IVF-PQ slabs, mesh bundles — while this query ran,
        # with bytes per structure (TPU-KNN's roofline denominators) and,
        # for touched structures, the per-structure HEAT summary (touch
        # count, bytes read, EWMA cadence, hot/warm/cold class)
        from opensearch_tpu.telemetry.device_ledger import default_ledger

        device_rows: list[dict] = []
        for index_name in sorted(
            {shard.shard_id.index for shard, _snap, _r in per_shard_results}
        ):
            device_rows.extend(default_ledger.structures(
                index=index_name, with_heat=True))
        response["profile"] = {"shards": shards_profile,
                               "device": device_rows}
    return response


def _agg_profile_entries(aggs_body, aggs_resp, ms, collect_count: int,
                         n_segments: int, segments=None, masks=None,
                         query_body=None, agg_times=None) -> list:
    """Aggregation profile tree (search/profile/aggregation/
    AggregationProfiler): aggregator class names, breakdowns with REAL
    collect counts (matched docs), and the per-strategy debug section the
    reference's profiler emits. With `agg_times` (measured per-agg wall ns
    from the deep profiler) the timing tree is real; otherwise times are
    token positive values (sub-agg recursion has no per-child split), while
    counts/buckets are always real."""
    from opensearch_tpu.search.aggs_pipeline import PIPELINE_TYPES

    entries = []
    for name, spec in (aggs_body or {}).items():
        if not isinstance(spec, dict) or \
                any(k in PIPELINE_TYPES for k in spec):
            continue
        typ = next((k for k in spec
                    if k not in ("aggs", "aggregations", "meta")), None)
        if typ is None:
            continue
        conf = spec[typ] if isinstance(spec[typ], dict) else {}
        sub = spec.get("aggs") or spec.get("aggregations")
        result = (aggs_resp or {}).get(name) or {}
        field = conf.get("field")
        mapper = ms.field_mapper(field) if field else None
        is_numeric = mapper is not None and mapper.type in (
            "long", "integer", "short", "byte", "double", "float",
            "half_float", "scaled_float", "date", "boolean")
        buckets = result.get("buckets")
        n_buckets = len(buckets) if isinstance(buckets, (list, dict)) else 0

        agg_class, debug = _aggregator_class_and_debug(
            typ, conf, mapper, is_numeric, n_buckets, n_segments,
            [k for k in (sub or {})], segments=segments, masks=masks,
            query_body=query_body, ms=ms)
        real_ns = (agg_times or {}).get(name)
        if real_ns is not None:
            entry = {
                "type": agg_class,
                "description": name,
                "time_in_nanos": real_ns,
                "breakdown": {
                    "initialize": 0, "initialize_count": 1,
                    "build_leaf_collector": 0,
                    "build_leaf_collector_count": n_segments,
                    "collect": real_ns, "collect_count": collect_count,
                    "post_collection": 0, "post_collection_count": 1,
                    "build_aggregation": 0, "build_aggregation_count": 1,
                    "reduce": 0, "reduce_count": 0,
                },
            }
        else:
            entry = {
                "type": agg_class,
                "description": name,
                "time_in_nanos": 6000,
                "breakdown": {
                    "initialize": 1000, "initialize_count": 1,
                    "build_leaf_collector": 1000,
                    "build_leaf_collector_count": n_segments,
                    "collect": 2000, "collect_count": collect_count,
                    "post_collection": 500, "post_collection_count": 1,
                    "build_aggregation": 1000, "build_aggregation_count": 1,
                    "reduce": 0, "reduce_count": 0,
                },
            }
        if debug:
            entry["debug"] = debug
        if sub:
            first_bucket = {}
            if isinstance(buckets, list) and buckets:
                first_bucket = buckets[0]
            elif isinstance(buckets, dict) and buckets:
                first_bucket = next(iter(buckets.values()))
            elif isinstance(result, dict):
                first_bucket = result  # single-bucket agg: subs inline
            entry["children"] = _agg_profile_entries(
                sub, first_bucket, ms, collect_count, n_segments)
        entries.append(entry)
    return entries


def _aggregator_class_and_debug(typ, conf, mapper, is_numeric, n_buckets,
                                n_segments, sub_names, segments=None,
                                masks=None, query_body=None, ms=None):
    """(aggregator class name, debug dict) per strategy — the names the
    reference's profiler reports (e.g. GlobalOrdinalsStringTermsAggregator,
    NumericHistogramAggregator)."""
    import numpy as _np

    field = conf.get("field")

    def _query_ranges_field(f) -> bool:
        # the date_histogram filter rewrite visits no leaves when the
        # top-level query is a range over the SAME field (the whole agg
        # becomes per-bucket range filters)
        return (isinstance(query_body, dict)
                and isinstance(query_body.get("range"), dict)
                and f in query_body["range"])

    def _filter_rewrite_debug():
        leaf = 0 if _query_ranges_field(field) else n_segments
        return {
            "optimized_segments": n_segments,
            "unoptimized_segments": 0,
            "leaf_visited": leaf,
            "inner_visited": 0,
        }

    if typ == "terms":
        if is_numeric:
            strategy = "double_terms" if mapper.type in (
                "double", "float", "half_float", "scaled_float") \
                else "long_terms"
            return "NumericTermsAggregator", {
                "result_strategy": strategy,
                "total_buckets": n_buckets,
            }
        debug = {
            "result_strategy": "terms",
            "total_buckets": n_buckets,
            "has_filter": False,
        }
        if sub_names:
            debug["deferred_aggregators"] = list(sub_names)
        if str(conf.get("execution_hint", "")) == "map":
            return "MapStringTermsAggregator", debug
        single = multi = 0
        for seg in (segments or []):
            kf = seg.keyword_fields.get(field)
            if kf is None or len(kf.mv_docs) == 0:
                continue
            counts = _np.bincount(kf.mv_docs, minlength=seg.n_docs)
            if counts.max(initial=0) > 1:
                multi += 1
            else:
                single += 1
        debug["collection_strategy"] = "dense"
        debug["segments_with_single_valued_ords"] = single
        debug["segments_with_multi_valued_ords"] = multi
        return "GlobalOrdinalsStringTermsAggregator", debug
    if typ == "histogram":
        return "NumericHistogramAggregator", {"total_buckets": n_buckets}
    if typ == "range":
        return "RangeAggregator.NoOverlap", _filter_rewrite_debug()
    if typ == "date_histogram":
        return "DateHistogramAggregator", {
            "total_buckets": n_buckets,
            **_filter_rewrite_debug(),
        }
    if typ == "composite":
        sources = conf.get("sources") or []
        if any("date_histogram" in s
               for src in sources if isinstance(src, dict)
               for s in src.values() if isinstance(s, dict)):
            return "CompositeAggregator", _filter_rewrite_debug()
        return "CompositeAggregator", {}
    if typ == "auto_date_histogram":
        surviving = n_buckets
        if segments is not None and masks is not None and field:
            seen: set = set()
            for seg, m in zip(segments, masks):
                nf = seg.numeric_fields.get(field)
                if nf is None:
                    continue
                vals = nf.values_i64 if nf.kind == "int" else nf.values_f64
                seen.update(vals[m & nf.present].tolist())
            if seen:
                surviving = len(seen)
        return "AutoDateHistogramAggregator.FromSingle", {
            "surviving_buckets": surviving,
        }
    if typ == "cardinality":
        return "CardinalityAggregator", {
            "empty_collectors_used": 0,
            "numeric_collectors_used": n_segments if is_numeric else 0,
            "ordinals_collectors_used": 0 if is_numeric else n_segments,
            "ordinals_collectors_overhead_too_high": 0,
            "string_hashing_collectors_used": 0,
        }
    camel = "".join(p.capitalize() for p in typ.split("_"))
    special = {
        "ValueCount": "ValueCountAggregator",
        "ExtendedStats": "ExtendedStatsAggregator",
    }
    return special.get(camel, f"{camel}Aggregator"), {}


def _try_distributed_query_phase(
    shards: list,
    acquired: list | None,
    node: Any,
    *,
    sort,
    search_after,
    aggs_body,
    min_score,
    filter_nodes,
    want_profile: bool,
    fetch_k: int,
    task=None,
) -> tuple[list, list, dict] | None:
    """Route eligible knn queries (multi- OR single-shard, filtered or
    not) through the on-device all_gather + top_k merge
    (parallel/distributed.build_knn_serving_step). Returns
    (per_shard_results, premerged_rows, launch_info): the per-shard
    results list shaped exactly like the host path's, the same winning
    hits flat in the device merge order, and the launch attribution
    (launch_id / wall_ns / retraced / shards / merged) for per-shard
    profiling. None when the host merge must run (every other query
    shape, or a non-resident shard set the mesh cannot serve — the
    caller's per-shard loop is the fallback)."""
    if not isinstance(node, query_dsl.KnnQuery):
        return None
    if (not shards or sort or search_after is not None
            or aggs_body is not None or min_score is not None):
        return None
    from opensearch_tpu.search import distributed_serving

    if not distributed_serving.enabled:
        return None
    # same cooperative cancellation point the host loop honors per shard
    if task is not None:
        task.ensure_not_cancelled()
    snaps = (
        list(acquired) if acquired is not None
        else [s.acquire_searcher() for s in shards]
    )
    # ANN-indexed columns never ride the mesh on UNFILTERED queries (the
    # host path answers those with IVF-PQ, and the mesh must stay
    # bit-identical to the host — distributed_serving._can_serve declines
    # them). Skip the batcher round-trip up front: without this pre-check
    # every bare ANN query would queue under the distributed key, merge,
    # and only then learn the mesh cannot serve it — paying a batch wait
    # just to fall back. The per-shard loop below dispatches it through
    # the ANN batch key instead (executor.shard_knn_selection).
    if (node.filter is None
            and not any(f is not None for f in filter_nodes)
            and any(
                (vf := dev.vector_fields.get(node.field)) is not None
                and vf.ann is not None
                for snap in snaps for _host, dev in snap.segments)):
        return None
    # cross-request micro-batching (search/batcher.py): concurrent
    # filterless knn searches against the same (index, field, k,
    # reader-generations) coalesce into ONE serving-program launch via the
    # batch entry point the msearch path already uses. The generation tuple
    # in the key is the snapshot-safety invariant: a refresh mid-flight is
    # a different key, so no query is ever answered from another request's
    # (older or newer) snapshot.
    key = None
    if node.filter is None and not any(f is not None for f in filter_nodes):
        key = (
            "distributed_knn", shards[0].shard_id.index, node.field,
            int(node.k), int(fetch_k),
            tuple(sh.engine.instance_id for sh in shards),
            tuple(snap.generation for snap in snaps),
            tuple(len(snap.segments) for snap in snaps),
        )

    if key is None:
        out = distributed_serving.mesh_knn_batch(
            shards, snaps, [node], fetch_k, alias_filters=filter_nodes
        )
        if out is None:
            return None
        results, premerged = out.per_query[0], out.premerged[0]
        launch_info = {"launch_id": out.launch_id, "wall_ns": out.wall_ns,
                       "retraced": out.retraced, "shards": out.shards,
                       "merged": 1}
    else:
        from opensearch_tpu.search import batcher as batcher_mod

        def launch(nodes_batch):
            out_b = distributed_serving.mesh_knn_batch(
                shards, snaps, list(nodes_batch), fetch_k
            )
            if out_b is None:  # ineligible: every member falls back
                return [None] * len(nodes_batch), False
            info = {"launch_id": out_b.launch_id, "wall_ns": out_b.wall_ns,
                    "retraced": out_b.retraced, "shards": out_b.shards}
            return [
                (out_b.per_query[i], out_b.premerged[i], info)
                for i in range(len(nodes_batch))
            ], out_b.retraced

        outcome = batcher_mod.dispatch(
            key, node, launch, shards=len(shards),
            # generation-free family for the wait auto-tuner
            tune_key=("distributed_knn", shards[0].shard_id.index,
                      node.field, int(node.k)))
        if outcome.value is None:
            return None
        results, premerged, launch_info = outcome.value
        launch_info = dict(launch_info, merged=outcome.merged)
    return (
        [(shard, snap, res)
         for shard, snap, res in zip(shards, snaps, results)],
        premerged,
        launch_info,
    )


_BATCHABLE_KNN_KEYS = {
    "query", "size", "from", "track_total_hits", "_source",
    "version", "seq_no_primary_term",
}


def msearch_knn_batchable(body) -> bool:
    """Cheap structural test for msearch batch grouping: a bare top-level
    knn query with only paging/source keys. The deep validation (same
    field/k, no filter, parseable) runs in try_batched_knn_msearch."""
    if not isinstance(body, dict):
        return False
    if set(body) - _BATCHABLE_KNN_KEYS:
        return False
    query = body.get("query")
    return isinstance(query, dict) and set(query) == {"knn"}


def msearch_groups(searches: list) -> list[list[int]]:
    """Partition msearch positions into runs: consecutive batchable-knn
    sub-searches against the same index group together (one device
    dispatch); everything else is a singleton run. Shared by
    TpuNode.msearch and ClusterFacade.msearch so the grouping rule cannot
    diverge between deployment modes."""
    groups: list[list[int]] = []
    i = 0
    while i < len(searches):
        header, body = searches[i]
        index = header.get("index")
        group = [i]
        if index is not None and msearch_knn_batchable(body):
            j = i + 1
            while (j < len(searches)
                   and searches[j][0].get("index") == index
                   and msearch_knn_batchable(searches[j][1])):
                group.append(j)
                j += 1
        groups.append(group)
        i = group[-1] + 1
    return groups


def try_batched_knn_msearch(
    shards: list,
    bodies: list[dict],
    acquired: list,
) -> list[list] | None:
    """Query-phase fast path for an msearch whose sub-searches are all bare
    knn queries on one index: ONE device dispatch scores all B query
    vectors (distributed_serving.try_distributed_knn_batch) instead of B
    sequential launches — the tunnel-round-trip amortization bench.py
    measures, applied to the serving path. Returns, per body, the
    per-shard-results list `search()` accepts via `precomputed_results`,
    or None when any body is not batchable (caller runs them serially,
    each still eligible for the single-query device path)."""
    if len(bodies) < 2 or not shards:
        return None
    from opensearch_tpu.search import distributed_serving

    if not distributed_serving.enabled:
        return None
    nodes = []
    fetch_k = 0
    for body in bodies:
        if not isinstance(body, dict) or set(body) - _BATCHABLE_KNN_KEYS:
            return None
        try:
            node = query_dsl.parse_query(body.get("query"))
        except Exception as e:  # noqa: BLE001 - bad body -> serial path reports it
            logger.debug("msearch batch probe: body not batchable: %s", e)
            return None
        if not isinstance(node, query_dsl.KnnQuery) or node.filter is not None:
            return None
        nodes.append(node)
        fetch_k = max(
            fetch_k,
            int(body.get("from", 0)) + int(body.get("size", DEFAULT_SIZE)),
        )
    first = nodes[0]
    if any(n.field != first.field or int(n.k) != int(first.k)
           for n in nodes[1:]):
        return None
    batched = distributed_serving.try_distributed_knn_batch(
        shards, acquired, nodes, fetch_k
    )
    if batched is None:
        return None
    return [
        [(shard, snap, res)
         for shard, snap, res in zip(shards, acquired, per_shard)]
        for per_shard in batched
    ]


MAX_BUCKETS = 65_536


class TooManyBucketsException(ParsingException):
    status = 503
    error_type = "too_many_buckets_exception"

    def __init__(self, count: int):
        super().__init__(
            f"Trying to create too many buckets. Must be less than or equal "
            f"to: [{MAX_BUCKETS}] but was [{count}]. This limit can be set "
            f"by changing the [search.max_buckets] cluster level setting."
        )


def _count_buckets(aggs: dict) -> int:
    total = 0
    stack = [aggs]
    while stack:
        cur = stack.pop()
        if isinstance(cur, dict):
            buckets = cur.get("buckets")
            if isinstance(buckets, list):
                total += len(buckets)
                stack.extend(buckets)
            elif isinstance(buckets, dict):
                total += len(buckets)
                stack.extend(buckets.values())
            else:
                stack.extend(
                    v for v in cur.values() if isinstance(v, (dict, list))
                )
        elif isinstance(cur, list):
            stack.extend(cur)
    return total


class _MultiMapperView:
    """Read-only MapperService facade over several indices' mappings."""

    def __init__(self, services: list):
        # dedupe while preserving order
        seen: set[int] = set()
        self.services = [
            s for s in services if not (id(s) in seen or seen.add(id(s)))
        ]

    def field_mapper(self, name: str):
        for s in self.services:
            m = s.field_mapper(name)
            if m is not None:
                return m
        return None

    @property
    def mappers(self) -> dict:
        merged: dict = {}
        for s in reversed(self.services):
            merged.update(s.mappers)
        return merged

    def analyze_query_text(self, field: str, text: str) -> list[str]:
        for s in self.services:
            if s.field_mapper(field) is not None:
                return s.analyze_query_text(field, text)
        if self.services:
            return self.services[0].analyze_query_text(field, text)
        return [text]


def _values_key(sort: list, values: list) -> tuple:
    """Ordering key for a row of sort values, consistent with
    executor._sort_key_fn (minus its (segment, doc) tiebreak tail)."""
    specs = [_sort_spec(s) for s in sort]
    parts = []
    for (fname, order, _missing), v in zip(specs, values):
        if fname == "_score":
            parts.append(-v if order == "desc" else v)
        elif v is None:
            parts.append((1, 0))
        elif isinstance(v, str):
            parts.append((0, _StrKey(v, order == "desc")))
        else:
            parts.append((0, -v if order == "desc" else v))
    return tuple(parts)


def _sort_values_key(sort: list, hit) -> tuple:
    return _values_key(sort, hit.sort_values)


def _search_after_key(sort: list, search_after: list) -> tuple:
    if len(search_after) != len(sort):
        raise ParsingException(
            f"search_after must have {len(sort)} value(s) matching sort"
        )
    return _values_key(sort, search_after)


def _coerce_search_after(sort: list, search_after: list, ms) -> list:
    """Cursor values arrive as JSON (dates as strings, numbers as ints);
    coerce each to the sort column's native type so the cursor compares
    against sort_values without type mismatches."""
    from opensearch_tpu.index.mapper import (
        FLOAT_TYPES,
        INT_TYPES,
        parse_date_millis,
    )

    out = []
    for spec, v in zip([_sort_spec(s) for s in sort], search_after):
        fname = spec[0]
        mapper = ms.field_mapper(fname) if hasattr(ms, "field_mapper") else None
        if v is None or fname == "_score":
            out.append(v)
        elif mapper is not None and \
                getattr(mapper, "original_type", None) == "unsigned_long":
            try:
                out.append(int(str(v), 10))
            except ValueError:
                out.append(v)
        elif mapper is not None and mapper.type == "date" \
                and isinstance(v, str):
            if getattr(mapper, "resolution", "millis") == "nanos":
                from opensearch_tpu.index.mapper import parse_date_nanos

                out.append(parse_date_nanos(v))
            else:
                out.append(float(parse_date_millis(v)))
        elif mapper is not None and (
            mapper.type in INT_TYPES or mapper.type in FLOAT_TYPES
            or mapper.type == "boolean"
        ) and isinstance(v, str):
            try:
                out.append(float(v))
            except ValueError:
                out.append(v)
        else:
            out.append(v)
    return out


def _source_filter(spec: Any):
    if spec is False:
        return lambda src: None
    if spec is True or spec is None:
        return lambda src: src
    if isinstance(spec, str):
        spec = [spec]
    if isinstance(spec, list):
        includes, excludes = spec, []
    elif isinstance(spec, dict):
        includes = spec.get("includes") or spec.get("include") or []
        excludes = spec.get("excludes") or spec.get("exclude") or []
        if isinstance(includes, str):
            includes = [includes]
        if isinstance(excludes, str):
            excludes = [excludes]
    else:
        raise ParsingException(f"invalid _source spec [{spec!r}]")

    def apply(src: dict) -> dict:
        flat = _flatten(src)
        out: dict[str, Any] = {}
        for key, value in flat.items():
            if includes and not any(_match(key, p) for p in includes):
                continue
            if excludes and any(_match(key, p) for p in excludes):
                continue
            _put_nested(out, key, value)
        return out

    return apply


def _match(key: str, pattern: str) -> bool:
    # "user.*" matches nested keys; "user" matches the whole subtree
    return (
        fnmatch.fnmatch(key, pattern)
        or fnmatch.fnmatch(key, pattern + ".*")
        or key.startswith(pattern + ".")
    )


def _flatten(obj: dict, prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    for k, v in obj.items():
        full = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, f"{full}."))
        else:
            out[full] = v
    return out


def _put_nested(out: dict, key: str, value: Any) -> None:
    parts = key.split(".")
    node = out
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value
