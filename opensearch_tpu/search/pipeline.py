"""Search pipelines: request/response/phase-results processor chains.

The analog of the reference's search-pipeline subsystem
(server/src/main/java/org/opensearch/search/pipeline/SearchPipelineService.java
+ modules/search-pipeline-common, SURVEY.md §2.2 "Search pipelines"): named
pipelines of processors that transform the search request before execution,
the response after, and — the hook hybrid-ranking plugins use — the query
phase results BETWEEN query and fetch (SearchPhaseResultsProcessor).

Built-in processors:
  request:        filter_query, oversample
  response:       rename_field, truncate_hits, sort, script-less collapse
  phase_results:  normalization-processor (min_max | l2 | z_score + arithmetic
                  / geometric / harmonic mean), score-ranker-processor (RRF)

The phase-results processors implement hybrid BM25+kNN score fusion
(BASELINE config #4): per-sub-query score lists from every shard are
normalized GLOBALLY, then combined per doc.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any

from opensearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceNotFoundException,
)

_REQUEST_PROCESSORS = ("filter_query", "oversample")
_RESPONSE_PROCESSORS = ("rename_field", "truncate_hits", "sort")
_PHASE_PROCESSORS = ("normalization-processor", "score-ranker-processor")


class SearchPipelineService:
    """Pipeline registry with file persistence (IngestService-style)."""

    def __init__(self, state_path: Path):
        self._path = Path(state_path)
        self.pipelines: dict[str, dict] = {}
        if self._path.exists():
            self.pipelines = json.loads(self._path.read_text())

    def _persist(self) -> None:
        self._path.parent.mkdir(parents=True, exist_ok=True)
        self._path.write_text(json.dumps(self.pipelines))

    def put(self, pipeline_id: str, body: dict) -> None:
        self._validate(body)
        self.pipelines[pipeline_id] = body
        self._persist()

    def get(self, pipeline_id: str) -> dict:
        if pipeline_id not in self.pipelines:
            raise ResourceNotFoundException(
                f"search pipeline [{pipeline_id}] not found"
            )
        return self.pipelines[pipeline_id]

    def delete(self, pipeline_id: str) -> None:
        if pipeline_id not in self.pipelines:
            raise ResourceNotFoundException(
                f"search pipeline [{pipeline_id}] not found"
            )
        del self.pipelines[pipeline_id]
        self._persist()

    def _validate(self, body: dict) -> None:
        for section, known in (
            ("request_processors", _REQUEST_PROCESSORS),
            ("response_processors", _RESPONSE_PROCESSORS),
            ("phase_results_processors", _PHASE_PROCESSORS),
        ):
            for proc in body.get(section) or []:
                if not isinstance(proc, dict) or len(proc) != 1:
                    raise IllegalArgumentException(
                        f"each processor in [{section}] must be a single-key object"
                    )
                name = next(iter(proc))
                if name not in known:
                    raise IllegalArgumentException(
                        f"unknown processor type [{name}] in [{section}]"
                    )

    # -- execution ---------------------------------------------------------

    def transform_request(self, pipeline: dict, body: dict) -> dict:
        body = dict(body)
        for proc in pipeline.get("request_processors") or []:
            name, conf = next(iter(proc.items()))
            conf = conf or {}
            if name == "filter_query":
                extra = conf.get("query")
                if extra:
                    orig = body.get("query")
                    must = [orig] if orig else []
                    body["query"] = {"bool": {"must": must, "filter": [extra]}}
            elif name == "oversample":
                factor = float(conf.get("sample_factor", 1.0))
                if factor < 1.0:
                    raise IllegalArgumentException(
                        "[oversample] sample_factor must be >= 1"
                    )
                size = int(body.get("size", 10))
                body["_original_size"] = size
                body["size"] = int(math.ceil(size * factor))
        return body

    def transform_response(self, pipeline: dict, body: dict, response: dict) -> dict:
        for proc in pipeline.get("response_processors") or []:
            name, conf = next(iter(proc.items()))
            conf = conf or {}
            hits = response.get("hits", {}).get("hits", [])
            if name == "rename_field":
                field, target = conf.get("field"), conf.get("target_field")
                for hit in hits:
                    src = hit.get("_source")
                    if isinstance(src, dict) and field in src:
                        src[target] = src.pop(field)
            elif name == "truncate_hits":
                target = conf.get("target_size", body.get("_original_size"))
                if target is not None:
                    response["hits"]["hits"] = hits[: int(target)]
            elif name == "sort":
                field = conf.get("field")
                order = conf.get("order", "asc")
                target = conf.get("target_field", field)
                for hit in hits:
                    src = hit.get("_source")
                    if isinstance(src, dict) and isinstance(src.get(field), list):
                        src[target] = sorted(
                            src[field], reverse=(order == "desc")
                        )
        return response

    def phase_results_config(self, pipeline: dict) -> dict | None:
        """The first phase-results processor's config (normalization/RRF)."""
        for proc in pipeline.get("phase_results_processors") or []:
            name, conf = next(iter(proc.items()))
            conf = dict(conf or {})
            conf["_processor"] = name
            return conf
        return None


# --------------------------------------------------------------------------
# hybrid score fusion (the phase-results compute)
# --------------------------------------------------------------------------


def _normalize(all_scores: list[float], scores: list[float], technique: str) -> list[float]:
    if technique == "l2":
        norm = math.sqrt(sum(s * s for s in all_scores)) or 1.0
        return [s / norm for s in scores]
    if technique == "z_score":
        n = len(all_scores) or 1
        mean = sum(all_scores) / n
        var = sum((s - mean) ** 2 for s in all_scores) / n
        std = math.sqrt(var) or 1.0
        return [(s - mean) / std for s in scores]
    # min_max (default); single-point range maps to 1.0
    lo, hi = (min(all_scores), max(all_scores)) if all_scores else (0.0, 0.0)
    if hi <= lo:
        return [1.0 for _ in scores]
    return [max((s - lo) / (hi - lo), 0.001) for s in scores]


def _combine(sub_scores: list[float | None], technique: str, weights: list[float]) -> float:
    n = len(sub_scores)
    w = (weights + [1.0] * n)[:n] if weights else [1.0] * n
    if technique == "geometric_mean":
        num = den = 0.0
        for s, wi in zip(sub_scores, w):
            if s is not None and s > 0:
                num += wi * math.log(s)
                den += wi
        return math.exp(num / den) if den > 0 else 0.0
    if technique == "harmonic_mean":
        num = den = 0.0
        for s, wi in zip(sub_scores, w):
            if s is not None and s > 0:
                num += wi
                den += wi / s
        return num / den if den > 0 else 0.0
    # arithmetic_mean: absent sub-scores count as 0 against the full weight
    total_w = sum(w) or 1.0
    return sum(wi * (s or 0.0) for s, wi in zip(sub_scores, w)) / total_w


def fuse_hybrid_results(
    per_shard_sub_results: list[list],
    config: dict | None,
    fetch_k: int,
):
    """Normalize per-sub-query scores globally, combine per doc, re-rank.

    per_shard_sub_results[shard][sub] is a ShardQueryResult. Returns a list
    of per-shard fused ShardQueryResults (hits re-scored and re-sorted).
    Mirrors the normalization-processor contract: min/max statistics span
    ALL shards' query-phase results for a sub-query, not one shard's.
    """
    from opensearch_tpu.search.executor import ShardHit, ShardQueryResult

    config = config or {}
    processor = config.get("_processor", "normalization-processor")
    n_sub = len(per_shard_sub_results[0]) if per_shard_sub_results else 0

    if processor == "score-ranker-processor":
        comb = config.get("combination") or {}
        rank_constant = int(comb.get("rank_constant", 60))
        weights = list((comb.get("parameters") or {}).get("weights") or [])
        w = (weights + [1.0] * n_sub)[:n_sub] if weights else [1.0] * n_sub
        fused_scores_per_shard: list[dict] = []
        for sub_results in per_shard_sub_results:
            fused: dict[tuple[int, int], float] = {}
            for i, res in enumerate(sub_results):
                ranked = sorted(
                    res.hits, key=lambda h: (-h.score, h.segment, h.doc)
                )
                for rank, h in enumerate(ranked):
                    key = (h.segment, h.doc)
                    fused[key] = fused.get(key, 0.0) + w[i] / (
                        rank_constant + rank + 1
                    )
            fused_scores_per_shard.append(fused)
        return _build_fused(
            per_shard_sub_results, fused_scores_per_shard, fetch_k,
            ShardHit, ShardQueryResult,
        )

    norm_technique = (config.get("normalization") or {}).get("technique", "min_max")
    comb_conf = config.get("combination") or {}
    comb_technique = comb_conf.get("technique", "arithmetic_mean")
    weights = list((comb_conf.get("parameters") or {}).get("weights") or [])

    # global per-sub-query score pools for normalization statistics
    pools: list[list[float]] = [[] for _ in range(n_sub)]
    for sub_results in per_shard_sub_results:
        for i, res in enumerate(sub_results):
            pools[i].extend(h.score for h in res.hits)

    fused_scores_per_shard = []
    for sub_results in per_shard_sub_results:
        per_doc: dict[tuple[int, int], list[float | None]] = {}
        for i, res in enumerate(sub_results):
            if not res.hits:
                continue
            normed = _normalize(
                pools[i], [h.score for h in res.hits], norm_technique
            )
            for h, s in zip(res.hits, normed):
                key = (h.segment, h.doc)
                if key not in per_doc:
                    per_doc[key] = [None] * n_sub
                per_doc[key][i] = s
        fused_scores_per_shard.append({
            key: _combine(subs, comb_technique, weights)
            for key, subs in per_doc.items()
        })
    return _build_fused(
        per_shard_sub_results, fused_scores_per_shard, fetch_k,
        ShardHit, ShardQueryResult,
    )


def _build_fused(per_shard_sub_results, fused_scores_per_shard, fetch_k,
                 ShardHit, ShardQueryResult):
    out = []
    for sub_results, fused in zip(per_shard_sub_results, fused_scores_per_shard):
        ranked = sorted(
            fused.items(), key=lambda kv: (-kv[1], kv[0][0], kv[0][1])
        )[:fetch_k]
        hits = [
            ShardHit(score=score, segment=seg, doc=doc)
            for (seg, doc), score in ranked
        ]
        # union totals / masks across sub-queries
        n_seg = len(sub_results[0].masks) if sub_results and sub_results[0].masks else 0
        masks = []
        score_arrays = []
        for seg_i in range(n_seg):
            m = None
            for res in sub_results:
                seg_mask = res.masks[seg_i]
                if seg_mask is None:
                    continue
                m = seg_mask.copy() if m is None else (m | seg_mask)
            masks.append(m)
            if m is not None:
                import numpy as np

                arr = np.zeros(m.shape[0], np.float32)
                for (seg, doc), score in fused.items():
                    if seg == seg_i and doc < arr.shape[0]:
                        arr[doc] = score
                score_arrays.append(arr)
            else:
                score_arrays.append(None)
        # union total: exact from OR'd masks when present (aggs path),
        # otherwise the best lower bound from the sub-query totals
        if masks and all(m is not None for m in masks):
            total = int(sum(int(m.sum()) for m in masks))
        else:
            total = max(
                (max((r.total for r in sub_results), default=0), len(fused))
            )
        out.append(ShardQueryResult(
            hits=hits,
            total=total,
            max_score=hits[0].score if hits else None,
            masks=masks,
            score_arrays=score_arrays,
        ))
    return out
