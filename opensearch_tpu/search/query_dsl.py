"""Query DSL: JSON -> query node tree.

The analog of the reference's 86 QueryBuilder classes + parsing
(server/src/main/java/org/opensearch/index/query/ — AbstractQueryBuilder,
QueryShardContext): `parse_query` turns the JSON DSL into a typed node tree;
opensearch_tpu/search/executor.py compiles nodes against a segment into
device score/mask ops (the `toQuery(QueryShardContext)` step).

Supported (growing set): match_all, match_none, match, multi_match, term,
terms, range, exists, ids, bool, constant_score, boost on all nodes,
match_phrase (position-less approximation: all terms must match), knn,
script_score (k-NN script patterns), function_score (subset).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

from opensearch_tpu.common.errors import ParsingException


@dataclass
class QueryNode:
    boost: float = 1.0
    # `_name` (named queries): hits report which named clauses matched
    # (matched_queries; AbstractQueryBuilder#queryName)
    name: str | None = None


@dataclass
class MatchAllQuery(QueryNode):
    pass


@dataclass
class MatchNoneQuery(QueryNode):
    pass


@dataclass
class SliceQuery(QueryNode):
    """Sliced scroll partition (search/slice/SliceBuilder.java): doc belongs
    to slice `id` of `max` iff murmur3(_id) % max == id."""

    id: int = 0
    max: int = 1


@dataclass
class MatchQuery(QueryNode):
    field: str = ""
    query: str = ""
    operator: str = "or"          # or | and
    minimum_should_match: int | None = None


@dataclass
class MatchPhraseQuery(QueryNode):
    field: str = ""
    query: str = ""
    slop: int = 0


@dataclass
class IntervalsQuery(QueryNode):
    """intervals query (IntervalQueryBuilder) — source tree parsed by
    opensearch_tpu/search/intervals.py, verified against position postings."""

    field: str = ""
    source: Any = None            # intervals.IntervalSource


@dataclass
class MultiMatchQuery(QueryNode):
    fields: list[str] = dc_field(default_factory=list)
    query: str = ""
    type: str = "best_fields"     # best_fields | most_fields | bool_prefix | phrase | phrase_prefix | cross_fields
    operator: str = "or"
    minimum_should_match: Any = None
    fuzziness: Any = None
    analyzer: str | None = None
    slop: int = 0                 # phrase/phrase_prefix types
    field_boosts: dict = dc_field(default_factory=dict)  # "f^2" per-field boost


@dataclass
class TermQuery(QueryNode):
    field: str = ""
    value: Any = None
    case_insensitive: bool = False


@dataclass
class TermsQuery(QueryNode):
    field: str = ""
    values: list[Any] = dc_field(default_factory=list)


@dataclass
class RangeQuery(QueryNode):
    field: str = ""
    gte: Any = None
    gt: Any = None
    lte: Any = None
    lt: Any = None
    # range-FIELD relation (RangeQueryBuilder.relation, BKD range fields):
    # intersects (default) | contains | within
    relation: str = "intersects"


@dataclass
class ExistsQuery(QueryNode):
    field: str = ""


@dataclass
class TermsSetQuery(QueryNode):
    """terms_set (TermsSetQueryBuilder): per-doc minimum-should-match from
    a field or a script."""

    field: str = ""
    terms: list = dc_field(default_factory=list)
    minimum_should_match_field: str | None = None
    minimum_should_match_script: dict | None = None


@dataclass
class RankFeatureQuery(QueryNode):
    """rank_feature (RankFeatureQueryBuilder): score from a positive
    feature value via saturation/log/sigmoid/linear."""

    field: str = ""
    function: str = "saturation"  # saturation | log | sigmoid | linear
    pivot: float | None = None
    scaling_factor: float = 1.0   # log
    exponent: float = 1.0         # sigmoid


@dataclass
class GeoDistanceQuery(QueryNode):
    """geo_distance (GeoDistanceQueryBuilder): docs within `distance` of a
    center point."""

    field: str = ""
    distance: Any = None
    point: Any = None             # {lat, lon} | [lon, lat] | "lat,lon"


@dataclass
class GeoBoundingBoxQuery(QueryNode):
    """geo_bounding_box (GeoBoundingBoxQueryBuilder)."""

    field: str = ""
    top_left: Any = None
    bottom_right: Any = None


@dataclass
class GeoShapeQuery(QueryNode):
    """geo_shape against geo_point columns (envelope/point/polygon-bbox
    subset of GeoShapeQueryBuilder)."""

    field: str = ""
    shape: dict | None = None
    relation: str = "intersects"


@dataclass
class DistanceFeatureQuery(QueryNode):
    """distance_feature (DistanceFeatureQueryBuilder): score decays with
    distance from origin; boost * pivot / (pivot + distance)."""

    field: str = ""
    origin: Any = None
    pivot: Any = None


@dataclass
class IdsQuery(QueryNode):
    values: list[str] = dc_field(default_factory=list)


@dataclass
class BoolQuery(QueryNode):
    must: list[QueryNode] = dc_field(default_factory=list)
    should: list[QueryNode] = dc_field(default_factory=list)
    filter: list[QueryNode] = dc_field(default_factory=list)
    must_not: list[QueryNode] = dc_field(default_factory=list)
    minimum_should_match: int | None = None


@dataclass
class ConstantScoreQuery(QueryNode):
    filter: QueryNode | None = None


@dataclass
class KnnQuery(QueryNode):
    field: str = ""
    vector: list[float] = dc_field(default_factory=list)
    k: int = 10
    filter: QueryNode | None = None
    # per-query ANN knobs ({"nprobe": N}, k-NN plugin method_parameters)
    method_parameters: dict | None = None


@dataclass
class PrefixQuery(QueryNode):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class WildcardQuery(QueryNode):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class RegexpQuery(QueryNode):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class FuzzyQuery(QueryNode):
    field: str = ""
    value: str = ""
    fuzziness: str = "AUTO"
    prefix_length: int = 0


@dataclass
class MatchPhrasePrefixQuery(QueryNode):
    field: str = ""
    query: str = ""
    max_expansions: int = 50


@dataclass
class MatchBoolPrefixQuery(QueryNode):
    field: str = ""
    query: str = ""
    operator: str = "or"
    minimum_should_match: Any = None
    fuzziness: Any = None
    analyzer: str | None = None


@dataclass
class QueryStringQuery(QueryNode):
    query: str = ""
    fields: list[str] = dc_field(default_factory=list)
    default_operator: str = "or"


@dataclass
class SimpleQueryStringQuery(QueryNode):
    query: str = ""
    fields: list[str] = dc_field(default_factory=list)
    default_operator: str = "or"


@dataclass
class BoostingQuery(QueryNode):
    positive: QueryNode | None = None
    negative: QueryNode | None = None
    negative_boost: float = 0.5


@dataclass
class DisMaxQuery(QueryNode):
    queries: list[QueryNode] = dc_field(default_factory=list)
    tie_breaker: float = 0.0


@dataclass
class ScoreFunction:
    """One entry of function_score.functions (FunctionScoreQueryBuilder)."""

    kind: str = "weight"          # weight | field_value_factor | random_score | decay
    filter: QueryNode | None = None
    weight: float | None = None
    # field_value_factor
    field: str = ""
    factor: float = 1.0
    modifier: str = "none"
    missing: float | None = None
    # random_score
    seed: int = 0
    # decay (gauss | exp | linear over numeric/date field)
    decay_type: str = ""
    origin: Any = None
    scale: Any = None
    offset: Any = None
    decay: float = 0.5


@dataclass
class FunctionScoreQuery(QueryNode):
    query: QueryNode | None = None
    functions: list[ScoreFunction] = dc_field(default_factory=list)
    score_mode: str = "multiply"  # multiply | sum | avg | first | max | min
    boost_mode: str = "multiply"  # multiply | replace | sum | avg | max | min
    max_boost: float = float("inf")
    min_score: float | None = None


@dataclass
class NestedQuery(QueryNode):
    """Flattened-semantics nested: delegates to the inner query over the
    dotted subfields (arrays are multi-valued columns in our layout)."""

    path: str = ""
    query: QueryNode | None = None
    score_mode: str = "avg"


@dataclass
class HybridQuery(QueryNode):
    """OpenSearch neural-search hybrid query: sub-query scores are kept
    separate through the query phase so a search-pipeline normalization
    processor can combine them (reference: neural-search plugin's
    HybridQuery + NormalizationProcessor)."""

    queries: list[QueryNode] = dc_field(default_factory=list)


@dataclass
class MoreLikeThisQuery(QueryNode):
    """TF-IDF representative-term selection over like-texts (reference:
    index/query/MoreLikeThisQueryBuilder; doc refs are resolved to texts
    before shard execution, like the two-phase rewrite)."""

    fields: list[str] = dc_field(default_factory=list)
    like_texts: list[str] = dc_field(default_factory=list)
    like_docs: list[dict] = dc_field(default_factory=list)  # {_index, _id}
    min_term_freq: int = 2
    min_doc_freq: int = 5
    max_query_terms: int = 25
    minimum_should_match: str = "30%"


@dataclass
class PercolateQuery(QueryNode):
    """Reverse search: match stored queries against provided documents
    (reference: modules/percolator PercolateQueryBuilder)."""

    field: str = ""
    documents: list[dict] = dc_field(default_factory=list)


@dataclass
class HasChildQuery(QueryNode):
    type: str = ""
    query: QueryNode | None = None
    score_mode: str = "none"     # none | sum | max | avg
    min_children: int = 1
    max_children: int = 2**31 - 1


@dataclass
class HasParentQuery(QueryNode):
    parent_type: str = ""
    query: QueryNode | None = None
    score: bool = False


@dataclass
class ParentIdQuery(QueryNode):
    type: str = ""
    id: str = ""


@dataclass
class GenericScriptScoreQuery(QueryNode):
    """script_score with an arbitrary painless script (per-doc host eval);
    the recognized vector-function patterns compile to the fused device
    path (ScriptScoreQuery) instead."""

    query: QueryNode | None = None
    script: dict = dc_field(default_factory=dict)


@dataclass
class ScriptQuery(QueryNode):
    """script filter query: {"script": {"script": {...}}} — keep docs where
    the script returns true."""

    script: dict = dc_field(default_factory=dict)


@dataclass
class ScriptScoreQuery(QueryNode):
    query: QueryNode | None = None
    # recognized vector scoring functions (the k-NN plugin script patterns)
    function: str = ""            # knn_score | cosineSimilarity | dotProduct | l2Squared
    field: str = ""
    query_vector: list[float] = dc_field(default_factory=list)
    space_type: str = "l2"
    add_constant: float = 0.0     # e.g. "cosineSimilarity(...) + 1.0"


def iter_query_nodes(node: QueryNode):
    """Depth-first walk over a query node tree (all QueryNode-typed fields
    and lists thereof)."""
    import dataclasses as _dc

    yield node
    for f in _dc.fields(node):
        v = getattr(node, f.name, None)
        if isinstance(v, QueryNode):
            yield from iter_query_nodes(v)
        elif isinstance(v, list):
            for item in v:
                if isinstance(item, QueryNode):
                    yield from iter_query_nodes(item)


def _single_kv(body: dict, name: str) -> tuple[str, Any]:
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException(f"[{name}] query must have a single field")
    return next(iter(body.items()))


def parse_query(body: dict | None) -> QueryNode:
    if body is None:
        return MatchAllQuery()
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException(
            "query must be an object with a single top-level key, got "
            f"{list(body) if isinstance(body, dict) else type(body).__name__}"
        )
    qtype, qbody = next(iter(body.items()))
    parser = _PARSERS.get(qtype)
    if parser is None:
        # same did-you-mean hint as the reference's
        # AbstractQueryBuilder.parseInnerQueryBuilder
        import difflib

        close = difflib.get_close_matches(qtype, list(_PARSERS), n=1,
                                          cutoff=0.7)
        hint = f" did you mean [{close[0]}]?" if close else ""
        raise ParsingException(f"unknown query [{qtype}]{hint}")
    # `_name` may sit at the query-body level ({"bool": {..., "_name": x}})
    # or inside the single-field conf ({"term": {"f": {.., "_name": x}}})
    qname = None
    if isinstance(qbody, dict):
        if "_name" in qbody:
            qbody = {k: v for k, v in qbody.items() if k != "_name"}
            qname = body[qtype]["_name"]
        elif len(qbody) == 1:
            inner = next(iter(qbody.values()))
            if isinstance(inner, dict) and "_name" in inner:
                qname = inner["_name"]
                qbody = {next(iter(qbody)): {
                    k: v for k, v in inner.items() if k != "_name"
                }}
        body = {qtype: qbody}
    if not isinstance(qbody, dict):
        raise ParsingException(
            f"[{qtype}] query malformed, expected an object but got "
            f"[{type(qbody).__name__}]"
        )
    node = parser(qbody)
    if qname is not None:
        node.name = str(qname)
    return node


def _parse_match_all(body: dict) -> QueryNode:
    return MatchAllQuery(boost=float(body.get("boost", 1.0)))


def _parse_match_none(_body: dict) -> QueryNode:
    return MatchNoneQuery()


def _query_text(v: Any) -> str:
    """JSON-canonical text for a match value: booleans render as the JSON
    literals (the reference coerces via XContent text, so `true`, not
    Python's `True` — a boolean-field match must round-trip)."""
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _parse_match(body: dict) -> QueryNode:
    fname, conf = _single_kv(body, "match")
    if isinstance(conf, dict):
        return MatchQuery(
            field=fname,
            query=_query_text(conf.get("query", "")),
            operator=str(conf.get("operator", "or")).lower(),
            minimum_should_match=_parse_msm(conf.get("minimum_should_match")),
            boost=float(conf.get("boost", 1.0)),
        )
    return MatchQuery(field=fname, query=_query_text(conf))


def _parse_match_phrase(body: dict) -> QueryNode:
    fname, conf = _single_kv(body, "match_phrase")
    if isinstance(conf, dict):
        return MatchPhraseQuery(field=fname, query=_query_text(conf.get("query", "")),
                                slop=int(conf.get("slop", 0)),
                                boost=float(conf.get("boost", 1.0)))
    return MatchPhraseQuery(field=fname, query=_query_text(conf))


def _parse_span_source(qtype: str, body: Any) -> tuple[str, Any]:
    """(field, IntervalSource) for one span_* clause. Span queries are the
    reference's position-query family (index/query/Span*QueryBuilder);
    here they lower onto the minimal-interval algebra the intervals query
    already evaluates against position postings."""
    from opensearch_tpu.search import intervals as iv

    if qtype == "span_term":
        fname, conf = _single_kv(body, "span_term")
        value = conf.get("value") if isinstance(conf, dict) else conf
        boost = float(conf.get("boost", 1.0)) if isinstance(conf, dict) else 1.0
        _ = boost
        return fname, iv.TermSource(term=str(value))
    if qtype in ("span_near", "span_or"):
        clauses = body.get("clauses")
        if not isinstance(clauses, list) or not clauses:
            raise ParsingException(f"[{qtype}] requires [clauses]")
        parsed = []
        field = None
        for c in clauses:
            if not isinstance(c, dict) or len(c) != 1:
                raise ParsingException(f"[{qtype}] clause must be a span query")
            ctype, cbody = next(iter(c.items()))
            f, src = _parse_span_source(ctype, cbody)
            field = field or f
            if f != field:
                raise ParsingException(
                    "span clauses must target the same field"
                )
            parsed.append(src)
        if qtype == "span_or":
            return field, iv.AnyOfSource(sources=parsed)
        in_order = bool(body.get("in_order", True))
        slop = int(body.get("slop", 0))
        return field, iv.AllOfSource(
            sources=parsed, mode="ordered" if in_order else "unordered",
            max_gaps=slop,
        )
    if qtype == "span_first":
        match = body.get("match")
        if not isinstance(match, dict) or len(match) != 1:
            raise ParsingException("[span_first] requires [match]")
        ctype, cbody = next(iter(match.items()))
        field, src = _parse_span_source(ctype, cbody)
        return field, iv.FirstSource(source=src, end=int(body.get("end", 0)))
    if qtype in ("span_containing", "span_within"):
        big = body.get("big")
        little = body.get("little")
        if not isinstance(big, dict) or not isinstance(little, dict):
            raise ParsingException(f"[{qtype}] requires [big] and [little]")
        bf, bsrc = _parse_span_source(*next(iter(big.items())))
        lf, lsrc = _parse_span_source(*next(iter(little.items())))
        if bf != lf:
            raise ParsingException("span clauses must target the same field")
        if qtype == "span_containing":
            bsrc.filter = iv.IntervalFilter("containing", lsrc)
            return bf, bsrc
        lsrc.filter = iv.IntervalFilter("contained_by", bsrc)
        return lf, lsrc
    if qtype == "span_not":
        include = body.get("include")
        exclude = body.get("exclude")
        if not isinstance(include, dict) or not isinstance(exclude, dict):
            raise ParsingException(
                "[span_not] requires [include] and [exclude]"
            )
        inf, insrc = _parse_span_source(*next(iter(include.items())))
        exf, exsrc = _parse_span_source(*next(iter(exclude.items())))
        if inf != exf:
            raise ParsingException("span clauses must target the same field")
        insrc.filter = iv.IntervalFilter("not_overlapping", exsrc)
        return inf, insrc
    if qtype == "span_multi":
        match = body.get("match")
        if not isinstance(match, dict) or len(match) != 1:
            raise ParsingException("[span_multi] requires [match]")
        mtype, mbody = next(iter(match.items()))
        if mtype not in ("prefix", "wildcard", "fuzzy", "regexp"):
            raise ParsingException(
                f"[span_multi] does not support [{mtype}]"
            )
        fname, conf = _single_kv(mbody, mtype)
        if isinstance(conf, dict):
            value = conf.get("value", conf.get(mtype, conf.get("wildcard")))
            ci = bool(conf.get("case_insensitive", False))
            fuzz = conf.get("fuzziness", "AUTO")
            plen = int(conf.get("prefix_length", 0))
        else:
            value, ci, fuzz, plen = conf, False, "AUTO", 0
        kind = {"prefix": "prefix", "wildcard": "wildcard",
                "fuzzy": "fuzzy", "regexp": "regexp"}[mtype]
        return fname, iv.ExpandSource(
            kind=kind, pattern=str(value), case_insensitive=ci,
            fuzziness=fuzz, prefix_length=plen,
        )
    raise ParsingException(f"unknown span query [{qtype}]")


def _parse_span_query(qtype: str):
    def parse(body: dict) -> QueryNode:
        field, src = _parse_span_source(qtype, body)
        boost = float(body.get("boost", 1.0)) if isinstance(body, dict) else 1.0
        return IntervalsQuery(field=field, source=src, boost=boost)

    return parse


def _parse_intervals(body: dict) -> QueryNode:
    from opensearch_tpu.search import intervals as iv

    fname, conf = _single_kv(body, "intervals")
    if not isinstance(conf, dict):
        raise ParsingException("[intervals] query body must be an object")
    conf = dict(conf)
    boost = float(conf.pop("boost", 1.0))
    return IntervalsQuery(
        field=fname, source=iv.parse_intervals_source(conf), boost=boost
    )


def _parse_combined_fields(body: dict) -> QueryNode:
    """combined_fields (CombinedFieldsQueryBuilder): BM25F-style scoring —
    here lowered onto the weighted most_fields sum, the closest shape in
    this engine's scoring model."""
    if "query" not in body or not body.get("fields"):
        raise ParsingException(
            "[combined_fields] requires [query] and [fields]"
        )
    raw_fields = body["fields"]
    field_boosts = {}
    for f in raw_fields:
        if "^" in f:
            name, _, sfx = f.partition("^")
            field_boosts[name] = float(sfx)
    return MultiMatchQuery(
        fields=[f.split("^")[0] for f in raw_fields],
        query=_query_text(body["query"]),
        type="most_fields",
        field_boosts=field_boosts,
        operator=str(body.get("operator", "or")).lower(),
        minimum_should_match=body.get("minimum_should_match"),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_multi_match(body: dict) -> QueryNode:
    mm_type = body.get("type", "best_fields")
    known = {"best_fields", "most_fields", "cross_fields", "phrase",
             "phrase_prefix", "bool_prefix"}
    if mm_type not in known:
        raise ParsingException(f"[multi_match] unknown type [{mm_type}]")
    # parameter/type validation (MultiMatchQueryBuilder.doToQuery rejects
    # positional params for term-centric types)
    if mm_type == "bool_prefix":
        for bad in ("slop", "cutoff_frequency"):
            if bad in body:
                raise ParsingException(
                    f"[{bad}] not allowed for type [{mm_type}]"
                )
    raw_fields = body.get("fields", [])
    for f in raw_fields:
        if not isinstance(f, str) or not f:
            raise ParsingException(
                "[multi_match] field name is null or empty"
            )
    field_boosts = {}
    for f in raw_fields:
        if "^" not in f:
            continue
        name, _, suffix = f.partition("^")
        try:
            field_boosts[name] = float(suffix)
        except ValueError:
            raise ParsingException(
                f"[multi_match] invalid field boost [{f}]"
            ) from None
    return MultiMatchQuery(
        fields=[f.split("^")[0] for f in raw_fields],
        query=_query_text(body.get("query", "")),
        type=mm_type,
        field_boosts=field_boosts,
        operator=str(body.get("operator", "or")).lower(),
        minimum_should_match=body.get("minimum_should_match"),
        fuzziness=body.get("fuzziness"),
        analyzer=body.get("analyzer"),
        slop=int(body.get("slop", 0)),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_term(body: dict) -> QueryNode:
    fname, conf = _single_kv(body, "term")
    if isinstance(conf, dict):
        return TermQuery(field=fname, value=conf.get("value"),
                         case_insensitive=bool(
                             conf.get("case_insensitive", False)),
                         boost=float(conf.get("boost", 1.0)))
    return TermQuery(field=fname, value=conf)


def _parse_terms(body: dict) -> QueryNode:
    body = dict(body)
    boost = float(body.pop("boost", 1.0))
    if len(body) != 1:
        raise ParsingException("[terms] query must have a single field")
    fname, values = next(iter(body.items()))
    if not isinstance(values, list):
        raise ParsingException("[terms] query values must be an array")
    return TermsQuery(field=fname, values=values, boost=boost)


def _parse_range(body: dict) -> QueryNode:
    fname, conf = _single_kv(body, "range")
    if not isinstance(conf, dict):
        raise ParsingException("[range] body must be an object")
    known = {"gte", "gt", "lte", "lt", "boost", "format", "time_zone", "relation",
             "from", "to", "include_lower", "include_upper"}
    unknown = set(conf) - known
    if unknown:
        raise ParsingException(f"[range] unknown options {sorted(unknown)}")
    gte, gt, lte, lt = conf.get("gte"), conf.get("gt"), conf.get("lte"), conf.get("lt")

    def _flag(v, default=True):
        if isinstance(v, str):
            return v.lower() != "false"
        return default if v is None else bool(v)

    # legacy from/to form
    if "from" in conf:
        if _flag(conf.get("include_lower")):
            gte = conf["from"]
        else:
            gt = conf["from"]
    if "to" in conf:
        if _flag(conf.get("include_upper")):
            lte = conf["to"]
        else:
            lt = conf["to"]
    return RangeQuery(field=fname, gte=gte, gt=gt, lte=lte, lt=lt,
                      relation=str(conf.get("relation", "intersects")).lower(),
                      boost=float(conf.get("boost", 1.0)))


def _parse_terms_set(body: dict) -> QueryNode:
    fname, conf = _single_kv(body, "terms_set")
    if not isinstance(conf, dict) or "terms" not in conf:
        raise ParsingException("[terms_set] requires [terms]")
    return TermsSetQuery(
        field=fname,
        terms=list(conf["terms"]),
        minimum_should_match_field=conf.get("minimum_should_match_field"),
        minimum_should_match_script=conf.get("minimum_should_match_script"),
        boost=float(conf.get("boost", 1.0)),
    )


def _parse_rank_feature(body: dict) -> QueryNode:
    if not isinstance(body, dict) or "field" not in body:
        raise ParsingException("[rank_feature] requires [field]")
    fn, pivot, sf, exp = "saturation", None, 1.0, 1.0
    if "saturation" in body:
        pivot = (body["saturation"] or {}).get("pivot")
    elif "log" in body:
        fn = "log"
        sf = float((body["log"] or {}).get("scaling_factor", 1.0))
    elif "sigmoid" in body:
        fn = "sigmoid"
        conf = body["sigmoid"] or {}
        pivot = conf.get("pivot")
        exp = float(conf.get("exponent", 1.0))
    elif "linear" in body:
        fn = "linear"
    return RankFeatureQuery(
        field=str(body["field"]), function=fn,
        pivot=float(pivot) if pivot is not None else None,
        scaling_factor=sf, exponent=exp,
        boost=float(body.get("boost", 1.0)),
    )


def _parse_geo_distance(body: dict) -> QueryNode:
    conf = dict(body)
    distance = conf.pop("distance", None)
    boost = float(conf.pop("boost", 1.0))
    conf.pop("distance_type", None)
    conf.pop("validation_method", None)
    conf.pop("_name", None)
    if distance is None or len(conf) != 1:
        raise ParsingException(
            "[geo_distance] requires [distance] and exactly one field"
        )
    fname, point = next(iter(conf.items()))
    return GeoDistanceQuery(field=fname, distance=distance, point=point,
                            boost=boost)


def _parse_geo_bounding_box(body: dict) -> QueryNode:
    conf = dict(body)
    boost = float(conf.pop("boost", 1.0))
    conf.pop("validation_method", None)
    conf.pop("type", None)
    conf.pop("_name", None)
    if len(conf) != 1:
        raise ParsingException(
            "[geo_bounding_box] requires exactly one field"
        )
    fname, box = next(iter(conf.items()))
    if not isinstance(box, dict):
        raise ParsingException("[geo_bounding_box] field body must be an object")
    tl = box.get("top_left")
    br = box.get("bottom_right")
    if tl is None or br is None:
        # corner-list form {"top_right": .., "bottom_left": ..} or wkt
        tr, bl = box.get("top_right"), box.get("bottom_left")
        if tr is not None and bl is not None:
            from opensearch_tpu.search.executor import _parse_geo_origin

            tr_lat, tr_lon = _parse_geo_origin(tr)
            bl_lat, bl_lon = _parse_geo_origin(bl)
            tl = {"lat": tr_lat, "lon": bl_lon}
            br = {"lat": bl_lat, "lon": tr_lon}
        else:
            raise ParsingException(
                "[geo_bounding_box] requires [top_left] and [bottom_right]"
            )
    return GeoBoundingBoxQuery(field=fname, top_left=tl, bottom_right=br,
                               boost=boost)


def _parse_geo_shape(body: dict) -> QueryNode:
    conf = dict(body)
    boost = float(conf.pop("boost", 1.0))
    conf.pop("ignore_unmapped", None)
    conf.pop("_name", None)
    if len(conf) != 1:
        raise ParsingException("[geo_shape] requires exactly one field")
    fname, fconf = next(iter(conf.items()))
    if not isinstance(fconf, dict) or "shape" not in fconf:
        raise ParsingException("[geo_shape] requires [shape]")
    relation = str(fconf.get("relation", "intersects")).lower()
    if relation not in ("intersects", "disjoint", "within", "contains"):
        raise ParsingException(f"[geo_shape] unknown relation [{relation}]")
    return GeoShapeQuery(field=fname, shape=fconf["shape"],
                         relation=relation, boost=boost)


def _parse_distance_feature(body: dict) -> QueryNode:
    if not isinstance(body, dict) or "field" not in body:
        raise ParsingException("[distance_feature] requires [field]")
    if "origin" not in body or "pivot" not in body:
        raise ParsingException(
            "[distance_feature] requires [origin] and [pivot]"
        )
    return DistanceFeatureQuery(
        field=str(body["field"]), origin=body["origin"],
        pivot=body["pivot"], boost=float(body.get("boost", 1.0)),
    )


def _parse_exists(body: dict) -> QueryNode:
    return ExistsQuery(field=str(body["field"]), boost=float(body.get("boost", 1.0)))


def _parse_ids(body: dict) -> QueryNode:
    return IdsQuery(values=[str(v) for v in body.get("values", [])],
                    boost=float(body.get("boost", 1.0)))


def _parse_msm(v: Any) -> int | None:
    if v is None:
        return None
    s = str(v)
    if s.endswith("%"):
        raise ParsingException("percentage minimum_should_match not yet supported")
    return int(s)


def _as_list(v: Any) -> list:
    return v if isinstance(v, list) else [v]


def _parse_bool(body: dict) -> QueryNode:
    return BoolQuery(
        must=[parse_query(q) for q in _as_list(body.get("must", []))],
        should=[parse_query(q) for q in _as_list(body.get("should", []))],
        filter=[parse_query(q) for q in _as_list(body.get("filter", []))],
        must_not=[parse_query(q) for q in _as_list(body.get("must_not", []))],
        minimum_should_match=_parse_msm(body.get("minimum_should_match")),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_constant_score(body: dict) -> QueryNode:
    return ConstantScoreQuery(
        filter=parse_query(body.get("filter")),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_knn(body: dict) -> QueryNode:
    fname, conf = _single_kv(body, "knn")
    if not isinstance(conf, dict) or "vector" not in conf:
        raise ParsingException("[knn] requires {field: {vector: [...], k: N}}")
    filt = conf.get("filter")
    return KnnQuery(
        field=fname,
        vector=[float(x) for x in conf["vector"]],
        k=int(conf.get("k", 10)),
        filter=parse_query(filt) if filt else None,
        method_parameters=(
            conf["method_parameters"]
            if isinstance(conf.get("method_parameters"), dict) else None
        ),
        boost=float(conf.get("boost", 1.0)),
    )


def _parse_term_level(cls, name: str, value_key: str = "value"):
    def parse(body: dict) -> QueryNode:
        fname, conf = _single_kv(body, name)
        if isinstance(conf, dict):
            kwargs = dict(
                field=fname,
                value=str(conf.get(value_key, conf.get("value", ""))),
                boost=float(conf.get("boost", 1.0)),
            )
            if cls is FuzzyQuery:
                kwargs["fuzziness"] = str(conf.get("fuzziness", "AUTO"))
                kwargs["prefix_length"] = int(conf.get("prefix_length", 0))
            else:
                kwargs["case_insensitive"] = bool(conf.get("case_insensitive", False))
            return cls(**kwargs)
        return cls(field=fname, value=str(conf))

    return parse


def _parse_match_phrase_prefix(body: dict) -> QueryNode:
    fname, conf = _single_kv(body, "match_phrase_prefix")
    if isinstance(conf, dict):
        return MatchPhrasePrefixQuery(
            field=fname, query=_query_text(conf.get("query", "")),
            max_expansions=int(conf.get("max_expansions", 50)),
            boost=float(conf.get("boost", 1.0)),
        )
    return MatchPhrasePrefixQuery(field=fname, query=_query_text(conf))


def _parse_match_bool_prefix(body: dict) -> QueryNode:
    fname, conf = _single_kv(body, "match_bool_prefix")
    if isinstance(conf, dict):
        return MatchBoolPrefixQuery(
            field=fname, query=_query_text(conf.get("query", "")),
            operator=str(conf.get("operator", "or")).lower(),
            minimum_should_match=conf.get("minimum_should_match"),
            fuzziness=conf.get("fuzziness"),
            analyzer=conf.get("analyzer"),
            boost=float(conf.get("boost", 1.0)),
        )
    return MatchBoolPrefixQuery(field=fname, query=_query_text(conf))


def _parse_query_string(body: dict) -> QueryNode:
    fields = [f.split("^")[0] for f in body.get("fields", [])]
    if body.get("default_field"):
        fields = [str(body["default_field"]).split("^")[0]]
    return QueryStringQuery(
        query=_query_text(body.get("query", "")),
        fields=fields,
        default_operator=str(body.get("default_operator", "or")).lower(),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_simple_query_string(body: dict) -> QueryNode:
    return SimpleQueryStringQuery(
        query=_query_text(body.get("query", "")),
        fields=[f.split("^")[0] for f in body.get("fields", [])],
        default_operator=str(body.get("default_operator", "or")).lower(),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_boosting(body: dict) -> QueryNode:
    if "positive" not in body or "negative" not in body:
        raise ParsingException("[boosting] requires [positive] and [negative]")
    return BoostingQuery(
        positive=parse_query(body["positive"]),
        negative=parse_query(body["negative"]),
        negative_boost=float(body.get("negative_boost", 0.5)),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_dis_max(body: dict) -> QueryNode:
    return DisMaxQuery(
        queries=[parse_query(q) for q in body.get("queries", [])],
        tie_breaker=float(body.get("tie_breaker", 0.0)),
        boost=float(body.get("boost", 1.0)),
    )


_FVF_MODIFIERS = {
    "none", "log", "log1p", "log2p", "ln", "ln1p", "ln2p",
    "square", "sqrt", "reciprocal",
}


def _parse_one_function(conf: dict) -> ScoreFunction:
    fn = ScoreFunction()
    if conf.get("filter") is not None:
        fn.filter = parse_query(conf["filter"])
    if "weight" in conf:
        fn.weight = float(conf["weight"])
    if "field_value_factor" in conf:
        fvf = conf["field_value_factor"]
        fn.kind = "field_value_factor"
        fn.field = str(fvf.get("field", ""))
        fn.factor = float(fvf.get("factor", 1.0))
        fn.modifier = str(fvf.get("modifier", "none")).lower()
        if fn.modifier not in _FVF_MODIFIERS:
            raise ParsingException(f"unknown field_value_factor modifier [{fn.modifier}]")
        fn.missing = float(fvf["missing"]) if "missing" in fvf else None
    elif "random_score" in conf:
        fn.kind = "random_score"
        fn.seed = int((conf["random_score"] or {}).get("seed", 0))
    elif any(d in conf for d in ("gauss", "exp", "linear")):
        fn.kind = "decay"
        fn.decay_type = next(d for d in ("gauss", "exp", "linear") if d in conf)
        spec = conf[fn.decay_type]
        fname, dconf = _single_kv(spec, fn.decay_type)
        fn.field = fname
        fn.origin = dconf.get("origin")
        fn.scale = dconf.get("scale")
        fn.offset = dconf.get("offset", 0)
        fn.decay = float(dconf.get("decay", 0.5))
        if fn.scale is None:
            raise ParsingException(f"[{fn.decay_type}] requires [scale]")
    elif "weight" in conf:
        fn.kind = "weight"
    elif "script_score" in conf:
        raise ParsingException(
            "script_score inside function_score is not supported; use the "
            "top-level script_score query"
        )
    else:
        fn.kind = "weight"
        if fn.weight is None:
            raise ParsingException(f"unknown function in function_score: {sorted(conf)}")
    return fn


def _parse_function_score(body: dict) -> QueryNode:
    functions = [_parse_one_function(f) for f in body.get("functions", [])]
    # shorthand single-function form
    if not functions:
        single = {
            k: v for k, v in body.items()
            if k in ("field_value_factor", "random_score", "gauss", "exp", "linear", "weight")
        }
        if single:
            functions = [_parse_one_function(single)]
    return FunctionScoreQuery(
        query=parse_query(body.get("query")) if body.get("query") else MatchAllQuery(),
        functions=functions,
        score_mode=str(body.get("score_mode", "multiply")).lower(),
        boost_mode=str(body.get("boost_mode", "multiply")).lower(),
        max_boost=float(body.get("max_boost", float("inf"))),
        min_score=float(body["min_score"]) if "min_score" in body else None,
        boost=float(body.get("boost", 1.0)),
    )


def _parse_nested(body: dict) -> QueryNode:
    if "path" not in body or "query" not in body:
        raise ParsingException("[nested] requires [path] and [query]")
    return NestedQuery(
        path=str(body["path"]),
        query=parse_query(body["query"]),
        score_mode=str(body.get("score_mode", "avg")),
        boost=float(body.get("boost", 1.0)),
    )


def _parse_hybrid(conf: dict) -> QueryNode:
    if not isinstance(conf, dict) or not isinstance(conf.get("queries"), list):
        raise ParsingException("[hybrid] requires a [queries] array")
    queries = [parse_query(q) for q in conf["queries"]]
    if not queries:
        raise ParsingException("[hybrid] requires at least one sub-query")
    if len(queries) > 5:
        raise ParsingException("[hybrid] supports at most 5 sub-queries")
    return HybridQuery(queries=queries, boost=float(conf.get("boost", 1.0)))


_VECTOR_FUNCS = ("cosineSimilarity", "dotProduct", "l2Squared", "knn_score")


def _parse_script_score(body: dict) -> QueryNode:
    inner = parse_query(body.get("query"))
    script = body.get("script") or {}
    source = script.get("source", "")
    params = script.get("params") or {}
    if source == "knn_score":
        # legacy k-NN plugin script: params {field, query_value, space_type}
        return ScriptScoreQuery(
            query=inner,
            function="knn_score",
            field=str(params.get("field", "")),
            query_vector=[float(x) for x in params.get("query_value", [])],
            space_type=params.get("space_type", "l2"),
            boost=float(body.get("boost", 1.0)),
        )
    for fn in _VECTOR_FUNCS:
        if fn in source:
            # e.g. "cosineSimilarity(params.query_vector, doc['vec']) + 1.0"
            import re

            m = re.search(
                rf"{fn}\(\s*params\.(\w+)\s*,\s*doc\[['\"]([\w.]+)['\"]\]\s*\)"
                r"(?:\s*\+\s*([0-9.]+))?",
                source,
            )
            if not m:
                raise ParsingException(f"unsupported script_score source [{source}]")
            pname, fieldname, const = m.groups()
            if pname not in params:
                raise ParsingException(f"missing script param [{pname}]")
            space = {"cosineSimilarity": "cosine", "dotProduct": "dot_product",
                     "l2Squared": "l2_raw"}[fn] if fn != "knn_score" else "l2"
            return ScriptScoreQuery(
                query=inner,
                function=fn,
                field=fieldname,
                query_vector=[float(x) for x in params[pname]],
                space_type=space,
                add_constant=float(const) if const else 0.0,
                boost=float(body.get("boost", 1.0)),
            )
    # arbitrary painless script: per-doc host evaluation path
    return GenericScriptScoreQuery(
        query=inner, script=script, boost=float(body.get("boost", 1.0))
    )


def _parse_script_query(body: dict) -> QueryNode:
    if "script" not in body:
        raise ParsingException("[script] query requires [script]")
    return ScriptQuery(script=body["script"], boost=float(body.get("boost", 1.0)))


def _parse_more_like_this(conf: dict) -> QueryNode:
    like = conf.get("like")
    if like is None:
        raise ParsingException("[more_like_this] requires [like]")
    likes = like if isinstance(like, list) else [like]
    texts = [x for x in likes if isinstance(x, str)]
    docs = [x for x in likes if isinstance(x, dict)]
    fields = conf.get("fields") or []
    return MoreLikeThisQuery(
        fields=list(fields),
        like_texts=texts,
        like_docs=docs,
        min_term_freq=int(conf.get("min_term_freq", 2)),
        min_doc_freq=int(conf.get("min_doc_freq", 5)),
        max_query_terms=int(conf.get("max_query_terms", 25)),
        minimum_should_match=str(conf.get("minimum_should_match", "30%")),
        boost=float(conf.get("boost", 1.0)),
    )


def _parse_percolate(conf: dict) -> QueryNode:
    if not isinstance(conf, dict) or not conf.get("field"):
        raise ParsingException("[percolate] requires [field]")
    if "document" in conf:
        documents = [conf["document"]]
    elif "documents" in conf:
        documents = list(conf["documents"])
    else:
        raise ParsingException("[percolate] requires [document] or [documents]")
    return PercolateQuery(
        field=conf["field"], documents=documents,
        boost=float(conf.get("boost", 1.0)),
    )


def _parse_has_child(conf: dict) -> QueryNode:
    if not conf.get("type") or "query" not in conf:
        raise ParsingException("[has_child] requires [type] and [query]")
    return HasChildQuery(
        type=conf["type"],
        query=parse_query(conf["query"]),
        score_mode=conf.get("score_mode", "none"),
        min_children=int(conf.get("min_children", 1)),
        max_children=int(conf.get("max_children", 2**31 - 1)),
        boost=float(conf.get("boost", 1.0)),
    )


def _parse_has_parent(conf: dict) -> QueryNode:
    if not conf.get("parent_type") or "query" not in conf:
        raise ParsingException("[has_parent] requires [parent_type] and [query]")
    return HasParentQuery(
        parent_type=conf["parent_type"],
        query=parse_query(conf["query"]),
        score=bool(conf.get("score", False)),
        boost=float(conf.get("boost", 1.0)),
    )


def _parse_parent_id(conf: dict) -> QueryNode:
    if not conf.get("type") or conf.get("id") is None:
        raise ParsingException("[parent_id] requires [type] and [id]")
    return ParentIdQuery(
        type=conf["type"], id=str(conf["id"]),
        boost=float(conf.get("boost", 1.0)),
    )


_PARSERS = {
    "more_like_this": _parse_more_like_this,
    "percolate": _parse_percolate,
    "has_child": _parse_has_child,
    "has_parent": _parse_has_parent,
    "parent_id": _parse_parent_id,
    "match_all": _parse_match_all,
    "match_none": _parse_match_none,
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "intervals": _parse_intervals,
    "span_term": _parse_span_query("span_term"),
    "span_near": _parse_span_query("span_near"),
    "span_or": _parse_span_query("span_or"),
    "span_first": _parse_span_query("span_first"),
    "span_not": _parse_span_query("span_not"),
    "span_containing": _parse_span_query("span_containing"),
    "span_within": _parse_span_query("span_within"),
    "span_multi": _parse_span_query("span_multi"),
    "multi_match": _parse_multi_match,
    "combined_fields": _parse_combined_fields,
    "term": _parse_term,
    "terms": _parse_terms,
    "range": _parse_range,
    "exists": _parse_exists,
    "terms_set": _parse_terms_set,
    "distance_feature": _parse_distance_feature,
    "geo_distance": _parse_geo_distance,
    "rank_feature": _parse_rank_feature,
    "geo_bounding_box": _parse_geo_bounding_box,
    "geo_shape": _parse_geo_shape,
    "ids": _parse_ids,
    "bool": _parse_bool,
    "constant_score": _parse_constant_score,
    "knn": _parse_knn,
    "script_score": _parse_script_score,
    "script": _parse_script_query,
    "prefix": _parse_term_level(PrefixQuery, "prefix"),
    "wildcard": _parse_term_level(WildcardQuery, "wildcard", "wildcard"),
    "regexp": _parse_term_level(RegexpQuery, "regexp"),
    "fuzzy": _parse_term_level(FuzzyQuery, "fuzzy"),
    "match_phrase_prefix": _parse_match_phrase_prefix,
    "match_bool_prefix": _parse_match_bool_prefix,
    "query_string": _parse_query_string,
    "simple_query_string": _parse_simple_query_string,
    "boosting": _parse_boosting,
    "dis_max": _parse_dis_max,
    "function_score": _parse_function_score,
    "nested": _parse_nested,
    "hybrid": _parse_hybrid,
}
