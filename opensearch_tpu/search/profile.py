"""Deep per-operator search profiler (the `"profile": true` engine).

The analog of the reference's search/profile/ package (Profilers,
AbstractProfileBreakdown, AggregationProfiler) rebuilt around what actually
costs time on this engine, per TPU-KNN's roofline argument (arxiv
2206.14286: reason about kernels against peak FLOP/s — which requires
per-kernel timing with explicit fences and host<->device transfer byte
counts) and FusionANNS-style stage attribution (arxiv 2409.16576):

- an OPERATOR TREE: one entry per executed query node (BoolQuery children
  nest), accumulated across the shard's segments, with the classic
  rewrite/build_scorer/score breakdown analogs;
- TPU-specific fields per operator and per shard: `device_time_in_nanos`
  (kernel wall bracketed by `block_until_ready` fences — without the fence
  async dispatch attributes kernel time to whoever materializes the result
  later), `transfer_bytes` (host-resident arguments shipped to the device
  for this request; resident postings/vectors don't count), and `retraced`
  (first time this process launches a kernel under this argument-shape
  signature — the jit retrace/compile proxy);
- per-aggregation collector timings feeding the agg profile entries.

The active profiler rides a contextvar (`profiling(...)` scope) so the
executor, the aggregation framework, and the ops kernels record into it
without threading a handle through every signature. When no profiler is
active the instrumented paths cost one contextvar read.
"""

from __future__ import annotations

import contextvars
import time
from typing import Any, Callable

_active_profiler: contextvars.ContextVar["ShardProfiler | None"] = (
    contextvars.ContextVar("opensearch_tpu_active_profiler", default=None)
)

# (kernel name, arg signature) pairs this process has launched before; a
# miss is the retrace/compile proxy (jit caches compiled programs by the
# same key: static config + arg shapes/dtypes)
_seen_kernel_signatures: set[tuple] = set()


def active() -> "ShardProfiler | None":
    return _active_profiler.get()


class _ProfilingScope:
    __slots__ = ("_profiler", "_token")

    def __init__(self, profiler: "ShardProfiler | None"):
        self._profiler = profiler

    def __enter__(self) -> "ShardProfiler | None":
        self._token = _active_profiler.set(self._profiler)
        return self._profiler

    def __exit__(self, exc_type, exc, tb):
        _active_profiler.reset(self._token)
        return False


def profiling(profiler: "ShardProfiler | None") -> _ProfilingScope:
    return _ProfilingScope(profiler)


class OpProfile:
    """One operator node of the profile tree, accumulated across segments
    (the same query node executes once per segment of the shard)."""

    __slots__ = ("type", "description", "time_ns", "device_ns",
                 "transfer_bytes", "retraced", "kernels", "children",
                 "_child_index", "calls", "kernel_annotations")

    def __init__(self, type_: str, description: str):
        self.type = type_
        self.description = description
        self.time_ns = 0
        self.device_ns = 0
        self.transfer_bytes = 0
        self.retraced = False
        self.calls = 0
        # kernel name -> [calls, time_ns, transfer_bytes, retraces]
        self.kernels: dict[str, list] = {}
        # kernel name -> static launch configuration (e.g. the ANN path's
        # adc_precision / rescore candidate pool); merged PER KEY — when a
        # request's records disagree on a value (a live precision flip
        # between segments, a coalesced mixed batch) the key keeps every
        # distinct value as a list instead of silently reporting only the
        # last writer's
        self.kernel_annotations: dict[str, dict] = {}
        self.children: list[OpProfile] = []
        self._child_index: dict[tuple[str, str], OpProfile] = {}

    def child(self, type_: str, description: str) -> "OpProfile":
        key = (type_, description)
        op = self._child_index.get(key)
        if op is None:
            op = OpProfile(type_, description)
            self._child_index[key] = op
            self.children.append(op)
        return op

    def record_kernel(self, name: str, time_ns: int, transfer_bytes: int,
                      retraced: bool, annotations: dict | None = None) -> None:
        self.device_ns += time_ns
        self.transfer_bytes += transfer_bytes
        self.retraced = self.retraced or retraced
        cell = self.kernels.setdefault(name, [0, 0, 0, 0])
        cell[0] += 1
        cell[1] += time_ns
        cell[2] += transfer_bytes
        cell[3] += int(retraced)
        if annotations:
            merged = self.kernel_annotations.setdefault(name, {})
            for key, value in annotations.items():
                have = merged.get(key)
                if key not in merged:
                    merged[key] = value
                elif isinstance(have, list):
                    if value not in have:
                        have.append(value)
                elif have != value:
                    merged[key] = [have, value]

    def to_dict(self) -> dict:
        # children's wall time is nested inside self.time_ns (inclusive),
        # so the host-side share is self minus device minus children
        child_ns = sum(c.time_ns for c in self.children)
        host_ns = max(self.time_ns - self.device_ns - child_ns, 0)
        out: dict[str, Any] = {
            "type": self.type,
            "description": self.description,
            "time_in_nanos": self.time_ns,
            "breakdown": {
                # Lucene analogs: create_weight ~ host-side query prep,
                # build_scorer ~ kernel launches (device), score ~ device
                # scoring time, next_doc ~ folded into score (vectorized)
                "create_weight": host_ns, "create_weight_count": self.calls,
                "build_scorer": 0, "build_scorer_count": self.calls,
                "score": self.device_ns,
                "score_count": self.calls,
                "next_doc": 0, "next_doc_count": 0,
            },
            # TPU-specific fields (TPU-KNN roofline attribution)
            "device_time_in_nanos": self.device_ns,
            "transfer_bytes": self.transfer_bytes,
            "retraced": self.retraced,
        }
        if self.kernels:
            # roofline attribution per kernel row (telemetry/roofline.py):
            # the family's EWMA achieved GFLOP/s, arithmetic intensity,
            # fraction of the calibrated roofline, and the bound verdict —
            # "profile": true answers "is this kernel worth rewriting"
            from opensearch_tpu.telemetry.roofline import default_recorder

            out["kernels"] = [
                {"name": name, "calls": c[0], "time_in_nanos": c[1],
                 "transfer_bytes": c[2], "retraces": c[3],
                 **default_recorder.kernel_row_fields(name),
                 **(self.kernel_annotations.get(name) or {})}
                for name, c in sorted(self.kernels.items())
            ]
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out


class ShardProfiler:
    """Collects one shard's query-phase profile: the operator tree,
    rewrite (can_match) time, collector (top-k/sort) time, per-agg
    collector timings, and the shard-level TPU totals."""

    def __init__(self) -> None:
        self._root = OpProfile("<root>", "")
        self._stack: list[OpProfile] = [self._root]
        self.rewrite_ns = 0
        self.collect_ns = 0
        # agg name -> {"time_ns": int, "collect_count": int}
        self.agg_times: dict[str, int] = {}
        # sharded-launch records (record_sharded_launch): one entry per
        # device launch this shard participated in; every shard covered by
        # the same launch carries the same launch_id
        self.launches: list[dict] = []

    # -- operator tree ------------------------------------------------------

    class _OpScope:
        __slots__ = ("_profiler", "_op", "_t0")

        def __init__(self, profiler: "ShardProfiler", op: "OpProfile"):
            self._profiler = profiler
            self._op = op

        def __enter__(self) -> "OpProfile":
            self._profiler._stack.append(self._op)
            self._op.calls += 1
            self._t0 = time.perf_counter_ns()
            return self._op

        def __exit__(self, exc_type, exc, tb):
            self._op.time_ns += time.perf_counter_ns() - self._t0
            self._profiler._stack.pop()
            return False

    def operator(self, type_: str, description: str) -> "_OpScope":
        op = self._stack[-1].child(type_, description)
        return ShardProfiler._OpScope(self, op)

    def record_kernel(self, name: str, time_ns: int, transfer_bytes: int,
                      retraced: bool, annotations: dict | None = None) -> None:
        self._stack[-1].record_kernel(name, time_ns, transfer_bytes, retraced,
                                      annotations)

    def record_agg(self, name: str, time_ns: int) -> None:
        self.agg_times[name] = self.agg_times.get(name, 0) + time_ns

    def record_sharded_launch(self, type_: str, description: str, *,
                              name: str, launch_id: int, shards: int,
                              wall_ns: int, transfer_bytes: int,
                              retraced: bool) -> None:
        """Attribute this shard's share of ONE sharded device launch (the
        shard-mesh kNN program covers S shards in a single `shard_map`
        dispatch). The fenced launch wall splits evenly across the shards
        it served; the shared `launch_id` is how a reader of the per-shard
        profile entries proves they came from one launch, not S."""
        op = self._stack[-1].child(type_, description)
        op.calls += 1
        share = wall_ns // max(shards, 1)
        op.time_ns += share
        op.record_kernel(name, share, transfer_bytes, retraced)
        self.launches.append({
            "name": name, "launch_id": launch_id, "shards": shards,
            "wall_ns": wall_ns, "share_ns": share, "retraced": retraced,
        })

    # -- rollups ------------------------------------------------------------

    @property
    def roots(self) -> list[OpProfile]:
        return self._root.children

    def _totals(self) -> tuple[int, int, bool]:
        device = transfer = 0
        retraced = False
        stack = list(self.roots)
        while stack:
            op = stack.pop()
            device += op.device_ns
            transfer += op.transfer_bytes
            retraced = retraced or op.retraced
            stack.extend(op.children)
        return device, transfer, retraced

    def query_entries(self) -> list[dict]:
        return [op.to_dict() for op in self.roots]

    def total_time_ns(self) -> int:
        return sum(op.time_ns for op in self.roots)

    def tpu_summary(self) -> dict:
        device, transfer, retraced = self._totals()
        out = {
            "device_time_in_nanos": device,
            "transfer_bytes": transfer,
            "jit_retrace": retraced,
        }
        if self.launches:
            out["launches"] = list(self.launches)
        return out


# fetch sub-phase keys -> the reference's subphase class names
# (fetch/subphase/*; search/fetch/FetchPhase.java runs them per winning doc)
FETCH_SUBPHASES = {
    "load_source": "FetchSourcePhase",
    "docvalue_fields": "FetchDocValuesPhase",
    "fields": "FetchFieldsPhase",
    "stored_fields": "StoredFieldsPhase",
    "highlight": "HighlightPhase",
    "script_fields": "ScriptFieldsPhase",
    "explain": "ExplainPhase",
}


class FetchProfiler:
    """Per-shard fetch-phase sub-phase timings: the `"profile": true`
    coverage for fetch that the operator tree provides for the query phase
    (the reference's FetchProfiler / ProfileResult over the 17-subphase
    chain). One instance covers one search request; hits attribute to the
    shard they came from, so per-shard entries merge across a cluster
    exactly like the query profiles do."""

    def __init__(self, n_shards: int) -> None:
        # shard idx -> {subphase: [time_ns, count]}
        self._phases: list[dict[str, list[int]]] = [
            {} for _ in range(n_shards)
        ]
        self._hits: list[int] = [0] * n_shards

    def hit(self, shard_idx: int) -> None:
        self._hits[shard_idx] += 1

    def add(self, shard_idx: int, phase: str, t0_ns: int) -> None:
        cell = self._phases[shard_idx].setdefault(phase, [0, 0])
        cell[0] += time.perf_counter_ns() - t0_ns
        cell[1] += 1

    def entry(self, shard_idx: int) -> dict:
        phases = self._phases[shard_idx]
        total = sum(c[0] for c in phases.values())
        breakdown: dict[str, int] = {}
        children = []
        for key, cls in FETCH_SUBPHASES.items():
            ns, count = phases.get(key, (0, 0))
            breakdown[key] = ns
            breakdown[f"{key}_count"] = count
            if count:
                children.append({
                    "type": cls, "description": key,
                    "time_in_nanos": ns,
                    "breakdown": {key: ns, f"{key}_count": count},
                })
        return {
            "type": "fetch",
            "description": "fetch",
            "time_in_nanos": total,
            "breakdown": breakdown,
            "debug": {"hits_fetched": self._hits[shard_idx]},
            "children": children,
        }


def describe_node(node: Any) -> str:
    """Compact operator description: the node's salient config, not the
    whole query JSON (which the reference also truncates)."""
    parts = []
    for attr in ("field", "fields", "query", "value", "values", "k"):
        v = getattr(node, attr, None)
        if v is None:
            continue
        text = str(v)
        if len(text) > 64:
            text = text[:61] + "..."
        parts.append(f"{attr}={text}")
    return " ".join(parts)


def _host_bytes(value: Any) -> int:
    """Bytes this argument ships host->device: numpy arrays and python
    sequences count, resident jax Arrays don't."""
    nbytes = getattr(value, "nbytes", None)
    if nbytes is not None:
        # jax Arrays are already device-resident; numpy arrays transfer
        return 0 if _is_jax_array(value) else int(nbytes)
    if isinstance(value, (list, tuple)):
        return 8 * len(value)
    if isinstance(value, (int, float, bool)):
        return 8
    return 0


def _is_jax_array(value: Any) -> bool:
    try:
        import jax

        return isinstance(value, jax.Array)
    except (ImportError, AttributeError):  # no jax Array API: not a jax array
        return False


def _under_trace(args: tuple) -> bool:
    try:
        from jax.core import Tracer

        return any(isinstance(a, Tracer) for a in args)
    except (ImportError, AttributeError):  # jax internals moved; assume eager
        return False


def _signature(name: str, args: tuple, kwargs: dict) -> tuple:
    parts: list = [name]
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            parts.append((tuple(shape), str(getattr(a, "dtype", ""))))
        elif isinstance(a, (list, tuple)):
            parts.append(("seq", len(a)))
        else:
            parts.append(type(a).__name__)
    for k in sorted(kwargs):
        parts.append((k, str(kwargs[k])))
    return tuple(parts)


def _block_until_ready(out: Any) -> None:
    if isinstance(out, (list, tuple)):
        for item in out:
            _block_until_ready(item)
        return
    fence = getattr(out, "block_until_ready", None)
    if fence is not None:
        fence()


def signature_retraced(name: str, args: tuple, static: tuple = ()) -> bool:
    """Manual retrace probe for jitted paths the decorator can't wrap
    (cached program factories): True the first time this process sees the
    (name, arg shapes, static config) combination."""
    sig = _signature(name, args, {"static": static})
    retraced = sig not in _seen_kernel_signatures
    _seen_kernel_signatures.add(sig)
    return retraced


def profiled_kernel(name: str) -> Callable:
    """Decorator for device kernel entry points (ops/bm25.py, ops/knn.py):
    when a profiler is active and the call is eager (not inside a jit
    trace), bracket the launch with `block_until_ready`, count host->device
    transfer bytes, and flag first-seen argument-shape signatures as
    retraces. Zero-cost path otherwise: one contextvar read."""

    def deco(fn: Callable) -> Callable:
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            prof = _active_profiler.get()
            if prof is None or _under_trace(args):
                return fn(*args, **kwargs)
            transfer = sum(_host_bytes(a) for a in args)
            transfer += sum(_host_bytes(v) for v in kwargs.values())
            sig = _signature(name, args, kwargs)
            retraced = sig not in _seen_kernel_signatures
            _seen_kernel_signatures.add(sig)
            t0 = time.perf_counter_ns()
            out = fn(*args, **kwargs)
            # fence: without it async dispatch returns immediately and the
            # kernel time lands on whoever np.asarray()s the result later
            _block_until_ready(out)
            elapsed = time.perf_counter_ns() - t0
            prof.record_kernel(name, elapsed, transfer, retraced)
            # roofline accounting: the fenced wall + the call's argument
            # shapes are exactly what the family's cost model needs
            from opensearch_tpu.telemetry import roofline

            roofline.observe_kernel(name, args, kwargs, elapsed)
            if retraced:
                # retrace oracle fired: one jit-cache entry for this kernel
                # family in the device ledger's compile table (the first
                # launch wall includes the compile)
                from opensearch_tpu.telemetry.device_ledger import (
                    default_ledger,
                )

                default_ledger.record_compile(name, elapsed)
            return out

        return wrapper

    return deco
