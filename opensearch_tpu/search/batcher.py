"""Adaptive cross-request micro-batching for device kNN dispatch.

The continuous-batching pattern of every inference-serving stack applied to
the search path: today N concurrent requests over the same device-resident
corpus pay N kernel launches, and the bench shows per-dispatch overhead
dominates throughput (BENCH dispatch_wall ~145 ms for 2000 solo-chunked
queries vs ~70 ms for one batched call of 100 — TPU-KNN's whole point,
arxiv 2206.14286, is amortizing one large batched distance computation
across many queries; FusionANNS, arxiv 2409.16576, shows the same
coalescing for heterogeneous serving).

Mechanism: shard-level kNN dispatch sites (executor.shard_knn_selection's
streaming and materializing scans, and the distributed serving program in
search/service.py) route each query through :func:`dispatch` with a BATCH
KEY — the identity of the kernel launch they would have made: (kind,
device-column identity, reader GENERATION, k bucket, similarity, chunk).
Concurrent queries with the same key coalesce into one padded batch launch;
per-query rows scatter back to the waiting requests. Because the key
carries the snapshot generation, a mid-flight refresh can never merge a
query into a batch against the wrong snapshot — the bumped generation is a
different key, a different bucket, a different launch.

Flush policy (the "adaptive" part):
 - size threshold: a bucket reaching ``max_batch_size`` flushes at once;
 - deadline: otherwise the earliest-queued entry flushes the bucket after
   ``max_wait_ms`` (timeutil clock, so sim runs stay deterministic);
 - adaptive solo fast-path: when recent flushes show no concurrency (EWMA
   of merged batch sizes at/below ~1) and no launch for the key is in
   flight, a new arrival launches immediately — sequential clients pay
   zero added latency, and the wait window re-engages as soon as merged
   batches reappear. While a launch IS in flight, arrivals queue and the
   completing leader flags the backlog for immediate flush (continuous
   batching: the next batch forms while the device is busy).

Batch sizes are padded to powers of two (pad rows are zero queries whose
results are sliced off) so the jit program cache stays warm across batch
widths — the PR 3 profiler's per-operator `retraced` flag is the
regression oracle for this.

Since the shard-mesh data plane (ISSUE 7) the batcher coalesces across
SHARDS as well as requests: the mesh kNN path's batch key spans a whole
node's shard set (service.py's distributed_knn key), so one launch serves
many concurrent queries over all resident shards at once. Callers declare
the span via ``dispatch(..., shards=S)``; `cross_shard_launches` /
`cross_shard_queries` in the stats (and the `knn.batch.shards` histogram)
show when that amortization is happening.

Since the batched ANN path (ISSUE 9) the batcher also serves IVF-PQ
launches: the executor's ANN branch dispatches with kernel kind "ivfpq"
keys carrying the INDEX-BUILD GENERATION (a rebuild can never merge into
an old batch), nprobe/k buckets, and the live ADC precision pair
(search/ann.py). ``kind="ann"`` splits the `ann_dispatches` /
`exact_dispatches` counters, and ``alt_keys`` enables CROSS-K coalescing:
a k=5 arrival rides a same-family k=8 batch already forming
(`cross_k_served`), since the bigger-k rows truncate for free.

Since the fused Pallas ADC scan (ISSUE 14) the ANN key ALSO carries the
RESOLVED KERNEL VARIANT (search/ann.resolve_kernel: "pallas" fused scan
vs "xla" monolithic lowering): a live `search.knn.ann.kernel` flip starts
new batches under the new variant, and because the key still carries the
build generation, a mid-stream ANN rebuild can never merge old-generation
queries into the new kernel variant either.

Backpressure: the pending-query queue is bounded by a
:class:`~opensearch_tpu.index.pressure.QueuePressure` budget — crossing it
sheds the request with RejectedExecutionException (HTTP 429) instead of
growing the queue (the IndexingPressure shedding contract, and the
tpulint unbounded-queue concern).

Since the tail-latency control plane (ISSUE 11) the wait window is
PER-KEY AUTO-TUNED: a :class:`_KeyTuner` per stable key family (the
``tune_key`` callers pass — the batch key minus its generation terms, so
a refresh doesn't reset what the controller learned) tracks the EWMA of
merged batch sizes, measured per-entry queue waits, and inter-arrival
gaps, and derives each arrival's effective wait from them. Solo traffic
converges to a ~0 ms window (no added latency); bursty keys earn up to
the configured ``max_wait_ms``. The request's priority LANE
(search/lanes.py contextvar) rides along: background entries accept a
longer deadline (they earn bigger merges), but because every entry keeps
its OWN deadline and a flush takes the whole bucket, an interactive
arrival's short deadline flushes any backlog of background entries it
joins — background queueing can never extend an interactive wait.

Settings (dynamic, cluster scope — see common/settings.py Setting model):
  search.knn.batch.max_wait_ms   flush deadline ceiling (default 2ms)
  search.knn.batch.max_batch_size  flush size bound  (default 32)
  search.knn.batch.max_queue     pending-query bound (default 1024)
  search.knn.batch.enabled       kill switch         (default true)
  search.knn.batch.auto_tune     per-key wait tuner  (default true)
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Sequence

from opensearch_tpu.common import timeutil
from opensearch_tpu.common.settings import Property, Setting
from opensearch_tpu.index.pressure import QueuePressure

# -- settings (registered dynamic in cluster/cluster_settings.py) -----------

MAX_WAIT_MS_SETTING = Setting.time_setting(
    "search.knn.batch.max_wait_ms", 2,
    Property.NODE_SCOPE, Property.DYNAMIC,
)
MAX_BATCH_SIZE_SETTING = Setting.int_setting(
    "search.knn.batch.max_batch_size", 32,
    Property.NODE_SCOPE, Property.DYNAMIC, min_value=1,
)
MAX_QUEUE_SETTING = Setting.int_setting(
    "search.knn.batch.max_queue", 1024,
    Property.NODE_SCOPE, Property.DYNAMIC, min_value=0,
)
ENABLED_SETTING = Setting.bool_setting(
    "search.knn.batch.enabled", True,
    Property.NODE_SCOPE, Property.DYNAMIC,
)
AUTO_TUNE_SETTING = Setting.bool_setting(
    "search.knn.batch.auto_tune", True,
    Property.NODE_SCOPE, Property.DYNAMIC,
)

BATCH_SETTINGS = (
    MAX_WAIT_MS_SETTING, MAX_BATCH_SIZE_SETTING, MAX_QUEUE_SETTING,
    ENABLED_SETTING, AUTO_TUNE_SETTING,
)

# EWMA of merged batch sizes at/below this -> no recent concurrency ->
# skip the wait window for idle-device arrivals
_SOLO_EWMA_THRESHOLD = 1.25
_EWMA_DECAY = 0.7
# background-lane entries accept this multiple of the configured wait:
# they are throughput traffic, and a longer window earns bigger merges —
# interactive entries in the same bucket still flush it at THEIR deadline
_BACKGROUND_WAIT_FACTOR = 4
# per-key tuner table bound (LRU): tune_keys are generation-free and few,
# but a pathological workload must not grow the table without bound
_MAX_TUNERS = 256


class _KeyTuner:
    """Per-key-family wait controller. Fed (under the batcher lock) by
    every arrival and every flush; read at dispatch time to derive the
    entry's effective wait window from what this key's traffic has
    actually been doing — the measured queue-wait and arrival-rate
    distributions, not the static ceiling."""

    __slots__ = ("ewma_merged", "ewma_wait_ms", "ewma_gap_ms", "flushes",
                 "last_arrival_ms")

    def __init__(self) -> None:
        # optimistic start (matches the batcher's global EWMA): assume
        # concurrency until flushes prove otherwise, so a key's first
        # burst coalesces instead of stampeding solo
        self.ewma_merged = 2.0 * _SOLO_EWMA_THRESHOLD
        self.ewma_wait_ms = 0.0
        self.ewma_gap_ms: float | None = None
        self.flushes = 0
        self.last_arrival_ms: int | None = None

    def note_arrival(self, now_ms: int) -> None:
        if self.last_arrival_ms is not None:
            gap = max(0, now_ms - self.last_arrival_ms)
            self.ewma_gap_ms = (
                gap if self.ewma_gap_ms is None
                else _EWMA_DECAY * self.ewma_gap_ms + (1 - _EWMA_DECAY) * gap)
        self.last_arrival_ms = now_ms

    def note_flush(self, merged: int, max_wait_ms: int) -> None:
        self.ewma_merged = (_EWMA_DECAY * self.ewma_merged
                            + (1 - _EWMA_DECAY) * merged)
        self.ewma_wait_ms = (_EWMA_DECAY * self.ewma_wait_ms
                             + (1 - _EWMA_DECAY) * max_wait_ms)
        self.flushes += 1

    @property
    def solo(self) -> bool:
        return self.ewma_merged <= _SOLO_EWMA_THRESHOLD

    def effective_wait(self, ceiling_ms: int) -> int:
        """0 for solo traffic; for concurrent traffic, scale toward the
        ceiling with the observed merge factor, CAPPED at the measured
        wait the key's batches actually needed (batches that fill by size
        before the deadline never needed the whole window), and floored
        at the observed inter-arrival gap (waiting less than one gap can
        never coalesce the next arrival)."""
        if ceiling_ms <= 0 or self.solo:
            return 0
        frac = min(1.0, self.ewma_merged - 1.0)
        wait = max(1, round(ceiling_ms * frac))
        if self.flushes >= 4:
            # enough history: the window need not exceed what the
            # measured per-entry waits show this key's merges cost
            wait = min(wait, max(1, round(self.ewma_wait_ms) + 1))
        if self.ewma_gap_ms is not None and self.ewma_gap_ms < ceiling_ms:
            wait = max(wait, min(ceiling_ms, int(self.ewma_gap_ms) + 1))
        return min(wait, ceiling_ms)

    def snapshot(self) -> dict:
        return {
            "ewma_merged": round(self.ewma_merged, 3),
            "ewma_wait_ms": round(self.ewma_wait_ms, 3),
            "ewma_gap_ms": (round(self.ewma_gap_ms, 3)
                            if self.ewma_gap_ms is not None else None),
            "flushes": self.flushes,
        }


class _Entry:
    __slots__ = ("payload", "enq_ms", "taken", "done", "result", "error",
                 "batch_size", "wall_ns", "retraced", "wait_ms", "launch",
                 "rank", "tune_key")

    def __init__(self, payload: Any, enq_ms: int, launch=None, rank: int = 0,
                 tune_key: Any = None):
        self.payload = payload
        self.enq_ms = enq_ms
        self.taken = False
        self.done = False
        self.result: Any = None
        self.error: BaseException | None = None
        self.batch_size = 1
        self.wall_ns = 0
        self.retraced = False
        self.wait_ms = 0
        # the entry's own launch closure + its k-bucket rank: a batch is
        # always launched by the closure of its LARGEST-rank member, so a
        # smaller-k joiner (cross-k coalescing) can ride a bigger-k launch
        # but can never shrink one
        self.launch = launch
        self.rank = rank
        # generation-free key family feeding the per-key wait auto-tuner
        self.tune_key = tune_key


class _Bucket:
    __slots__ = ("entries", "flush_now")

    def __init__(self) -> None:
        self.entries: list[_Entry] = []
        # set by a completing leader: the backlog that queued while the
        # device was busy flushes at once instead of waiting out a deadline
        self.flush_now = False


class DispatchOutcome:
    """What one query learns about the launch that served it."""

    __slots__ = ("value", "merged", "wall_ns", "retraced", "wait_ms")

    def __init__(self, value: Any, merged: int, wall_ns: int,
                 retraced: bool, wait_ms: int):
        self.value = value
        self.merged = merged          # live queries in the batch
        self.wall_ns = wall_ns        # fenced wall of the whole launch
        self.retraced = retraced
        self.wait_ms = wait_ms        # time this query spent queued

    @property
    def kernel_share_ns(self) -> int:
        """This query's share of the fenced kernel time (profiler entry)."""
        return self.wall_ns // max(self.merged, 1)


class KnnDispatchBatcher:
    """Per-node scheduler coalescing concurrent same-key kNN dispatches."""

    def __init__(self, *, max_batch_size: int | None = None,
                 max_wait_ms: int | None = None,
                 max_queue: int | None = None,
                 enabled: bool | None = None,
                 auto_tune: bool | None = None,
                 metrics=None):
        from opensearch_tpu.common.settings import Settings

        self.max_batch_size = (max_batch_size if max_batch_size is not None
                               else MAX_BATCH_SIZE_SETTING.default(Settings.EMPTY))
        self.max_wait_ms = (max_wait_ms if max_wait_ms is not None
                            else MAX_WAIT_MS_SETTING.default(Settings.EMPTY))
        self.enabled = (enabled if enabled is not None
                        else ENABLED_SETTING.default(Settings.EMPTY))
        self.auto_tune = (auto_tune if auto_tune is not None
                          else AUTO_TUNE_SETTING.default(Settings.EMPTY))
        limit = (max_queue if max_queue is not None
                 else MAX_QUEUE_SETTING.default(Settings.EMPTY))
        self.pressure = QueuePressure(limit, operation="knn batch dispatch")
        self.metrics = metrics       # optional telemetry MetricsRegistry
        self._cond = threading.Condition()
        self._buckets: dict[Any, _Bucket] = {}
        self._in_flight: dict[Any, int] = {}
        # per-key-family wait controllers (LRU-bounded, guarded by _cond)
        self._tuners: dict[Any, _KeyTuner] = {}
        # optimistic start (above the solo threshold): a fresh node assumes
        # concurrency until flushes prove otherwise, so the very first burst
        # coalesces instead of stampeding solo
        self._ewma = 2.0 * _SOLO_EWMA_THRESHOLD
        self.stats = {
            "dispatches": 0,        # device launches
            "merged_queries": 0,    # queries served by those launches
            "coalesced_batches": 0,  # launches with more than one query
            "max_batch": 0,
            "solo_fast_path": 0,    # adaptive immediate launches
            "rejections": 0,        # queue-bound sheds (429)
            # launches whose key spans a whole shard MESH (shards > 1):
            # one device program served every shard of the node at once,
            # so the batcher amortized across shards AND requests
            "cross_shard_launches": 0,
            "cross_shard_queries": 0,
            # ANN (IVF-PQ) vs exact-scan launch split, and queries served
            # from a LARGER k-bucket's pending batch (cross-k coalescing:
            # a k=5 arrival rides an in-formation k=8 batch of the same
            # family, truncation is free — extra rows never win the cut)
            "ann_dispatches": 0,
            "exact_dispatches": 0,
            "cross_k_served": 0,
        }

    # -- config ------------------------------------------------------------

    def configure(self, *, max_batch_size: int | None = None,
                  max_wait_ms: int | None = None,
                  max_queue: int | None = None,
                  enabled: bool | None = None,
                  auto_tune: bool | None = None) -> None:
        # config fields are plain atomic assignments read racily by design:
        # a dispatch that reads the old value completes under the old
        # policy, which is exactly the dynamic-settings contract
        if max_batch_size is not None:
            self.max_batch_size = max(1, int(max_batch_size))
        if max_wait_ms is not None:
            self.max_wait_ms = int(max_wait_ms)
        if enabled is not None:
            self.enabled = bool(enabled)
        if auto_tune is not None:
            self.auto_tune = bool(auto_tune)
        if max_queue is not None:
            self.pressure.set_limit(max_queue)
        with self._cond:
            self._cond.notify_all()

    def apply_settings(self, flat: dict) -> None:
        """Pick this batcher's keys out of a flat effective-settings map
        (the cluster-settings update consumer)."""
        from opensearch_tpu.common.settings import Settings

        s = Settings.from_flat({
            st.key: flat[st.key] for st in BATCH_SETTINGS if st.key in flat
        })
        self.configure(
            max_wait_ms=MAX_WAIT_MS_SETTING.get(s),
            max_batch_size=MAX_BATCH_SIZE_SETTING.get(s),
            max_queue=MAX_QUEUE_SETTING.get(s),
            enabled=ENABLED_SETTING.get(s),
            auto_tune=AUTO_TUNE_SETTING.get(s),
        )

    # tuner entries surfaced in stats (the table itself is bounded at
    # _MAX_TUNERS; the stats payload shows the busiest few)
    _STATS_TUNER_ROWS = 16

    def snapshot_stats(self) -> dict:
        with self._cond:
            out = dict(self.stats)
            out["mean_merged_batch"] = (
                out["merged_queries"] / out["dispatches"]
                if out["dispatches"] else 0.0
            )
            out["ewma_batch"] = round(self._ewma, 3)
            busiest = sorted(self._tuners.items(),
                             key=lambda kv: -kv[1].flushes)
            out["auto_tune"] = {
                "enabled": self.auto_tune,
                "tuned_keys": len(self._tuners),
                "keys": {
                    str(tk): {
                        **tuner.snapshot(),
                        "effective_wait_ms": tuner.effective_wait(
                            self.max_wait_ms),
                    }
                    for tk, tuner in busiest[: self._STATS_TUNER_ROWS]
                },
            }
        out["queue"] = self.pressure.stats()
        out["rejections"] = out["queue"]["rejections"]
        out["enabled"] = self.enabled
        out["max_batch_size"] = self.max_batch_size
        out["max_wait_ms"] = self.max_wait_ms
        # live ANN serving knobs + index-build accounting ride the same
        # stats section (one `knn_batch` surface for the whole kNN
        # dispatch tier, single-node and cluster alike)
        from opensearch_tpu.search import ann as ann_mod

        out["ann"] = ann_mod.default_config.snapshot()
        return out

    def reset(self) -> None:
        """Test hook: forget adaptive state and counters (never pending
        entries — callers must be idle, so no lock discipline applies)."""
        for k in self.stats:
            self.stats[k] = 0
        self._ewma = 2.0 * _SOLO_EWMA_THRESHOLD
        self._tuners.clear()
        self.pressure.rejections = 0
        self.pressure.total = 0

    # -- dispatch ----------------------------------------------------------

    def dispatch(self, key: Any, payload: Any,
                 launch: Callable[[Sequence[Any]],
                                  tuple[list, bool]],
                 shards: int = 1, *, kind: str = "exact",
                 rank: int = 0,
                 alt_keys: Sequence[Any] = (),
                 family: str | None = None,
                 tune_key: Any = None) -> DispatchOutcome:
        """Run `payload` through the batch identified by `key`.

        `launch(payloads)` performs ONE device launch for the whole batch
        (padding the width as it sees fit) and returns
        (per-payload results, retraced flag). Every payload sharing a key
        MUST be servable by any member's launch closure — the key is the
        caller's promise that the kernel and its device-resident arguments
        are identical. key=None means "not mergeable" (e.g. a filtered
        query whose valid mask is request-private): the launch runs solo,
        still counted in the stats.

        `shards` declares how many shards the launch covers (the
        shard-mesh path passes its mesh width): cross-shard launches are
        tracked separately so the stats show when one launch amortized
        across the whole node instead of one shard.

        `kind` ("exact" | "ann") splits the dispatch counters so the
        stats/Prometheus surface shows which scan family launches serve.

        `alt_keys` (cross-k coalescing) are LARGER-k-bucket variants of
        `key`, nearest first, that this request may ride: if one already
        has a batch forming, the entry joins it instead of opening its own
        bucket — the bigger-k result is a superset, the caller's top-k cut
        truncates for free. `rank` orders the k-buckets: a batch launches
        with its largest-rank member's closure, so joiners can never
        shrink the launch the natives asked for.

        `family` names the kernel family for the device-residency ledger's
        retrace/compile accounting: a launch whose retraced flag fires
        counts one jit-cache entry (plus its first-launch wall) there.

        `tune_key` names the entry's GENERATION-FREE key family for the
        per-key wait auto-tuner (defaults to `key` itself): the controller
        derives this arrival's effective wait window from the family's
        measured merge factor / queue waits / arrival gaps instead of the
        static `max_wait_ms` ceiling. The active priority lane
        (search/lanes.py) widens the window for background entries.
        """
        if key is None or not self.enabled or self.max_batch_size <= 1:
            return self._solo(payload, launch, shards, kind, family)
        from opensearch_tpu.search import lanes as lanes_mod

        # the lanes kill switch governs the batcher's wait-widening too:
        # control-plane-off must be exactly the pre-lane behavior (and the
        # bench's OFF baseline must not keep one lever engaged)
        background = (lanes_mod.default_config.enabled
                      and lanes_mod.active_lane() == lanes_mod.BACKGROUND)
        if tune_key is None:
            tune_key = key
        with self._cond:
            self.pressure.acquire()
            entry = _Entry(payload, timeutil.monotonic_millis(),
                           launch=launch, rank=rank, tune_key=tune_key)
            tuner = None
            if self.auto_tune:
                tuner = self._tuner_locked(tune_key)
                tuner.note_arrival(entry.enq_ms)
                eff_wait = tuner.effective_wait(self.max_wait_ms)
            else:
                eff_wait = self.max_wait_ms
            if background:
                # background traffic accepts a longer window (it earns
                # bigger merges); never BELOW the configured ceiling so a
                # tuned-down interactive window doesn't shrink it
                eff_wait = max(self.max_wait_ms, eff_wait) \
                    * _BACKGROUND_WAIT_FACTOR
            deadline = entry.enq_ms + max(eff_wait, 0)
            for alt in alt_keys:
                alt_bucket = self._buckets.get(alt)
                if (alt_bucket is not None and alt_bucket.entries
                        and len(alt_bucket.entries) < self.max_batch_size):
                    # ride the bigger-k batch already forming; never CREATE
                    # a bigger-k bucket just for a smaller-k request
                    key = alt
                    self.stats["cross_k_served"] += 1
                    break
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = self._buckets[key] = _Bucket()
            bucket.entries.append(entry)
            # the per-key controller's solo verdict wins when auto-tuning;
            # the global EWMA stays the fallback signal
            solo_now = (tuner.solo if tuner is not None
                        else self._ewma <= _SOLO_EWMA_THRESHOLD)
            if len(bucket.entries) >= self.max_batch_size:
                batch, reason = self._take_locked(key), "size"
            elif self.max_wait_ms <= 0 or (
                self._in_flight.get(key, 0) == 0 and solo_now
            ):
                if len(bucket.entries) == 1:
                    self.stats["solo_fast_path"] += 1
                batch, reason = self._take_locked(key), "solo"
            else:
                batch, reason = None, ""
        while True:
            if batch is not None:
                out = self._run_batch(key, batch, own=entry,
                                      shards=shards, kind=kind,
                                      family=family, reason=reason)
                if out is not None:
                    return out
                # we led a batch that did not include our own entry (the
                # size bound shrank under us): keep waiting for ours
                batch = None
                continue
            led = self._await_or_lead(key, entry, deadline)
            if led is None:
                # another leader served us
                if entry.error is not None:
                    raise entry.error
                return DispatchOutcome(
                    entry.result, entry.batch_size, entry.wall_ns,
                    entry.retraced, entry.wait_ms,
                )
            batch, reason = led

    # -- internals ---------------------------------------------------------

    def _solo(self, payload: Any, launch, shards: int = 1,
              kind: str = "exact",
              family: str | None = None) -> DispatchOutcome:
        t0 = time.perf_counter_ns()
        results, retraced = launch([payload])
        wall = time.perf_counter_ns() - t0
        self._record_launch(1, wall, (0,), shards, kind)
        self._after_launch(kind, family, retraced, wall, merged=1,
                           reason="unbatched")
        return DispatchOutcome(results[0], 1, wall, retraced, 0)

    def _tuner_locked(self, tune_key: Any) -> _KeyTuner:
        """The key family's controller (caller holds the lock); LRU touch
        + bound so generations of abandoned families age out."""
        tuner = self._tuners.pop(tune_key, None)
        if tuner is None:
            tuner = _KeyTuner()
        self._tuners[tune_key] = tuner
        while len(self._tuners) > _MAX_TUNERS:
            self._tuners.pop(next(iter(self._tuners)))
        return tuner

    def _after_launch(self, kind: str, family: str | None, retraced: bool,
                      wall_ns: int, merged: int, reason: str) -> None:
        """Post-launch observability: the flush reason rides the leader's
        span as an event, and a retraced launch counts one jit-cache entry
        (first-launch wall = compile + run) in the residency ledger's
        per-kernel-family compile table. Only NOTEWORTHY flushes emit an
        event — a coalesced batch or a wait-policy decision (size/
        deadline/backlog); the steady solo fast path stays event-free so
        the per-span export payload (the ≤5% otel-overhead gate) doesn't
        grow with every launch."""
        from opensearch_tpu.telemetry.device_ledger import default_ledger

        if merged > 1 or reason in ("size", "deadline", "backlog"):
            from opensearch_tpu.telemetry.tracing import add_span_event

            add_span_event("knn.batch.flush", {
                "reason": reason, "merged": merged, "kind": kind,
            })
        # launch closures that account their own compiles (the mesh path)
        # pass no family — recording here too would double-count the entry
        if retraced and family is not None:
            default_ledger.record_compile(family, wall_ns)

    def _take_locked(self, key: Any) -> list[_Entry]:
        """Detach the key's pending entries (<= max_batch_size of them) as
        one batch; caller holds the lock and becomes the leader."""
        bucket = self._buckets.get(key)
        assert bucket is not None and bucket.entries
        batch = bucket.entries[: self.max_batch_size]
        rest = bucket.entries[self.max_batch_size:]
        if rest:
            bucket.entries = rest
        else:
            del self._buckets[key]
        now = timeutil.monotonic_millis()
        for e in batch:
            e.taken = True
            e.wait_ms = max(0, now - e.enq_ms)
        self.pressure.release(len(batch))
        self._in_flight[key] = self._in_flight.get(key, 0) + 1
        return batch

    def _await_or_lead(self, key: Any, entry: _Entry,
                       deadline: int) -> tuple[list[_Entry], str] | None:
        """Wait until the entry is served, or its bucket qualifies for a
        flush it can lead. Returns (batch, flush reason) to lead, or None
        if done."""
        with self._cond:
            while True:
                if entry.done:
                    return None
                if entry.taken:
                    # a leader is running our batch; the 100ms timeout is a
                    # liveness backstop, completion notifies immediately
                    self._cond.wait(0.1)
                    continue
                bucket = self._buckets.get(key)
                now = timeutil.monotonic_millis()
                if bucket is not None and (
                        len(bucket.entries) >= self.max_batch_size
                        or bucket.flush_now):
                    reason = ("size"
                              if len(bucket.entries) >= self.max_batch_size
                              else "backlog")
                    return self._take_locked(key), reason
                if now >= deadline:
                    return self._take_locked(key), "deadline"
                remaining = max((deadline - now) / 1000.0, 0.0)
                signaled = self._cond.wait(remaining)
                if not signaled and timeutil.monotonic_millis() <= now:
                    # the injected clock is virtual/frozen: real time
                    # elapsed without virtual progress, so the deadline can
                    # never arrive by waiting — flush now (keeps
                    # deterministic-sim runs from hanging on wall time)
                    deadline = now

    def _run_batch(self, key: Any, batch: list[_Entry],
                   own: _Entry, shards: int = 1,
                   kind: str = "exact", family: str | None = None,
                   reason: str = "") -> DispatchOutcome | None:
        """Launch one batch; returns the outcome for `own`, or None when
        `own` was not part of this batch (its caller keeps waiting)."""
        # cross-k coalescing: the batch launches with its LARGEST-rank
        # member's closure — every smaller-k joiner's result is a prefix
        # truncation of that launch's rows
        launch = max(batch, key=lambda e: e.rank).launch
        t0 = time.perf_counter_ns()
        try:
            results, retraced = launch([e.payload for e in batch])
        except BaseException as err:
            with self._cond:
                for e in batch:
                    e.error = err
                    e.done = True
                self._finish_locked(key, batch)
            raise
        wall = time.perf_counter_ns() - t0
        with self._cond:
            for e, r in zip(batch, results):
                e.result = r
                e.batch_size = len(batch)
                e.wall_ns = wall
                e.retraced = retraced
                e.done = True
            self._finish_locked(key, batch)
        self._record_launch(len(batch), wall,
                            tuple(e.wait_ms for e in batch),
                            shards, kind)
        self._after_launch(kind, family, retraced, wall,
                           merged=len(batch), reason=reason or "lead")
        if not any(e is own for e in batch):
            return None
        return DispatchOutcome(own.result, len(batch), wall, retraced,
                               own.wait_ms)

    def _finish_locked(self, key: Any, batch: list[_Entry]) -> None:
        merged = len(batch)
        n = self._in_flight.get(key, 0) - 1
        if n > 0:
            self._in_flight[key] = n
        else:
            self._in_flight.pop(key, None)
        self._ewma = _EWMA_DECAY * self._ewma + (1 - _EWMA_DECAY) * merged
        if self.auto_tune:
            # every key family represented in the batch (cross-k joiners
            # carry their own tune_key) learns this flush's merge factor
            # and its members' MEASURED waits
            by_family: dict[Any, int] = {}
            for e in batch:
                if e.tune_key is not None:
                    by_family[e.tune_key] = max(
                        by_family.get(e.tune_key, 0), e.wait_ms)
            for tk, max_wait in by_family.items():
                self._tuner_locked(tk).note_flush(merged, max_wait)
        bucket = self._buckets.get(key)
        if bucket is not None and bucket.entries:
            # continuous batching: the backlog that formed while this
            # launch ran flushes immediately, led by one of its waiters
            bucket.flush_now = True
        self._cond.notify_all()

    def _record_launch(self, merged: int, wall_ns: int,
                       wait_ms_per_entry: Sequence[int], shards: int = 1,
                       kind: str = "exact") -> None:
        with self._cond:
            self.stats["dispatches"] += 1
            self.stats["merged_queries"] += merged
            if merged > 1:
                self.stats["coalesced_batches"] += 1
            self.stats["max_batch"] = max(self.stats["max_batch"], merged)
            if shards > 1:
                self.stats["cross_shard_launches"] += 1
                self.stats["cross_shard_queries"] += merged
            if kind == "ann":
                self.stats["ann_dispatches"] += 1
            else:
                self.stats["exact_dispatches"] += 1
        # record into the EXECUTING node's registry when a request scope is
        # active (multi-node sims share this process-wide batcher; the
        # exemplar trace_id must resolve in the recording node's ring),
        # else the attached sink
        from opensearch_tpu.telemetry.tracing import active_metrics

        metrics = active_metrics() or self.metrics
        if metrics is not None:
            metrics.histogram("knn.batch.size").record(merged)
            # one observation PER ENTRY with its MEASURED queue wait (the
            # auto-tuner and its operators need the real distribution, not
            # one per-batch point — and never the configured ceiling)
            for w in wait_ms_per_entry:
                metrics.histogram("knn.batch.queue_wait_ms").record(w)
            metrics.histogram("knn.batch.shards").record(shards)
            metrics.counter("knn.batch.dispatches").add(1)
            if kind == "ann":
                metrics.counter("knn.dispatch.ann").add(1)
            else:
                metrics.counter("knn.dispatch.exact").add(1)


# process-wide default: the executor's dispatch sites are module-level code
# with no node handle (same pattern as executor.knn_path_stats); a TpuNode
# adopts it at construction (stats + settings + metrics wiring). One
# process == one device, so per-process batching is the semantically right
# scope even when several sim nodes share the interpreter.
default_batcher = KnnDispatchBatcher()


def dispatch(key: Any, payload: Any, launch, shards: int = 1, *,
             kind: str = "exact", rank: int = 0,
             alt_keys: Sequence[Any] = (),
             family: str | None = None,
             tune_key: Any = None) -> DispatchOutcome:
    return default_batcher.dispatch(key, payload, launch, shards=shards,
                                    kind=kind, rank=rank, alt_keys=alt_keys,
                                    family=family, tune_key=tune_key)
