"""Per-shard query execution: query node tree -> device score/mask ops.

The analog of the reference's per-shard query phase
(search/query/QueryPhase.java:96 + ContextIndexSearcher.java:242 and the
QueryBuilder.toQuery compile step): each query node is executed against each
segment's device arrays, producing a dense (scores[n_pad] f32, mask[n_pad]
bool) pair; composition (bool logic) is elementwise on the VPU instead of
Lucene's doc-at-a-time conjunction/disjunction iterators.

Scoring follows Lucene semantics: BM25 with shard-level stats (idf over
summed per-segment doc freqs, avgdl over all segments — matching
IndexSearcher collection statistics), constant 1.0*boost for filter-ish
queries in scoring position, 0.0 scores for filter-only bools.

Sort-by-field runs host-side on the exact int64/float64 host columns (device
computes the match mask; numpy does the argsort) — exact semantics first,
device sort keys are a later optimization. Score sort runs fully on device
ending in lax.top_k.
"""

from __future__ import annotations

import logging
import re
import threading
import time
from dataclasses import dataclass, field as dc_field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from opensearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
)
from opensearch_tpu.index.device import DeviceSegment
from opensearch_tpu.index.mapper import (
    FLOAT_TYPES,
    INT_TYPES,
    RANGE_TYPES,
    MapperService,
    parse_date_millis,
)
from opensearch_tpu.index.engine import SearcherSnapshot
from opensearch_tpu.index.segment import (
    HostSegment,
    i64_query_words,
    pad_window,
)
from opensearch_tpu.ops import bm25, filters, knn
from opensearch_tpu.search import profile
from opensearch_tpu.search import query_dsl as q
from opensearch_tpu.telemetry import roofline

logger = logging.getLogger(__name__)

I64_MIN = -(2**63)
I64_MAX = 2**63 - 1

# exact-kNN scan strategy: segments at or above STREAMING_MIN_DOCS live docs
# score through the chunked streaming program (ops/fused.knn_topk_streaming,
# HBM traffic = one [B, chunk] tile per step); smaller segments materialize
# the [1, n] row eagerly (cheaper than a compiled scan at that size).
# Tests lower the threshold to pin both paths against each other.
STREAMING_MIN_DOCS = 16_384
STREAMING_CHUNK = 32_768

# observability: which scan strategy served _exec_KnnQuery selections.
# Searches run on a parallel pool (rest/http.py), so increments go through
# _count_knn_path — a bare `dict[k] += 1` is read-modify-write and drops
# counts under concurrency.
knn_path_stats = {"streaming": 0, "materializing": 0, "ann": 0, "fused": 0}
_knn_path_stats_lock = threading.Lock()


def _count_knn_path(kind: str) -> None:
    with _knn_path_stats_lock:
        knn_path_stats[kind] += 1


def _record_ann_metrics(nprobe: int) -> None:
    """`knn.batch.nprobe` histogram for an ANN dispatch — recorded into the
    EXECUTING node's registry when a request scope is active (the batcher's
    attribution rule), else the attached sink."""
    from opensearch_tpu.search import batcher as batcher_mod
    from opensearch_tpu.telemetry.tracing import active_metrics

    metrics = active_metrics() or batcher_mod.default_batcher.metrics
    if metrics is not None:
        metrics.histogram("knn.batch.nprobe").record(nprobe)


def _pad_query_batch(rows: list) -> np.ndarray:
    """Stack per-request query vectors into a [B_pad, d] batch, B padded to
    the next power of two (zero rows, results sliced off by the caller) so
    merged batch widths share compiled programs instead of retracing per
    distinct concurrency level. The padded batch is a per-launch
    host->device upload: the residency ledger counts it as transient
    (allocated and freed in one step)."""
    from opensearch_tpu.telemetry.device_ledger import (
        KIND_QUERY_BATCH,
        default_ledger,
    )

    b = len(rows)
    b_pad = 1 << (b - 1).bit_length()
    out = np.zeros((b_pad, len(rows[0])), np.float32)
    for i, row in enumerate(rows):
        out[i] = row
    default_ledger.record_transient(KIND_QUERY_BATCH, out.nbytes)
    return out


def _touch_targets(dev, field: str, ann=None) -> list:
    """The ledger allocations a kNN launch over this segment READS — the
    vector column, the live bitmap, and (ANN path) the IVF-PQ slab: the
    launch closures record a heat touch against them with the launch's
    modeled HBM bytes (telemetry/device_ledger.touch; tpulint TPU017)."""
    allocs = getattr(dev, "allocations", None) or {}
    out = [allocs.get(field), allocs.get("_live")]
    if ann is not None:
        out.append(getattr(ann, "allocation", None))
    return [a for a in out if a is not None]


# --------------------------------------------------------------------------
# Shard-level statistics (Lucene collection statistics analog)
# --------------------------------------------------------------------------


class ShardContext:
    def __init__(self, snapshot: SearcherSnapshot, mapper_service: MapperService):
        self.snapshot = snapshot
        self.mapper_service = mapper_service
        # per-query cache: knn nodes select k docs PER SHARD (k-NN plugin
        # semantics), so the top-k cut must span all segments of the shard
        self._knn_cache: dict[int, list] = {}
        # query_string trees are parsed once per shard, not per segment
        self._qs_cache: dict[int, Any] = {}

    def rewritten_query_string(self, node) -> Any:
        """Parse a query_string/simple_query_string node's text once per
        shard (the two-phase-rewrite analog: QueryStringQueryBuilder rewrites
        to a concrete query before per-segment execution)."""
        cached = self._qs_cache.get(id(node))
        if cached is not None:
            return cached
        from opensearch_tpu.search import query_dsl as qd
        from opensearch_tpu.search.query_string import (
            parse_query_string,
            parse_simple_query_string,
        )

        fields = node.fields or self.default_text_fields()
        if isinstance(node, qd.SimpleQueryStringQuery):
            tree = parse_simple_query_string(node.query, fields, node.default_operator)
        else:
            tree = parse_query_string(node.query, fields, node.default_operator)
        self._qs_cache[id(node)] = tree
        return tree

    def default_text_fields(self) -> list[str]:
        fields = [
            name for name, m in self.mapper_service.mappers.items()
            if m.type in ("text", "keyword")
        ]
        for host, _dev in self.snapshot.segments:
            for name in host.text_fields:
                if name not in fields:
                    fields.append(name)
        return fields or ["_all_absent_"]

    def shard_knn_selection(self, node) -> list:
        """Per-segment (sel_mask bool[n_pad], scores f32[n_pad]) numpy pairs
        for a KnnQuery, with the top-k cut applied across the whole shard.

        Large exact segments score through ops/fused.knn_topk_streaming
        (the corpus-chunked scan that never materializes [B, n] — VERDICT
        r4 weak #2 wired into the serving path): only the [1, k] winners
        come back to host, as a sparse -inf-based score array (the same
        representation the ANN path uses). Small segments keep the eager
        materializing scan — a [1, n] row below the streaming threshold
        costs less than a compiled scan program."""
        cached = self._knn_cache.get(id(node))
        if cached is not None:
            return cached
        from opensearch_tpu.ops import knn as knn_ops

        per_seg_scores: list[np.ndarray | None] = []
        candidates: list[tuple[float, int, int]] = []
        for seg_idx, (host, dev) in enumerate(self.snapshot.segments):
            vf = dev.vector_fields.get(node.field)
            if vf is None:
                per_seg_scores.append(None)
                continue
            valid = vf.present & dev.live
            if node.filter is not None:
                ex = SegmentExecutor(self, host, dev)
                valid = valid & ex.execute(node.filter).mask
            # host numpy: the query vector is this path's whole per-request
            # host->device transfer (the profiler counts host-typed args)
            qv = np.asarray([node.vector], np.float32)
            prof = profile.active()
            if vf.ann is not None and node.filter is None:
                # ANN path: IVF-PQ ADC + exact rescore gives candidate-only
                # scores; non-candidates stay -inf (they can never win).
                # Dispatch rides search/batcher.py with a REAL batch key —
                # (kernel "ivfpq", device column, INDEX-BUILD GENERATION,
                # reader generation, k bucket, nprobe bucket, similarity,
                # live precision pair) — so concurrent ANN queries against
                # the same built index coalesce into ONE search_index
                # launch, and a rebuild (fresh build generation) can never
                # merge into an old batch.
                from opensearch_tpu.ops import ivfpq
                from opensearch_tpu.search import ann as ann_mod
                from opensearch_tpu.search import batcher as batcher_mod

                cfg = ann_mod.default_config
                precision = cfg.adc_precision
                mult = cfg.rescore_multiplier
                # the RESOLVED ADC kernel ("pallas" fused scan vs "xla"
                # monolithic lowering) rides the batch key: a policy flip
                # mid-stream starts new batches, it never re-routes one —
                # and a rebuild (fresh build generation) can never merge
                # old-generation queries into the new kernel variant
                kernel = ann_mod.resolve_kernel(cfg.kernel)
                # bucket k AND nprobe to powers of two: both are static jit
                # args, so raw values would compile a fresh program per
                # distinct request shape (the query-shape cache concern,
                # SURVEY.md §7 hard part #3). Extra candidates/probes are
                # harmless — the shard-level cut below still takes exactly
                # node.k, and more probes only add recall.
                nprobe_req = int(
                    (node.method_parameters or {}).get(
                        "nprobe", vf.nprobe_default
                    )
                )
                nprobe = ann_mod.bucket_nprobe(
                    nprobe_req, vf.ann.params.nlist)
                k_req = max(1, min(node.k, host.n_docs))
                k_bucket = 1 << (k_req - 1).bit_length()
                sim = knn_ops.canonical_similarity(vf.similarity)
                gen = self.snapshot.generation

                def ann_key(kb: int):
                    return ("ivfpq", id(vf), vf.ann.build_generation, gen,
                            kb, nprobe, sim, precision, mult, kernel)

                rerank = ivfpq.default_rerank(k_bucket, mult)
                rescore = ivfpq.rescore_pool(vf.ann, k_bucket, nprobe,
                                             rerank)
                # roofline family per kernel variant: the fused Pallas
                # scan has its OWN cost model (no per-slot LUT gather
                # traffic, no [B, nprobe, L_pad] intermediate), so the
                # report can show exactly what the swap bought
                family = ("ivfpq_adc_pallas" if kernel == "pallas"
                          else "ivfpq_search")

                touch_allocs = _touch_targets(dev, node.field, ann=vf.ann)

                def launch_ann(rows):
                    q_batch = _pad_query_batch(rows)
                    t0 = time.perf_counter_ns()
                    with profile.profiling(None):
                        b_vals, b_ids = ivfpq.search_index(
                            vf.ann, vf.vectors, vf.norms_sq, valid,
                            q_batch, k=k_bucket, nprobe=nprobe,
                            similarity=vf.similarity,
                            adc_precision=precision,
                            rescore_multiplier=mult,
                            kernel=kernel,
                        )
                    # host materialization is the fence for this launch
                    b_vals = np.asarray(b_vals)
                    b_ids = np.asarray(b_ids)
                    # roofline accounting: one fenced launch against the
                    # variant's cost model, keyed per ADC precision so the
                    # report can compare the lowerings (ANNS-AMP)
                    launch_params = dict(
                        b=int(q_batch.shape[0]),
                        nlist=vf.ann.params.nlist, d=vf.ann.params.d,
                        m=vf.ann.params.m, ks=vf.ann.params.ks,
                        nprobe=nprobe, l_pad=vf.ann.l_pad,
                        rescore=rescore, adc_precision=precision,
                    )
                    roofline.record_launch(
                        f"{family}[{precision}]",
                        time.perf_counter_ns() - t0,
                        **launch_params,
                    )
                    # heat touch against the structures this launch READ
                    # (IVF-PQ slab + rescore column + live bitmap), bytes
                    # from the same cost model the roofline fold used
                    from opensearch_tpu.telemetry.device_ledger import (
                        default_ledger,
                    )

                    default_ledger.touch(
                        touch_allocs, family=f"{family}[{precision}]",
                        params=launch_params)
                    retraced = profile.signature_retraced(
                        "ivfpq_search", (vf.vectors, q_batch),
                        (k_bucket, nprobe, precision, mult, kernel))
                    return (
                        [(b_vals[i], b_ids[i]) for i in range(len(rows))],
                        retraced,
                    )

                # cross-k coalescing: this request may ride an already-
                # forming batch of the next-larger k buckets (its rows
                # truncate for free); it never creates one
                out = batcher_mod.dispatch(
                    ann_key(k_bucket), qv[0], launch_ann, shards=1,
                    kind="ann", rank=k_bucket,
                    alt_keys=(ann_key(k_bucket * 2), ann_key(k_bucket * 4)),
                    family=family,
                    # generation-free family for the wait auto-tuner: a
                    # rebuild/refresh must not reset the learned window
                    tune_key=("ivfpq", id(self.mapper_service),
                              node.field, k_bucket),
                )
                a_vals, a_ids = out.value
                # the batch leader may have run a LARGER k bucket: the
                # scatter below accepts any row count, the shard cut
                # truncates to node.k
                if prof is not None:
                    prof.record_kernel(
                        family, out.kernel_share_ns,
                        int(qv.nbytes), out.retraced,
                        annotations={
                            "adc_precision": precision,
                            "rescore_candidates": rescore,
                            "nprobe": nprobe,
                            "kernel": kernel,
                        },
                    )
                _record_ann_metrics(nprobe)
                _count_knn_path("ann")
                scores = np.full(dev.n_pad, -np.inf, np.float32)
                hit = a_ids >= 0
                scores[a_ids[hit]] = a_vals[hit]
                # the launch already returned the top candidates sorted —
                # skip the generic argpartition below and feed them to the
                # shard cut directly (host work on the serving path is
                # GIL-serial; every avoided O(n) pass widens the batch win)
                per_seg_scores.append(scores)
                for v, d in zip(a_vals[hit][: node.k], a_ids[hit][: node.k]):
                    if np.isfinite(v):
                        candidates.append((float(v), seg_idx, int(d)))
                continue
            else:
                n_pad = dev.n_pad
                k_req = max(1, min(int(node.k), host.n_docs))
                # k is a static jit arg: bucket to the next power of two so
                # distinct request ks share compiled programs (same concern
                # as the ANN branch above)
                k_bucket = 1 << (k_req - 1).bit_length()
                chunk = min(STREAMING_CHUNK, n_pad)
                sim = knn_ops.canonical_similarity(vf.similarity)
                # cross-request micro-batching (search/batcher.py):
                # concurrent filterless queries over this SAME segment
                # column + reader generation coalesce into one padded
                # batch launch. Filtered queries carry a request-private
                # valid mask, so they never merge (key=None -> solo).
                # The key's generation term is the snapshot-safety
                # invariant: a refresh mid-flight is a different key.
                from opensearch_tpu.ops import pallas_knn as pallas_knn_ops
                from opensearch_tpu.search import batcher as batcher_mod
                from opensearch_tpu.search.ann import (
                    default_config as ann_config,
                    resolve_kernel,
                )

                # EXACT-path kernel policy (search.knn.kernel): when it
                # resolves to "pallas", BOTH exact strategies (streaming
                # and materializing) serve through the fused blockwise
                # kernel instead — the RESOLVED kernel and scan precision
                # ride the batch key, so a live flip starts new batches
                # and never re-ranks an in-flight one
                exact_kernel = resolve_kernel(ann_config.exact_kernel)
                score_precision = ann_config.score_precision
                if (exact_kernel == "pallas"
                        and k_bucket <= pallas_knn_ops.FUSED_MAX_K):

                    def fused_key(kb: int):
                        return ("knn_fused", id(vf),
                                self.snapshot.generation, kb, sim,
                                score_precision, exact_kernel)

                    key = (
                        fused_key(k_bucket)
                        if node.filter is None else None
                    )
                    alt_keys = tuple(
                        fused_key(kb)
                        for kb in (k_bucket * 2, k_bucket * 4)
                        if kb <= pallas_knn_ops.FUSED_MAX_K
                    ) if key is not None else ()

                    touch_allocs = _touch_targets(dev, node.field)

                    def launch_fused(rows):
                        q_batch = _pad_query_batch(rows)
                        t0 = time.perf_counter_ns()
                        with profile.profiling(None):
                            b_vals, b_ids = pallas_knn_ops.knn_fused_auto(
                                vf.vectors, vf.norms_sq, valid, q_batch,
                                k=k_bucket, similarity=sim,
                                score_precision=score_precision,
                                impl=exact_kernel,
                            )
                        # host materialization is the fence for this launch
                        b_vals = np.asarray(b_vals)
                        b_ids = np.asarray(b_ids)
                        launch_params = dict(
                            b=int(q_batch.shape[0]),
                            n=int(vf.vectors.shape[0]),
                            d=int(vf.vectors.shape[1]), k=k_bucket,
                            r=pallas_knn_ops.fused_pool_width(
                                k_bucket, score_precision),
                            precision=score_precision,
                        )
                        roofline.record_launch(
                            f"knn_fused_pallas[{score_precision}]",
                            time.perf_counter_ns() - t0,
                            **launch_params,
                        )
                        from opensearch_tpu.telemetry.device_ledger import (
                            default_ledger,
                        )

                        default_ledger.touch(
                            touch_allocs, family="knn_fused_pallas",
                            params=launch_params)
                        retraced = profile.signature_retraced(
                            "knn_fused_pallas", (vf.vectors, q_batch),
                            (k_bucket, sim, score_precision, exact_kernel))
                        return (
                            [(b_vals[i], b_ids[i])
                             for i in range(len(rows))],
                            retraced,
                        )

                    out = batcher_mod.dispatch(
                        key, qv[0], launch_fused,
                        shards=1, rank=k_bucket,
                        alt_keys=alt_keys,
                        family="knn_fused_pallas",
                        tune_key=("knn_fused_pallas",
                                  id(self.mapper_service), node.field,
                                  k_bucket))
                    vals, ids = out.value
                    if prof is not None:
                        prof.record_kernel(
                            "knn_fused_pallas", out.kernel_share_ns,
                            int(qv.nbytes), out.retraced,
                            annotations={
                                "score_precision": score_precision,
                                "kernel": exact_kernel,
                            },
                        )
                    scores = np.full(n_pad, -np.inf, np.float32)
                    hit = ids >= 0
                    scores[ids[hit]] = vals[hit]
                    _count_knn_path("fused")
                elif (host.n_docs >= STREAMING_MIN_DOCS
                        and n_pad % chunk == 0 and k_bucket <= chunk):
                    from opensearch_tpu.ops import fused

                    jfn = fused.cached_knn_streaming(k_bucket, sim, chunk)

                    def stream_key(kb: int):
                        return ("knn_topk_streaming", id(vf),
                                self.snapshot.generation, kb, sim, chunk)

                    key = (
                        stream_key(k_bucket)
                        if node.filter is None else None
                    )
                    # cross-k coalescing: ride an already-forming batch of
                    # the next-larger k buckets (result rows truncate for
                    # free; kb stays within the streaming chunk bound)
                    alt_keys = tuple(
                        stream_key(kb)
                        for kb in (k_bucket * 2, k_bucket * 4)
                        if kb <= chunk
                    ) if key is not None else ()

                    touch_allocs = _touch_targets(dev, node.field)

                    def launch_streaming(rows):
                        q_batch = _pad_query_batch(rows)
                        t0 = time.perf_counter_ns()
                        with profile.profiling(None):
                            b_vals, b_ids = jfn(
                                vf.vectors, vf.norms_sq, valid, q_batch
                            )
                        # host materialization is the fence for this launch
                        b_vals = np.asarray(b_vals)
                        b_ids = np.asarray(b_ids)
                        launch_params = dict(
                            b=int(q_batch.shape[0]),
                            n=int(vf.vectors.shape[0]),
                            d=int(vf.vectors.shape[1]), k=k_bucket,
                        )
                        roofline.record_launch(
                            "knn_topk_streaming",
                            time.perf_counter_ns() - t0,
                            **launch_params,
                        )
                        # heat touch: the column + live bitmap this scan
                        # read, bytes from the same cost model
                        from opensearch_tpu.telemetry.device_ledger import (
                            default_ledger,
                        )

                        default_ledger.touch(
                            touch_allocs, family="knn_topk_streaming",
                            params=launch_params)
                        retraced = profile.signature_retraced(
                            "knn_topk_streaming", (vf.vectors, q_batch),
                            (k_bucket, chunk))
                        return (
                            [(b_vals[i], b_ids[i]) for i in range(len(rows))],
                            retraced,
                        )

                    # shards=1: this is the per-shard fallback path (the
                    # shard-mesh launch in service.py passes its mesh
                    # width); the batcher's cross-shard stats stay honest
                    out = batcher_mod.dispatch(
                        key, qv[0], launch_streaming,
                        shards=1, rank=k_bucket,
                        alt_keys=alt_keys,
                        family="knn_topk_streaming",
                        tune_key=("knn_topk_streaming",
                                  id(self.mapper_service), node.field,
                                  k_bucket))
                    vals, ids = out.value
                    if prof is not None:
                        # a batched operator owns its SHARE of the fenced
                        # kernel wall (merged launches split evenly)
                        prof.record_kernel(
                            "knn_topk_streaming", out.kernel_share_ns,
                            int(qv.nbytes), out.retraced,
                        )
                    scores = np.full(n_pad, -np.inf, np.float32)
                    finite = np.isfinite(vals)
                    scores[ids[finite]] = vals[finite]
                    _count_knn_path("streaming")
                else:
                    key = (
                        ("knn_exact_scores", id(vf),
                         self.snapshot.generation, sim)
                        if node.filter is None else None
                    )

                    touch_allocs = _touch_targets(dev, node.field)

                    def launch_exact(rows):
                        q_batch = _pad_query_batch(rows)
                        t0 = time.perf_counter_ns()
                        with profile.profiling(None):
                            b_scores = np.asarray(knn_ops.exact_knn_scores(
                                q_batch, vf.vectors, vf.norms_sq, valid,
                                vf.similarity,
                            ))
                        launch_params = dict(
                            b=int(q_batch.shape[0]),
                            n=int(vf.vectors.shape[0]),
                            d=int(vf.vectors.shape[1]),
                        )
                        roofline.record_launch(
                            "knn_exact_scores",
                            time.perf_counter_ns() - t0,
                            **launch_params,
                        )
                        # heat touch: the column + live bitmap, bytes from
                        # the same cost model
                        from opensearch_tpu.telemetry.device_ledger import (
                            default_ledger,
                        )

                        default_ledger.touch(
                            touch_allocs, family="knn_exact_scores",
                            params=launch_params)
                        retraced = profile.signature_retraced(
                            "knn_exact_scores", (vf.vectors, q_batch), (sim,))
                        return (
                            [b_scores[i] for i in range(len(rows))], retraced,
                        )

                    out = batcher_mod.dispatch(
                        key, qv[0], launch_exact, shards=1,
                        family="knn_exact_scores",
                        tune_key=("knn_exact_scores",
                                  id(self.mapper_service), node.field))
                    scores = out.value
                    if prof is not None:
                        prof.record_kernel(
                            "knn_exact_scores", out.kernel_share_ns,
                            int(qv.nbytes), out.retraced,
                        )
                    _count_knn_path("materializing")
            per_seg_scores.append(scores)
            n_take = min(node.k, host.n_docs)
            top = np.argpartition(-scores[: host.n_docs], min(n_take, host.n_docs - 1))[:n_take]
            for d in top:
                if np.isfinite(scores[d]):
                    candidates.append((float(scores[d]), seg_idx, int(d)))
        candidates.sort(key=lambda c: (-c[0], c[1], c[2]))
        winners = candidates[: node.k]
        out = []
        for seg_idx, (host, dev) in enumerate(self.snapshot.segments):
            scores = per_seg_scores[seg_idx]
            sel = np.zeros(dev.n_pad, bool)
            if scores is not None:
                for s, si, d in winners:
                    if si == seg_idx:
                        sel[d] = True
            out.append((sel, scores))
        self._knn_cache[id(node)] = out
        return out

    def mlt_rewrite(self, node) -> Any:
        """MoreLikeThisQuery -> bool-should of term queries, selected by
        TF-IDF over the shard's stats (MoreLikeThisQueryBuilder's term
        selection). Cached per shard."""
        cached = self._qs_cache.get(("mlt", id(node)))
        if cached is not None:
            return cached
        import math

        from opensearch_tpu.search import query_dsl as qd

        fields = node.fields or [
            f for f, m in self.mapper_service.mappers.items()
            if m.type == "text"
        ]
        total_docs = max(self.snapshot.num_docs, 1)

        def shard_doc_freq(field, term):
            return sum(
                host.text_fields[field].doc_freq(term)
                for host, _ in self.snapshot.segments
                if field in host.text_fields
            )

        scored: list[tuple[float, str, str]] = []
        for field in fields:
            tf_counts: dict[str, int] = {}
            for text in node.like_texts:
                for term in self.mapper_service.analyze_query_text(field, text):
                    tf_counts[term] = tf_counts.get(term, 0) + 1
            for term, tf in tf_counts.items():
                if tf < node.min_term_freq:
                    continue
                df = shard_doc_freq(field, term)
                if df < node.min_doc_freq or df == 0:
                    continue  # absent terms can never match this shard
                idf = math.log(1.0 + total_docs / df)
                scored.append((tf * idf, field, term))
        scored.sort(key=lambda s: (-s[0], s[1], s[2]))
        top = scored[: node.max_query_terms]
        should = [
            qd.TermQuery(field=f, value=t, boost=w) for w, f, t in top
        ]
        msm = node.minimum_should_match
        try:
            if isinstance(msm, str) and msm.endswith("%"):
                msm_n = int(len(should) * int(msm[:-1]) / 100)
            else:
                msm_n = int(msm)
        except ValueError:
            raise ParsingException(
                f"unsupported [minimum_should_match] value [{msm}] for "
                "[more_like_this] (use an integer or \"N%\")"
            ) from None
        tree = qd.BoolQuery(
            should=should, minimum_should_match=max(msm_n, 1) if should else None,
            boost=node.boost,
        ) if should else qd.MatchNoneQuery()
        self._qs_cache[("mlt", id(node))] = tree
        return tree

    def percolate_masks(self, node) -> list:
        """Per-segment bool masks for a PercolateQuery: each live doc whose
        stored query (at node.field in _source) matches ANY of the provided
        documents. The documents build one tiny in-memory index; each
        stored query executes against it (the percolator module's memory-
        index approach)."""
        cached = self._qs_cache.get(("perc", id(node)))
        if cached is not None:
            return cached
        import json as _json

        import numpy as np

        from opensearch_tpu.index.device import to_device
        from opensearch_tpu.index.engine import SearcherSnapshot
        from opensearch_tpu.index.segment import SegmentBuilder
        from opensearch_tpu.search import query_dsl as qd

        # a search must never mutate index schema: percolated documents are
        # parsed against a CLONE of the mapper service so dynamic mappings
        # introduced by the candidate doc stay local to this query
        import copy as _copy

        tmp_ms = _copy.copy(self.mapper_service)
        tmp_ms.mappers = dict(self.mapper_service.mappers)
        builder = SegmentBuilder(tmp_ms, "_percolate_tmp")
        for i, doc in enumerate(node.documents):
            builder.add(
                tmp_ms.parse_document(f"_tmp_{i}", doc), seq_no=i
            )
        tmp_host = builder.build()
        tmp_dev = to_device(tmp_host)
        tmp_snap = SearcherSnapshot(segments=[(tmp_host, tmp_dev)], generation=0)
        tmp_ctx = ShardContext(tmp_snap, tmp_ms)
        tmp_ex = SegmentExecutor(tmp_ctx, tmp_host, tmp_dev)

        try:
            masks = []
            for host, dev in self.snapshot.segments:
                mask = np.zeros(dev.n_pad, bool)
                for d in range(host.n_docs):
                    if not host.live[d]:
                        continue
                    source = _json.loads(host.sources[d])
                    stored = source.get(node.field)
                    if not isinstance(stored, dict):
                        continue
                    try:
                        parsed = qd.parse_query(stored)
                        r = tmp_ex.execute(parsed)
                        if bool(np.asarray(r.mask)[: tmp_host.n_docs].any()):
                            mask[d] = True
                    except Exception as e:  # noqa: BLE001
                        # malformed stored query never matches
                        logger.debug(
                            "percolate: stored query for doc %d unusable: %s",
                            d, e)
                        continue
                masks.append(mask)
        finally:
            # the throwaway memory-index's device arrays die with this
            # query: release their residency-ledger entries (to_device
            # registered them; without this every percolate query leaked
            # resident_bytes forever)
            tmp_dev.free_allocations(reason="percolate-transient")
        self._qs_cache[("perc", id(node))] = masks
        return masks

    def join_masks(self, node) -> list:
        """Per-segment masks for has_child / has_parent / parent_id.

        Children are routed to the parent's shard (callers index with
        routing=parent id), so the join closes over this shard's segments
        (parent-join module invariant)."""
        cached = self._qs_cache.get(("join", id(node)))
        if cached is not None:
            return cached
        import json as _json

        import numpy as np

        from opensearch_tpu.search import query_dsl as qd

        join_field = None
        for f, m in self.mapper_service.mappers.items():
            if m.type == "join":
                join_field = f
                break
        name_col = f"{join_field}#name" if join_field else None

        def names_of(host):
            kf = host.keyword_fields.get(name_col) if name_col else None
            return kf

        def doc_relation(host, d):
            kf = names_of(host)
            if kf is None:
                return None
            o = kf.first_ord[d]
            return kf.ord_values[o] if o >= 0 else None

        def doc_parent(host, d):
            kf = host.keyword_fields.get(f"{join_field}#parent")
            if kf is None:
                return None
            o = kf.first_ord[d]
            return kf.ord_values[o] if o >= 0 else None

        masks = []
        if isinstance(node, qd.ParentIdQuery):
            for host, dev in self.snapshot.segments:
                mask = np.zeros(dev.n_pad, bool)
                for d in range(host.n_docs):
                    if (host.live[d] and doc_relation(host, d) == node.type
                            and doc_parent(host, d) == node.id):
                        mask[d] = True
                masks.append(mask)
        elif isinstance(node, qd.HasChildQuery):
            # which relation is the parent of node.type? (multi-level joins:
            # a mid-level relation is both a child and a parent)
            join_mapper = self.mapper_service.mappers.get(join_field)
            parent_names = {
                p for p, children in (
                    (join_mapper.relations or {}) if join_mapper else {}
                ).items()
                if node.type in children
            }
            # pass 1: matching children -> parent ids (across segments)
            parent_counts: dict[str, int] = {}
            for host, dev in self.snapshot.segments:
                ex = SegmentExecutor(self, host, dev)
                child_mask = np.asarray(ex.execute(node.query).mask)
                for d in range(host.n_docs):
                    if (host.live[d] and child_mask[d]
                            and doc_relation(host, d) == node.type):
                        p = doc_parent(host, d)
                        if p is not None:
                            parent_counts[p] = parent_counts.get(p, 0) + 1
            wanted = {
                p for p, c in parent_counts.items()
                if node.min_children <= c <= node.max_children
            }
            # pass 2: docs of the parent relation whose _id is in the set
            for host, dev in self.snapshot.segments:
                mask = np.zeros(dev.n_pad, bool)
                for d in range(host.n_docs):
                    if (host.live[d] and host.doc_ids[d] in wanted
                            and doc_relation(host, d) in parent_names):
                        mask[d] = True
                masks.append(mask)
        elif isinstance(node, qd.HasParentQuery):
            # pass 1: matching parents -> their _ids
            parent_ids: set[str] = set()
            for host, dev in self.snapshot.segments:
                ex = SegmentExecutor(self, host, dev)
                pmask = np.asarray(ex.execute(node.query).mask)
                for d in range(host.n_docs):
                    if (host.live[d] and pmask[d]
                            and doc_relation(host, d) == node.parent_type):
                        parent_ids.add(host.doc_ids[d])
            # pass 2: children pointing at those parents
            masks = []
            for host, dev in self.snapshot.segments:
                mask = np.zeros(dev.n_pad, bool)
                for d in range(host.n_docs):
                    if (host.live[d]
                            and doc_parent(host, d) in parent_ids):
                        mask[d] = True
                masks.append(mask)
        self._qs_cache[("join", id(node))] = masks
        return masks

    def text_stats(self, field: str) -> tuple[int, float]:
        """(doc_count, avgdl) across all segments of the shard."""
        doc_count = 0
        total_terms = 0.0
        for host, _ in self.snapshot.segments:
            tf = host.text_fields.get(field)
            if tf is not None:
                doc_count += tf.docs_with_field
                total_terms += tf.total_terms
        if doc_count == 0:
            return 0, 1.0
        return doc_count, total_terms / doc_count

    def text_df(self, field: str, term: str) -> int:
        return sum(
            host.text_fields[field].doc_freq(term)
            for host, _ in self.snapshot.segments
            if field in host.text_fields
        )

    def keyword_df(self, field: str, value: str) -> int:
        df = 0
        for host, _ in self.snapshot.segments:
            kf = host.keyword_fields.get(field)
            if kf is None:
                continue
            o = kf.ord_dict.get(value)
            if o is not None:
                df += int(np.sum(kf.mv_ords == o))
        return df

    def keyword_doc_count(self, field: str) -> int:
        return sum(
            int((host.keyword_fields[field].first_ord >= 0).sum())
            for host, _ in self.snapshot.segments
            if field in host.keyword_fields
        )


# --------------------------------------------------------------------------
# Node execution against one segment
# --------------------------------------------------------------------------


def _phrase_match(lists: list, slop: int, terms: list | None = None) -> bool:
    """True iff one position per term can be chosen with total displacement
    cost Σ|p_i - p_{i-1} - 1| ≤ slop (slop 0 = exact adjacency; adjacent
    swaps cost 2, matching Lucene's sloppy-phrase distance). Repeated query
    terms must land on distinct positions (SloppyPhraseScorer repeats)."""
    if any(len(lst) == 0 for lst in lists):
        return False
    if terms is not None and len(set(terms)) < len(terms):
        # exhaustive search with the distinct-position constraint for
        # repeated terms; per-doc tf keeps the space tiny, but cap it
        def rec(i: int, prev_p: int | None, cost: int,
                used: dict[str, set], budget: list[int]) -> bool:
            if budget[0] <= 0:
                return False
            if cost > slop:
                return False
            if i == len(lists):
                return True
            t = terms[i]
            for p in lists[i]:
                p = int(p)
                if p in used.get(t, ()):
                    continue
                budget[0] -= 1
                step = 0 if prev_p is None else abs(p - prev_p - 1)
                used.setdefault(t, set()).add(p)
                if rec(i + 1, p, cost + step, used, budget):
                    return True
                used[t].discard(p)
            return False

        return rec(0, None, 0, {}, [200_000])
    prev = {int(p): 0 for p in lists[0]}
    for lst in lists[1:]:
        cur: dict[int, int] = {}
        for p in lst:
            p = int(p)
            cur[p] = min(c + abs(p - pq - 1) for pq, c in prev.items())
        prev = cur
        if min(prev.values()) > slop:
            return False  # costs only grow downstream
    return min(prev.values()) <= slop


@dataclass
class NodeResult:
    scores: jnp.ndarray            # f32 [n_pad], 0 where not matching
    mask: jnp.ndarray              # bool [n_pad]
    scoring: bool                  # False => pure filter (score ignored)


class HostNodeResult:
    """NodeResult duck-type for host-resident selections (the bare-kNN hot
    path): the shard cut already picked <= k winners on host, so a
    top-level consumer (execute_query_phase's host fast path) never needs
    device arrays — uploading the scatter arrays and re-top-k'ing them on
    device costs more than the whole remaining request. A COMPOUND parent
    (knn inside bool, rescore, ...) touching `.scores`/`.mask` transparently
    materializes the device arrays, so query semantics never change."""

    __slots__ = ("host_scores", "host_mask", "scoring",
                 "_dev_scores", "_dev_mask")

    def __init__(self, host_scores: np.ndarray, host_mask: np.ndarray,
                 scoring: bool = True):
        self.host_scores = host_scores    # f32 [n_pad], 0 where unselected
        self.host_mask = host_mask        # bool [n_pad]
        self.scoring = scoring
        self._dev_scores = None
        self._dev_mask = None

    @property
    def scores(self) -> jnp.ndarray:
        if self._dev_scores is None:
            self._dev_scores = jnp.asarray(self.host_scores)
        return self._dev_scores

    @property
    def mask(self) -> jnp.ndarray:
        if self._dev_mask is None:
            self._dev_mask = jnp.asarray(self.host_mask)
        return self._dev_mask


def _const_result(mask: jnp.ndarray, boost: float, scoring: bool) -> NodeResult:
    scores = jnp.where(mask, jnp.float32(boost), jnp.float32(0.0))
    return NodeResult(scores=scores, mask=mask, scoring=scoring)


def _empty(dev: DeviceSegment) -> NodeResult:
    z = jnp.zeros(dev.n_pad, jnp.float32)
    return NodeResult(scores=z, mask=jnp.zeros(dev.n_pad, bool), scoring=False)


class SegmentExecutor:
    def __init__(self, ctx: ShardContext, host: HostSegment, dev: DeviceSegment):
        self.ctx = ctx
        self.host = host
        self.dev = dev

    # -- text scoring ------------------------------------------------------

    def _bm25(self, field: str, terms: list[str], boost: float) -> tuple[NodeResult, jnp.ndarray]:
        """Returns (result, per-doc matched-term counts)."""
        dev_tf = self.dev.text_fields.get(field)
        host_tf = self.host.text_fields.get(field)
        if dev_tf is None or host_tf is None or not terms:
            return _empty(self.dev), jnp.zeros(self.dev.n_pad, jnp.int32)
        doc_count, avgdl = self.ctx.text_stats(field)
        offs, lens, idfs = [], [], []
        for t in terms:
            tid = host_tf.term_dict.get(t)
            if tid is None:
                offs.append(0)
                lens.append(0)
                idfs.append(0.0)
            else:
                offs.append(int(host_tf.term_offsets[tid]))
                lens.append(int(host_tf.term_offsets[tid + 1] - host_tf.term_offsets[tid]))
                idfs.append(bm25.idf(self.ctx.text_df(field, t), doc_count))
        window = pad_window(max(lens) if lens else 1)
        # per-term metadata stays HOST numpy here: these columns are the
        # only per-query host->device traffic of the BM25 path (postings
        # are HBM-resident), and the profiler counts transfer bytes from
        # host-typed kernel arguments
        scores, counts = bm25.bm25_term_scores(
            dev_tf.postings_docs,
            dev_tf.postings_tfs,
            dev_tf.doc_len,
            np.asarray(offs, np.int32),
            np.asarray(lens, np.int32),
            np.asarray(idfs, np.float32),
            np.float32(avgdl),
            n_pad=self.dev.n_pad,
            window=window,
        )
        mask = counts > 0
        return NodeResult(scores=scores * boost, mask=mask, scoring=True), counts

    # -- dispatch ----------------------------------------------------------

    def execute(self, node: q.QueryNode) -> NodeResult:
        method = getattr(self, f"_exec_{type(node).__name__}", None)
        if method is None:
            raise ParsingException(f"unexecutable query node [{type(node).__name__}]")
        prof = profile.active()
        if prof is None:
            return method(node)
        # deep profiler: nested execute() calls (bool children, rescore,
        # function_score inners) build the per-operator tree; same node
        # across segments accumulates into one entry
        with prof.operator(type(node).__name__, profile.describe_node(node)):
            return method(node)

    def _exec_MatchAllQuery(self, node: q.MatchAllQuery) -> NodeResult:
        return _const_result(self.dev.live, node.boost, scoring=True)

    def _exec_SliceQuery(self, node: q.SliceQuery) -> NodeResult:
        """Sliced scroll: murmur3(_id) % max == id (SliceBuilder's default
        _id-based partitioning). Hash per doc computed once per segment."""
        from opensearch_tpu.common.hashing import murmur3_x86_32

        host = self.host
        cache = getattr(host, "_slice_hash_cache", None)
        if cache is None:
            cache = np.asarray(
                [murmur3_x86_32(i.encode()) & 0xFFFFFFFF
                 for i in host.doc_ids],
                np.uint32,
            )
            host._slice_hash_cache = cache
        sel = np.zeros(self.dev.n_pad, bool)
        sel[: host.n_docs] = (cache % np.uint32(node.max)) == node.id
        mask = jnp.asarray(sel) & self.dev.live
        return _const_result(mask, node.boost, scoring=False)

    def _exec_MatchNoneQuery(self, node: q.MatchNoneQuery) -> NodeResult:
        return _empty(self.dev)

    def _exec_MatchQuery(self, node: q.MatchQuery) -> NodeResult:
        mapper = self.ctx.mapper_service.field_mapper(node.field)
        if mapper is None and \
                self.ctx.mapper_service.flat_object_parent(node.field):
            return self._exec_TermQuery(
                q.TermQuery(field=node.field, value=node.query, boost=node.boost)
            )
        if mapper is not None and mapper.type != "text":
            # match on non-text behaves like a term query (no analysis)
            return self._exec_TermQuery(
                q.TermQuery(field=node.field, value=node.query, boost=node.boost)
            )
        terms = self.ctx.mapper_service.analyze_query_text(node.field, node.query)
        if not terms:
            # zero analyzed tokens (e.g. all stopwords) matches nothing,
            # like the reference's MatchNoDocsQuery rewrite
            return _empty(self.dev)
        result, counts = self._bm25(node.field, terms, node.boost)
        if node.operator == "and":
            result = NodeResult(
                scores=result.scores, mask=counts >= len(terms), scoring=True
            )
        elif node.minimum_should_match is not None:
            result = NodeResult(
                scores=result.scores,
                mask=counts >= node.minimum_should_match,
                scoring=True,
            )
        return NodeResult(result.scores, result.mask & self.dev.live, True)

    def _exec_MatchPhraseQuery(self, node: q.MatchPhraseQuery) -> NodeResult:
        # Device conjunction narrows candidates; position postings
        # (HostTextField positions CSR) verify adjacency host-side
        # (MatchPhraseQueryBuilder -> Lucene PhraseQuery semantics).
        terms = self.ctx.mapper_service.analyze_query_text(node.field, node.query)
        if not terms:
            return _empty(self.dev)
        result, counts = self._bm25(node.field, terms, node.boost)
        conj = (counts >= len(terms)) & self.dev.live
        host_tf = self.host.text_fields.get(node.field)
        if len(terms) <= 1 or host_tf is None or not host_tf.has_positions:
            # single term, or a legacy segment without position postings:
            # conjunction is the best available answer
            return NodeResult(result.scores, conj, True)
        cand = np.nonzero(np.asarray(conj)[: self.host.n_docs])[0]
        verified = np.zeros(self.dev.n_pad, bool)
        for d in cand:
            lists = [host_tf.term_positions(t, int(d)) for t in terms]
            if _phrase_match(lists, node.slop, terms):
                verified[d] = True
        mask = jnp.asarray(verified)
        return NodeResult(jnp.where(mask, result.scores, 0.0), mask, True)

    def _exec_IntervalsQuery(self, node: q.IntervalsQuery) -> NodeResult:
        from opensearch_tpu.search import intervals as iv

        host_tf = self.host.text_fields.get(node.field)
        if host_tf is None or not host_tf.has_positions:
            return _empty(self.dev)
        ms = self.ctx.mapper_service

        def analyze(text: str, analyzer: str | None) -> list[str]:
            if analyzer:
                return ms.analysis.get(analyzer).analyze(text)
            return ms.analyze_query_text(node.field, text)

        ctx = iv.IntervalContext(
            analyze=analyze,
            vocab=host_tf.terms,
            positions=lambda t, d: host_tf.term_positions(t, d),
            edit_distance_at_most=_edit_distance_at_most,
            fuzziness_distance=_fuzziness_distance,
        )
        # candidate docs: union of posting lists of every involved term
        cand: set[int] = set()
        for t in ctx.leaf_terms(node.source):
            tid = host_tf.term_dict.get(t)
            if tid is None:
                continue
            off = int(host_tf.term_offsets[tid])
            end = int(host_tf.term_offsets[tid + 1])
            cand.update(int(d) for d in host_tf.postings_docs[off:end])
        live = np.asarray(self.dev.live)
        mask = np.zeros(self.dev.n_pad, bool)
        for d in sorted(cand):
            if live[d] and iv.evaluate(node.source, ctx, d):
                mask[d] = True
        return _const_result(jnp.asarray(mask), node.boost, scoring=True)

    def _exec_MultiMatchQuery(self, node: q.MultiMatchQuery) -> NodeResult:
        msm = node.minimum_should_match

        def fboost(f: str) -> float:
            return node.boost * node.field_boosts.get(f, 1.0)

        if node.type == "bool_prefix":
            per_field = [
                self._exec_MatchBoolPrefixQuery(q.MatchBoolPrefixQuery(
                    field=f, query=node.query, operator=node.operator,
                    minimum_should_match=msm, fuzziness=node.fuzziness,
                    analyzer=node.analyzer, boost=fboost(f),
                ))
                for f in node.fields
            ]
        elif node.type == "phrase":
            per_field = [
                self._exec_MatchPhraseQuery(q.MatchPhraseQuery(
                    field=f, query=node.query, slop=node.slop,
                    boost=fboost(f)))
                for f in node.fields
            ]
        elif node.type == "phrase_prefix":
            per_field = [
                self._exec_MatchPhrasePrefixQuery(q.MatchPhrasePrefixQuery(
                    field=f, query=node.query, boost=fboost(f)))
                for f in node.fields
            ]
        else:
            per_field = None
        if per_field is not None:
            if not per_field:
                return _empty(self.dev)
            mask = per_field[0].mask
            scores = per_field[0].scores
            for s in per_field[1:]:
                mask = mask | s.mask
                scores = jnp.maximum(scores, s.scores)
            return NodeResult(scores=scores, mask=mask, scoring=True)
        subs = [
            self._exec_MatchQuery(q.MatchQuery(
                field=f, query=node.query, boost=fboost(f),
                operator=node.operator,
                minimum_should_match=(
                    int(msm) if isinstance(msm, int) or
                    (isinstance(msm, str) and msm.lstrip("-").isdigit())
                    else None),
            ))
            for f in node.fields
        ]
        if not subs:
            return _empty(self.dev)
        mask = subs[0].mask
        for s in subs[1:]:
            mask = mask | s.mask
        if node.type == "most_fields":
            scores = sum((s.scores for s in subs[1:]), subs[0].scores)
        else:  # best_fields: max over fields
            scores = subs[0].scores
            for s in subs[1:]:
                scores = jnp.maximum(scores, s.scores)
        return NodeResult(scores=scores, mask=mask, scoring=True)

    def _normalize_kw(self, field: str, value: str) -> str:
        mapper = self.ctx.mapper_service.field_mapper(field)
        if mapper is not None and mapper.normalizer == "lowercase":
            return value.lower()
        return value

    def _exec_TermQuery(self, node: q.TermQuery) -> NodeResult:
        field, value = node.field, node.value
        if field == "_id":
            return self._exec_IdsQuery(q.IdsQuery(values=[str(value)],
                                                  boost=node.boost))
        mapper = self.ctx.mapper_service.field_mapper(field)
        if mapper is None:
            # sub-path of a flat_object field -> term on the shared
            # "{root}#paths" column with a "sub.path=value" entry
            flat = self.ctx.mapper_service.flat_object_parent(field)
            if flat is not None:
                root, subpath = flat
                return self._exec_TermQuery(q.TermQuery(
                    field=f"{root}#paths", value=f"{subpath}={value}",
                    case_insensitive=node.case_insensitive,
                    boost=node.boost,
                ))
        ftype = mapper.type if mapper else None
        if ftype == "flat_object":
            ftype = "keyword"
        if mapper is not None and mapper.normalizer == "lowercase" \
                and isinstance(value, str):
            value = value.lower()
        if ftype == "text":
            result, _counts = self._bm25(field, [str(value)], node.boost)
            return NodeResult(result.scores, result.mask & self.dev.live, True)
        if ftype == "keyword" or (ftype is None and field in self.host.keyword_fields):
            if node.case_insensitive:
                want = str(value).lower()
                return self._multi_term_result(
                    field, lambda t: t.lower() == want, node.boost
                )
            if mapper is not None and mapper.original_type == "ip" \
                    and "/" in str(value):
                # CIDR term: any stored address inside the subnet
                import ipaddress

                try:
                    net = ipaddress.ip_network(str(value), strict=False)
                except ValueError as e:
                    raise IllegalArgumentException(
                        f"invalid IP subnet [{value}]: {e}"
                    ) from None
                return self._multi_term_result(
                    field,
                    lambda t: (lambda a: a is not None and a in net)(
                        _try_ip(t)
                    ),
                    node.boost,
                )
            kf_dev = self.dev.keyword_fields.get(field)
            kf_host = self.host.keyword_fields.get(field)
            if kf_dev is None:
                return _empty(self.dev)
            qord = kf_host.ord_dict.get(str(value), -3)
            mask = filters.term_mask_keyword(
                kf_dev.mv_ords, kf_dev.mv_docs, jnp.int32(qord), self.dev.n_pad
            ) & self.dev.live
            # keyword term scoring: norms omitted -> idf * tf/(tf+k1), tf=1
            df = self.ctx.keyword_df(field, str(value))
            doc_count = max(self.ctx.keyword_doc_count(field), 1)
            score = bm25.idf(df, doc_count) / (1.0 + bm25.K1_DEFAULT) if df else 0.0
            return _const_result(mask, score * node.boost, scoring=True)
        if ftype in ("boolean",):
            want = 1 if value in (True, "true", 1) else 0
            return self._numeric_range(field, want, None, want, None, node.boost)
        if ftype == "date":
            if mapper.resolution == "nanos":
                from opensearch_tpu.index.mapper import parse_date_nanos

                ms = parse_date_nanos(value)
            else:
                ms = parse_date_millis(value)
            return self._numeric_range(field, ms, None, ms, None, node.boost)
        if ftype in INT_TYPES or ftype in FLOAT_TYPES or ftype is None:
            return self._numeric_range(field, value, None, value, None, node.boost)

        raise IllegalArgumentException(f"term query on unsupported field [{field}]")

    def _exec_TermsQuery(self, node: q.TermsQuery) -> NodeResult:
        if node.field == "_id":
            return self._exec_IdsQuery(q.IdsQuery(
                values=[str(v) for v in node.values], boost=node.boost))
        mapper = self.ctx.mapper_service.field_mapper(node.field)
        if mapper is None:
            flat = self.ctx.mapper_service.flat_object_parent(node.field)
            if flat is not None:
                root, subpath = flat
                return self._exec_TermsQuery(q.TermsQuery(
                    field=f"{root}#paths",
                    values=[f"{subpath}={v}" for v in node.values],
                    boost=node.boost,
                ))
        ftype = mapper.type if mapper else None
        if ftype in ("keyword", "flat_object"):
            kf_dev = self.dev.keyword_fields.get(node.field)
            kf_host = self.host.keyword_fields.get(node.field)
            if kf_dev is None:
                return _empty(self.dev)
            ords = [
                kf_host.ord_dict.get(self._normalize_kw(node.field, str(v)), -3)
                for v in node.values
            ]
            t_pad = max(pad_window(len(ords)), 8)
            ords_arr = np.full(t_pad, -3, np.int32)
            ords_arr[: len(ords)] = ords
            mask = filters.terms_mask_keyword(
                kf_dev.mv_ords, kf_dev.mv_docs, jnp.asarray(ords_arr), self.dev.n_pad
            ) & self.dev.live
            return _const_result(mask, node.boost, scoring=True)
        # numeric/text fallback: OR of term queries
        out: NodeResult | None = None
        for v in node.values:
            r = self._exec_TermQuery(q.TermQuery(field=node.field, value=v, boost=node.boost))
            out = r if out is None else NodeResult(
                jnp.maximum(out.scores, r.scores), out.mask | r.mask, True
            )
        return out if out is not None else _empty(self.dev)

    def _exec_range_field(self, node: q.RangeQuery, mapper) -> NodeResult:
        """Range query against a RANGE FIELD (doc values are intervals in
        the `{field}#lo`/`{field}#hi` columns):
          intersects: doc.lo <= q.hi  AND doc.hi >= q.lo
          contains:   doc.lo <= q.lo  AND doc.hi >= q.hi
          within:     doc.lo >= q.lo  AND doc.hi <= q.hi
        (RangeFieldMapper's BKD relation queries in columnar form)."""
        from opensearch_tpu.index.mapper import range_value_bounds

        try:
            q_lo, q_hi = range_value_bounds(
                mapper.type,
                {"gte": node.gte, "gt": node.gt,
                 "lte": node.lte, "lt": node.lt},
                mapper.format,
            )
        except (ValueError, TypeError) as e:
            raise IllegalArgumentException(
                f"failed to parse range query on [{node.field}]: {e}"
            ) from None
        lo_f, hi_f = f"{node.field}#lo", f"{node.field}#hi"
        relation = node.relation or "intersects"
        if relation == "contains":
            a = self._numeric_range(lo_f, None, None, q_lo, None, 1.0)
            b = self._numeric_range(hi_f, q_hi, None, None, None, 1.0)
        elif relation == "within":
            a = self._numeric_range(lo_f, q_lo, None, None, None, 1.0)
            b = self._numeric_range(hi_f, None, None, q_hi, None, 1.0)
        elif relation == "intersects":
            a = self._numeric_range(lo_f, None, None, q_hi, None, 1.0)
            b = self._numeric_range(hi_f, q_lo, None, None, None, 1.0)
        else:
            raise IllegalArgumentException(
                f"[range] unknown relation [{relation}]")
        mask = a.mask & b.mask & self.dev.live
        return _const_result(mask, node.boost, scoring=True)

    def _numeric_range(
        self, field: str, gte: Any, gt: Any, lte: Any, lt: Any, boost: float
    ) -> NodeResult:
        nf_dev = self.dev.numeric_fields.get(field)
        nf_host = self.host.numeric_fields.get(field)
        if nf_dev is None:
            return _empty(self.dev)
        mapper = self.ctx.mapper_service.field_mapper(field)
        is_date = mapper is not None and mapper.type == "date"
        nanos = is_date and mapper.resolution == "nanos"
        unsigned = mapper is not None and \
            mapper.original_type == "unsigned_long"

        def conv(v: Any) -> Any:
            if v is None:
                return None
            if nanos:
                from opensearch_tpu.index.mapper import parse_date_nanos

                return parse_date_nanos(v)
            if unsigned:
                return int(str(v), 10) - 2**63  # biased storage
            return parse_date_millis(v) if is_date else v

        gte, gt, lte, lt = conv(gte), conv(gt), conv(lte), conv(lt)
        if nf_host is not None and nf_host.mv_offsets is not None:
            # multi-valued docs: a doc matches if ANY value is in range
            # (SortedNumericDocValues semantics) — vectorized host CSR scan
            mv = nf_host.mv_values
            if nf_host.kind == "int":
                lo_b = I64_MIN if gte is None and gt is None else (
                    int(gte) if gte is not None else int(gt) + 1)
                hi_b = I64_MAX if lte is None and lt is None else (
                    int(lte) if lte is not None else int(lt) - 1)
                sel = (mv >= lo_b) & (mv <= hi_b)
            else:
                lo_v = float(gte) if gte is not None else (
                    float(gt) if gt is not None else -np.inf)
                hi_v = float(lte) if lte is not None else (
                    float(lt) if lt is not None else np.inf)
                sel = np.ones(len(mv), bool)
                sel &= (mv > lo_v) if gt is not None else (mv >= lo_v)
                sel &= (mv < hi_v) if lt is not None else (mv <= hi_v)
            mask_host = np.zeros(self.dev.n_pad, bool)
            idx = np.nonzero(sel)[0]
            if len(idx):
                # entry index -> owning doc via the CSR offsets
                doc_of = np.searchsorted(nf_host.mv_offsets, idx, side="right") - 1
                mask_host[np.unique(doc_of)] = True
            return _const_result(
                jnp.asarray(mask_host) & self.dev.live, boost, scoring=True
            )
        if nf_dev.kind == "int":
            lo_bound = I64_MIN if gte is None and gt is None else (
                int(gte) if gte is not None else int(gt) + 1
            )
            hi_bound = I64_MAX if lte is None and lt is None else (
                int(lte) if lte is not None else int(lt) - 1
            )
            ghi, glo = i64_query_words(lo_bound)
            lhi, llo = i64_query_words(hi_bound)
            mask = filters.range_mask_i64(
                nf_dev.hi, nf_dev.lo, nf_dev.present,
                jnp.int32(ghi), jnp.int32(glo), jnp.int32(lhi), jnp.int32(llo),
            )
        else:
            lo_v = float(gte) if gte is not None else (float(gt) if gt is not None else -np.inf)
            hi_v = float(lte) if lte is not None else (float(lt) if lt is not None else np.inf)
            mask = filters.range_mask_f32(
                nf_dev.values, nf_dev.present,
                jnp.float32(lo_v), jnp.float32(hi_v),
                jnp.asarray(gt is not None), jnp.asarray(lt is not None),
            )
        return _const_result(mask & self.dev.live, boost, scoring=True)

    def _exec_RangeQuery(self, node: q.RangeQuery) -> NodeResult:
        mapper = self.ctx.mapper_service.field_mapper(node.field)
        if mapper is not None and mapper.type in RANGE_TYPES:
            return self._exec_range_field(node, mapper)
        if mapper is not None and mapper.type == "flat_object":
            # the root column is keyword-shaped: lexicographic range
            from opensearch_tpu.index.mapper import FieldMapper as _FM

            mapper = _FM(node.field, "keyword")
        if mapper is None:
            flat = self.ctx.mapper_service.flat_object_parent(node.field)
            if flat is not None:
                root, sub = flat
                # lexicographic range inside the "sub=value" entries; the
                # constant "sub=" prefix keeps bounds within this sub-path
                return self._exec_RangeQuery(q.RangeQuery(
                    field=f"{root}#paths",
                    gte=(f"{sub}={node.gte}" if node.gte is not None
                         else f"{sub}="),
                    gt=f"{sub}={node.gt}" if node.gt is not None else None,
                    lte=(f"{sub}={node.lte}" if node.lte is not None
                         else f"{sub}=\uffff"),
                    lt=f"{sub}={node.lt}" if node.lt is not None else None,
                    boost=node.boost,
                ))
        if mapper is not None and mapper.type == "keyword":
            # lexicographic range over ordinals (ordinals are sorted)
            kf_host = self.host.keyword_fields.get(node.field)
            kf_dev = self.dev.keyword_fields.get(node.field)
            if kf_host is None:
                return _empty(self.dev)
            import bisect

            vals = kf_host.ord_values
            lo = 0
            hi = len(vals) - 1
            if node.gte is not None:
                lo = bisect.bisect_left(vals, str(node.gte))
            if node.gt is not None:
                lo = max(lo, bisect.bisect_right(vals, str(node.gt)))
            if node.lte is not None:
                hi = bisect.bisect_right(vals, str(node.lte)) - 1
            if node.lt is not None:
                hi = min(hi, bisect.bisect_left(vals, str(node.lt)) - 1)
            if hi < lo:
                return _empty(self.dev)
            in_range = (kf_dev.mv_ords >= lo) & (kf_dev.mv_ords <= hi)
            mask = (
                jnp.zeros(self.dev.n_pad, jnp.int32)
                .at[kf_dev.mv_docs]
                .max(in_range.astype(jnp.int32))
                .astype(bool)
                & self.dev.live
            )
            return _const_result(mask, node.boost, scoring=True)
        return self._exec_range_numeric(node)

    def _exec_range_numeric(self, node: q.RangeQuery) -> NodeResult:
        return self._numeric_range(node.field, node.gte, node.gt, node.lte, node.lt, node.boost)

    def _exec_TermsSetQuery(self, node: q.TermsSetQuery) -> NodeResult:
        """Per-doc msm: count matching terms against the msm field's value
        (TermsSetQueryBuilder -> CoveringQuery)."""
        field = node.field
        mapper = self.ctx.mapper_service.field_mapper(field)
        if mapper is None:
            flat = self.ctx.mapper_service.flat_object_parent(field)
            if flat is not None:
                root, subpath = flat
                return self._exec_TermsSetQuery(q.TermsSetQuery(
                    field=f"{root}#paths",
                    terms=[f"{subpath}={t}" for t in node.terms],
                    minimum_should_match_field=node.minimum_should_match_field,
                    minimum_should_match_script=node.minimum_should_match_script,
                    boost=node.boost,
                ))
        kf_host = self.host.keyword_fields.get(field)
        counts = np.zeros(self.host.n_docs, np.int64)
        if kf_host is not None:
            for v in node.terms:
                val = self._normalize_kw(field, str(v))
                o = kf_host.ord_dict.get(val)
                if o is None:
                    continue
                sel = kf_host.mv_ords == o
                np.add.at(counts, kf_host.mv_docs[sel], 1)
        elif mapper is not None and mapper.type == "text":
            tf_host = self.host.text_fields.get(field)
            if tf_host is not None:
                for v in node.terms:
                    tid = tf_host.term_dict.get(str(v))
                    if tid is None:
                        continue
                    off = int(tf_host.term_offsets[tid])
                    end = int(tf_host.term_offsets[tid + 1])
                    counts[tf_host.postings_docs[off:end]] += 1
        if node.minimum_should_match_field:
            nf = self.host.numeric_fields.get(node.minimum_should_match_field)
            if nf is None:
                return _empty(self.dev)
            msm = np.where(
                nf.present[: self.host.n_docs],
                (nf.values_i64 if nf.kind == "int" else nf.values_f64)[
                    : self.host.n_docs],
                np.iinfo(np.int32).max,
            )
        elif node.minimum_should_match_script:
            from opensearch_tpu.script import default_script_service

            src = str(node.minimum_should_match_script.get("source", ""))
            # common pattern: params.num_terms or a constant
            if "num_terms" in src:
                msm = np.full(self.host.n_docs, len(node.terms))
            else:
                try:
                    msm = np.full(self.host.n_docs, int(float(src)))
                except ValueError:
                    msm = np.full(self.host.n_docs, 1)
        else:
            raise IllegalArgumentException(
                "[terms_set] requires [minimum_should_match_field] or "
                "[minimum_should_match_script]"
            )
        mask_host = np.zeros(self.dev.n_pad, bool)
        mask_host[: self.host.n_docs] = (counts >= msm) & (counts > 0)
        return _const_result(
            jnp.asarray(mask_host) & self.dev.live, node.boost, scoring=True
        )

    def _exec_DistanceFeatureQuery(self, node: q.DistanceFeatureQuery) -> NodeResult:
        """score = boost * pivot / (pivot + distance(origin, value))."""
        field = node.field
        mapper = self.ctx.mapper_service.field_mapper(field)
        n = self.host.n_docs
        lat_f = self.host.numeric_fields.get(f"{field}#lat")
        if mapper is not None and mapper.type == "geo_point" \
                or lat_f is not None:
            lon_f = self.host.numeric_fields.get(f"{field}#lon")
            if lat_f is None or lon_f is None:
                return _empty(self.dev)
            o_lat, o_lon = _parse_geo_origin(node.origin)
            pivot_m = _parse_distance_meters(node.pivot)
            lat = lat_f.values_f64[:n]
            lon = lon_f.values_f64[:n]
            dist = _haversine_m(o_lat, o_lon, lat, lon)
            present = lat_f.present[:n]
            score = np.where(present, pivot_m / (pivot_m + dist), 0.0)
        else:
            nf = self.host.numeric_fields.get(field)
            if nf is None:
                return _empty(self.dev)
            is_date = mapper is not None and mapper.type == "date"
            if is_date and getattr(mapper, "resolution", "millis") == "nanos":
                from opensearch_tpu.index.mapper import parse_date_nanos

                origin = float(parse_date_nanos(str(node.origin)))
                pivot = float(_duration_millis(node.pivot)) * 1e6
            elif is_date:
                origin = float(_parse_date_or_now(node.origin))
                pivot = float(_duration_millis(node.pivot))
            else:
                origin = float(node.origin)
                pivot = float(node.pivot)
            vals = (nf.values_i64 if nf.kind == "int" else nf.values_f64)[:n]
            dist = np.abs(vals.astype(np.float64) - origin)
            score = np.where(nf.present[:n], pivot / (pivot + dist), 0.0)
        scores = np.zeros(self.dev.n_pad, np.float32)
        scores[:n] = score * node.boost
        mask = jnp.asarray(scores > 0) & self.dev.live
        return NodeResult(
            scores=jnp.where(mask, jnp.asarray(scores), 0.0), mask=mask,
            scoring=True,
        )

    def _exec_RankFeatureQuery(self, node: q.RankFeatureQuery) -> NodeResult:
        """saturation: v/(v+pivot) (default pivot = field mean); log:
        ln(sf + v); sigmoid: v^e/(v^e + pivot^e); linear: v."""
        nf = self.host.numeric_fields.get(node.field)
        if nf is None:
            return _empty(self.dev)
        n = self.host.n_docs
        vals = (nf.values_i64 if nf.kind == "int" else nf.values_f64)[:n]
        vals = vals.astype(np.float64)
        present = nf.present[:n]
        def default_pivot() -> float:
            # approximate geometric mean over the WHOLE shard (the
            # reference computes the pivot from index-level stats; a
            # per-segment pivot would rank equal-feature docs differently
            # across segments)
            total, count = 0.0, 0
            for h, _d in self.ctx.snapshot.segments:
                f = h.numeric_fields.get(node.field)
                if f is None:
                    continue
                v = (f.values_i64 if f.kind == "int" else f.values_f64)[
                    : h.n_docs]
                p = f.present[: h.n_docs]
                total += float(v[p].sum())
                count += int(p.sum())
            return max(total / count if count else 1.0, 1e-9)

        if node.function == "log":
            score = np.log(np.maximum(node.scaling_factor + vals, 1e-12))
        elif node.function == "linear":
            score = vals
        elif node.function == "sigmoid":
            pivot = node.pivot if node.pivot is not None else default_pivot()
            ve = np.power(vals, node.exponent)
            score = ve / (ve + pivot ** node.exponent)
        else:  # saturation
            pivot = node.pivot if node.pivot is not None else default_pivot()
            score = vals / (vals + pivot)
        scores = np.zeros(self.dev.n_pad, np.float32)
        scores[:n] = np.where(present, score, 0.0) * node.boost
        mask = jnp.asarray(np.pad(present, (0, self.dev.n_pad - n))) & self.dev.live
        return NodeResult(
            scores=jnp.where(mask, jnp.asarray(scores), 0.0), mask=mask,
            scoring=True,
        )

    def _geo_columns(self, field: str):
        lat_f = self.host.numeric_fields.get(f"{field}#lat")
        lon_f = self.host.numeric_fields.get(f"{field}#lon")
        if lat_f is None or lon_f is None:
            return None
        n = self.host.n_docs
        return (lat_f.values_f64[:n], lon_f.values_f64[:n],
                lat_f.present[:n])

    def _geo_match_docs(self, field: str, point_pred) -> np.ndarray | None:
        """bool[n_docs] — doc matches if ANY of its points satisfies
        `point_pred(lat_array, lon_array) -> bool_array` (multi-valued
        geo_point docs hold parallel lat/lon CSRs)."""
        lat_f = self.host.numeric_fields.get(f"{field}#lat")
        lon_f = self.host.numeric_fields.get(f"{field}#lon")
        if lat_f is None or lon_f is None:
            return None
        n = self.host.n_docs
        out = np.zeros(n, bool)
        if lat_f.mv_offsets is not None and lon_f.mv_offsets is not None:
            sel = point_pred(lat_f.mv_values, lon_f.mv_values)
            idx = np.nonzero(sel)[0]
            if len(idx):
                doc_of = np.searchsorted(lat_f.mv_offsets, idx,
                                         side="right") - 1
                out[np.unique(doc_of)] = True
            return out
        sel = point_pred(lat_f.values_f64[:n], lon_f.values_f64[:n])
        out[:n] = lat_f.present[:n] & sel
        return out

    def _exec_GeoDistanceQuery(self, node: q.GeoDistanceQuery) -> NodeResult:
        o_lat, o_lon = _parse_geo_origin(node.point)
        radius = _parse_distance_meters(node.distance)
        sel = self._geo_match_docs(
            node.field,
            lambda la, lo: _haversine_m(o_lat, o_lon, la, lo) <= radius,
        )
        if sel is None:
            return _empty(self.dev)
        mask_host = np.zeros(self.dev.n_pad, bool)
        mask_host[: self.host.n_docs] = sel
        return _const_result(jnp.asarray(mask_host) & self.dev.live,
                             node.boost, scoring=True)

    def _exec_GeoShapeQuery(self, node: q.GeoShapeQuery) -> NodeResult:
        """geo_shape over point columns: the shape's bounding box is the
        match region (exact for envelope/point; polygon matches by bbox —
        a documented approximation of the reference's tessellated shapes)."""
        shape = node.shape or {}
        styp = str(shape.get("type", "")).lower()
        coords = shape.get("coordinates")
        if styp == "point":
            lons = [coords[0]]
            lats = [coords[1]]
        elif styp == "envelope":
            (tl_lon, tl_lat), (br_lon, br_lat) = coords
            lons = [tl_lon, br_lon]
            lats = [tl_lat, br_lat]
        elif styp in ("polygon", "multipoint", "linestring"):
            flat = coords[0] if styp == "polygon" else coords
            lons = [c[0] for c in flat]
            lats = [c[1] for c in flat]
        else:
            raise IllegalArgumentException(
                f"[geo_shape] unsupported shape type [{styp}]"
            )
        lat_hi, lat_lo = max(lats), min(lats)
        lon_hi, lon_lo = max(lons), min(lons)

        def pred(la, lo):
            inside = (la >= lat_lo) & (la <= lat_hi) \
                & (lo >= lon_lo) & (lo <= lon_hi)
            return ~inside if node.relation == "disjoint" else inside

        sel = self._geo_match_docs(node.field, pred)
        if sel is None:
            return _empty(self.dev)
        mask_host = np.zeros(self.dev.n_pad, bool)
        mask_host[: self.host.n_docs] = sel
        return _const_result(jnp.asarray(mask_host) & self.dev.live,
                             node.boost, scoring=True)

    def _exec_GeoBoundingBoxQuery(self, node: q.GeoBoundingBoxQuery) -> NodeResult:
        tl_lat, tl_lon = _parse_geo_origin(node.top_left)
        br_lat, br_lon = _parse_geo_origin(node.bottom_right)

        def pred(la, lo):
            box = (la <= tl_lat) & (la >= br_lat)
            if tl_lon <= br_lon:
                return box & (lo >= tl_lon) & (lo <= br_lon)
            return box & ((lo >= tl_lon) | (lo <= br_lon))

        sel = self._geo_match_docs(node.field, pred)
        if sel is None:
            return _empty(self.dev)
        mask_host = np.zeros(self.dev.n_pad, bool)
        mask_host[: self.host.n_docs] = sel
        return _const_result(jnp.asarray(mask_host) & self.dev.live,
                             node.boost, scoring=True)

    def _exec_ExistsQuery(self, node: q.ExistsQuery) -> NodeResult:
        field = node.field
        flat = self.ctx.mapper_service.flat_object_parent(field)
        if flat is not None and self.ctx.mapper_service.mappers.get(field) is None:
            root, subpath = flat
            # sub-path exists == any "{subpath}=value" entry in #paths, or
            # any deeper "{subpath}.x=value" entry
            r1 = self._exec_PrefixQuery(q.PrefixQuery(
                field=f"{root}#paths", value=f"{subpath}=", boost=node.boost))
            r2 = self._exec_PrefixQuery(q.PrefixQuery(
                field=f"{root}#paths", value=f"{subpath}.", boost=node.boost))
            return NodeResult(jnp.maximum(r1.scores, r2.scores),
                              r1.mask | r2.mask, True)
        masks = []
        if field not in self.dev.numeric_fields \
                and field not in self.dev.vector_fields \
                and field not in self.dev.keyword_fields \
                and field not in self.dev.text_fields:
            # object prefix: exists == any mapped child exists
            children = [
                name for name in self.ctx.mapper_service.mappers
                if name.startswith(f"{field}.")
            ]
            if children:
                out = None
                for child in children:
                    r = self._exec_ExistsQuery(
                        q.ExistsQuery(field=child, boost=node.boost)
                    )
                    out = r if out is None else NodeResult(
                        jnp.maximum(out.scores, r.scores),
                        out.mask | r.mask, True,
                    )
                if out is not None:
                    return out
        if field in self.dev.numeric_fields:
            masks.append(self.dev.numeric_fields[field].present)
        if field in self.dev.vector_fields:
            masks.append(self.dev.vector_fields[field].present)
        if field in self.dev.keyword_fields:
            masks.append(self.dev.keyword_fields[field].first_ord >= 0)
        if field in self.dev.text_fields:
            masks.append(self.dev.text_fields[field].doc_len > 0)
        if not masks:
            return _empty(self.dev)
        mask = masks[0]
        for m in masks[1:]:
            mask = mask | m
        return _const_result(mask & self.dev.live, node.boost, scoring=True)

    def _exec_IdsQuery(self, node: q.IdsQuery) -> NodeResult:
        mask_host = np.zeros(self.dev.n_pad, dtype=bool)
        for doc_id in node.values:
            # doc_index (not local_doc): liveness comes from the snapshot's
            # device mask, so pinned PIT/scroll readers stay point-in-time
            d = self.host.doc_index(doc_id)
            if d is not None:
                mask_host[d] = True
        return _const_result(jnp.asarray(mask_host) & self.dev.live, node.boost, True)

    def _exec_ConstantScoreQuery(self, node: q.ConstantScoreQuery) -> NodeResult:
        inner = self.execute(node.filter)
        return _const_result(inner.mask, node.boost, scoring=True)

    def _exec_BoolQuery(self, node: q.BoolQuery) -> NodeResult:
        n_pad = self.dev.n_pad
        mask = self.dev.live
        scores = jnp.zeros(n_pad, jnp.float32)
        any_scoring = False
        for sub in node.must:
            r = self.execute(sub)
            mask = mask & r.mask
            if r.scoring:
                any_scoring = True
            scores = scores + r.scores
        for sub in node.filter:
            r = self.execute(sub)
            mask = mask & r.mask
        for sub in node.must_not:
            r = self.execute(sub)
            mask = mask & ~r.mask
        if node.should:
            should_results = [self.execute(sub) for sub in node.should]
            should_count = jnp.zeros(n_pad, jnp.int32)
            for r in should_results:
                should_count = should_count + r.mask.astype(jnp.int32)
                scores = scores + jnp.where(r.mask, r.scores, 0.0)
                if r.scoring:
                    any_scoring = True
            msm = node.minimum_should_match
            if msm is None:
                msm = 1 if not (node.must or node.filter) else 0
            if msm > 0:
                mask = mask & (should_count >= msm)
        # scores of non-matching docs must be zeroed (a must_not can strike
        # a doc that a should scored)
        scores = jnp.where(mask, scores, 0.0) * node.boost
        return NodeResult(scores=scores, mask=mask, scoring=any_scoring)

    def _exec_KnnQuery(self, node: q.KnnQuery) -> NodeResult:
        # k applies per SHARD (top-k cut across all its segments) — the
        # ShardContext caches the shard-wide selection per query node
        selections = self.ctx.shard_knn_selection(node)
        seg_idx = next(
            i for i, (h, d) in enumerate(self.ctx.snapshot.segments) if d is self.dev
        )
        sel_host, scores_host = selections[seg_idx]
        if scores_host is None:
            return _empty(self.dev)
        # host-resident result: the shard cut already chose the winners;
        # device arrays materialize only if a compound parent needs them
        out_scores = np.where(
            sel_host & np.isfinite(scores_host), scores_host, 0.0
        ).astype(np.float32)
        if node.boost != 1.0:
            out_scores *= np.float32(node.boost)
        return HostNodeResult(out_scores, sel_host, scoring=True)

    def _exec_ScriptScoreQuery(self, node: q.ScriptScoreQuery) -> NodeResult:
        inner = self.execute(node.query) if node.query else self._exec_MatchAllQuery(q.MatchAllQuery())
        vf = self.dev.vector_fields.get(node.field)
        if vf is None:
            return _empty(self.dev)
        valid = vf.present & inner.mask
        # host numpy: counted as this request's host->device transfer
        qv = np.asarray([node.query_vector], np.float32)
        if node.function == "knn_score":
            scores = knn.exact_knn_scores(qv, vf.vectors, vf.norms_sq, valid, node.space_type)[0]
            scores = jnp.where(valid, scores, 0.0)
        else:
            raw = knn.raw_similarity(
                qv, vf.vectors, vf.norms_sq,
                "l2_norm" if node.space_type == "l2_raw" else node.space_type,
            )[0]
            if node.space_type == "l2_raw":
                raw = jnp.maximum(-raw, 0.0)  # l2Squared returns the distance
            scores = jnp.where(valid, raw + node.add_constant, 0.0)
        return NodeResult(scores=scores * node.boost, mask=valid, scoring=True)

    def _exec_GenericScriptScoreQuery(self, node: q.GenericScriptScoreQuery) -> NodeResult:
        """Per-doc host evaluation (the reference's ScriptScoreFunction runs
        a compiled script per collected doc — same cost model; the vector
        patterns take the fused device path instead)."""
        from opensearch_tpu.script import default_script_service

        inner = self.execute(node.query) if node.query else self._exec_MatchAllQuery(
            q.MatchAllQuery()
        )
        ast, params = default_script_service.compile(node.script)
        mask_host = np.asarray(inner.mask)[: self.host.n_docs]
        base_scores = np.asarray(inner.scores)[: self.host.n_docs]
        scores = np.zeros(self.dev.n_pad, np.float32)
        ms = self.ctx.mapper_service
        for d in np.nonzero(mask_host)[0]:
            scores[d] = default_script_service.score(
                ast, params, self.host, int(d), ms, score=float(base_scores[d])
            )
        return NodeResult(
            scores=jnp.asarray(scores) * node.boost, mask=inner.mask, scoring=True
        )

    def _exec_ScriptQuery(self, node: q.ScriptQuery) -> NodeResult:
        from opensearch_tpu.script import default_script_service

        ast, params = default_script_service.compile(node.script)
        live_host = np.asarray(self.dev.live)[: self.host.n_docs]
        mask = np.zeros(self.dev.n_pad, bool)
        ms = self.ctx.mapper_service
        for d in np.nonzero(live_host)[0]:
            out = default_script_service.field(ast, params, self.host, int(d), ms)
            if out:
                mask[d] = True
        return _const_result(jnp.asarray(mask), node.boost, scoring=True)

    # -- multi-term (term-enumeration) queries -----------------------------
    # The reference rewrites these to constant-score over the matching term
    # set (MultiTermQuery CONSTANT_SCORE_REWRITE); here the term dictionary
    # walk happens host-side (same place Lucene's FST walk runs) and only
    # the final doc mask touches the device.

    def _host_mask_for_terms(self, field: str, match_fn) -> np.ndarray:
        mask = np.zeros(self.dev.n_pad, bool)
        host_tf = self.host.text_fields.get(field)
        if host_tf is not None:
            for tid, term in enumerate(host_tf.terms):
                if match_fn(term):
                    off = int(host_tf.term_offsets[tid])
                    end = int(host_tf.term_offsets[tid + 1])
                    mask[host_tf.postings_docs[off:end]] = True
        kf = self.host.keyword_fields.get(field)
        if kf is not None:
            ords = [o for o, v in enumerate(kf.ord_values) if match_fn(v)]
            if ords:
                sel = np.isin(kf.mv_ords, np.asarray(ords, kf.mv_ords.dtype))
                mask[kf.mv_docs[sel]] = True
        return mask

    def _multi_term_result(self, field: str, match_fn, boost: float) -> NodeResult:
        mask = jnp.asarray(self._host_mask_for_terms(field, match_fn)) & self.dev.live
        return _const_result(mask, boost, scoring=True)

    def _exec_PrefixQuery(self, node: q.PrefixQuery) -> NodeResult:
        if self.ctx.mapper_service.field_mapper(node.field) is None:
            flat = self.ctx.mapper_service.flat_object_parent(node.field)
            if flat is not None:
                root, subpath = flat
                return self._exec_PrefixQuery(q.PrefixQuery(
                    field=f"{root}#paths", value=f"{subpath}={node.value}",
                    case_insensitive=node.case_insensitive,
                    boost=node.boost,
                ))
        prefix = self._normalize_kw(node.field, node.value)
        prefix = prefix.lower() if node.case_insensitive else prefix
        if node.case_insensitive:
            return self._multi_term_result(
                node.field, lambda t: t.lower().startswith(prefix), node.boost
            )
        return self._multi_term_result(
            node.field, lambda t: t.startswith(prefix), node.boost
        )

    def _exec_WildcardQuery(self, node: q.WildcardQuery) -> NodeResult:
        if self.ctx.mapper_service.field_mapper(node.field) is None:
            flat = self.ctx.mapper_service.flat_object_parent(node.field)
            if flat is not None:
                root, subpath = flat
                return self._exec_WildcardQuery(q.WildcardQuery(
                    field=f"{root}#paths", value=f"{subpath}={node.value}",
                    case_insensitive=node.case_insensitive,
                    boost=node.boost,
                ))
        wc_value = self._normalize_kw(node.field, node.value)
        m_wc = self.ctx.mapper_service.field_mapper(node.field)
        if m_wc is not None and m_wc.type == "text":
            # wildcard patterns normalize through the analyzer chain
            # (lowercase) like the classic parser's multi-term handling
            wc_value = wc_value.lower()
        rx = _wildcard_to_regex(wc_value, node.case_insensitive)
        return self._multi_term_result(
            node.field, lambda t: rx.match(t) is not None, node.boost
        )

    def _exec_RegexpQuery(self, node: q.RegexpQuery) -> NodeResult:
        value = self._normalize_kw(node.field, node.value)
        m = self.ctx.mapper_service.field_mapper(node.field)
        if m is not None and m.type == "text":
            # analyzed text is lowercased; the classic parser normalizes
            # multi-term patterns through the analyzer chain
            value = value.lower()
        node = q.RegexpQuery(field=node.field, value=value,
                             case_insensitive=node.case_insensitive,
                             boost=node.boost)
        if len(node.value) > 1000:
            raise IllegalArgumentException(
                f"The length of regex [{len(node.value)}] used in the "
                f"Regexp Query request has exceeded the allowed maximum "
                f"of [1000]. This maximum can be set by changing the "
                f"[index.max_regex_length] index level setting."
            )
        try:
            rx = re.compile(
                node.value, re.IGNORECASE if node.case_insensitive else 0
            )
        except re.error as e:
            raise IllegalArgumentException(f"invalid regexp [{node.value}]: {e}")
        return self._multi_term_result(
            node.field, lambda t: rx.fullmatch(t) is not None, node.boost
        )

    def _exec_FuzzyQuery(self, node: q.FuzzyQuery) -> NodeResult:
        value = node.value
        max_d = _fuzziness_distance(node.fuzziness, value)
        plen = node.prefix_length

        def match(t: str) -> bool:
            if plen and t[:plen] != value[:plen]:
                return False
            if abs(len(t) - len(value)) > max_d:
                return False
            return _edit_distance_at_most(value, t, max_d)

        return self._multi_term_result(node.field, match, node.boost)

    def _exec_MatchPhrasePrefixQuery(self, node: q.MatchPhrasePrefixQuery) -> NodeResult:
        terms = self.ctx.mapper_service.analyze_query_text(node.field, node.query)
        if not terms:
            return _empty(self.dev)
        *body_terms, last = terms
        result = None
        if body_terms:
            r, counts = self._bm25(node.field, body_terms, node.boost)
            result = NodeResult(r.scores, counts >= len(body_terms), True)
        # expand the final term as a prefix (bounded by max_expansions, like
        # MatchPhrasePrefixQuery's MultiPhrasePrefixQuery expansion)
        expansions = 0

        def match(t: str) -> bool:
            nonlocal expansions
            if expansions >= node.max_expansions:
                return False
            if t.startswith(last):
                expansions += 1
                return True
            return False

        prefix_mask = jnp.asarray(self._host_mask_for_terms(node.field, match))
        if result is None:
            return _const_result(prefix_mask & self.dev.live, node.boost, True)
        mask = result.mask & prefix_mask & self.dev.live
        return NodeResult(jnp.where(mask, result.scores, 0.0), mask, True)

    def _exec_MatchBoolPrefixQuery(self, node: q.MatchBoolPrefixQuery) -> NodeResult:
        if node.analyzer:
            terms = self.ctx.mapper_service.analysis.get(node.analyzer).analyze(
                node.query
            )
        else:
            terms = self.ctx.mapper_service.analyze_query_text(node.field, node.query)
        if not terms:
            return _empty(self.dev)
        *body_terms, last = terms

        def term_clause(t: str) -> q.QueryNode:
            if node.fuzziness is not None:
                return q.FuzzyQuery(field=node.field, value=t,
                                    fuzziness=node.fuzziness)
            return q.TermQuery(field=node.field, value=t)

        subs: list[q.QueryNode] = [term_clause(t) for t in body_terms]
        subs.append(q.PrefixQuery(field=node.field, value=last))
        if node.operator == "and":
            return self._exec_BoolQuery(q.BoolQuery(must=subs, boost=node.boost))
        msm = node.minimum_should_match
        if msm is not None:
            try:
                msm = int(str(msm).rstrip("%"))
                if str(node.minimum_should_match).endswith("%"):
                    msm = max(1, (len(subs) * msm) // 100)
            except ValueError:
                msm = None
        return self._exec_BoolQuery(
            q.BoolQuery(should=subs, minimum_should_match=msm, boost=node.boost)
        )

    # -- query-string family ----------------------------------------------

    def _exec_QueryStringQuery(self, node: q.QueryStringQuery) -> NodeResult:
        r = self.execute(self.ctx.rewritten_query_string(node))
        return NodeResult(r.scores * node.boost, r.mask, r.scoring)

    def _exec_SimpleQueryStringQuery(self, node: q.SimpleQueryStringQuery) -> NodeResult:
        r = self.execute(self.ctx.rewritten_query_string(node))
        return NodeResult(r.scores * node.boost, r.mask, r.scoring)

    # -- compound scoring queries ------------------------------------------

    def _exec_BoostingQuery(self, node: q.BoostingQuery) -> NodeResult:
        pos = self.execute(node.positive)
        neg = self.execute(node.negative)
        scores = jnp.where(
            neg.mask, pos.scores * jnp.float32(node.negative_boost), pos.scores
        )
        return NodeResult(scores * node.boost, pos.mask, True)

    def _exec_DisMaxQuery(self, node: q.DisMaxQuery) -> NodeResult:
        if not node.queries:
            return _empty(self.dev)
        subs = [self.execute(sq) for sq in node.queries]
        mask = subs[0].mask
        best = subs[0].scores
        total = subs[0].scores
        for s in subs[1:]:
            mask = mask | s.mask
            best = jnp.maximum(best, s.scores)
            total = total + s.scores
        scores = best + jnp.float32(node.tie_breaker) * (total - best)
        return NodeResult(jnp.where(mask, scores, 0.0) * node.boost, mask, True)

    def _exec_NestedQuery(self, node: q.NestedQuery) -> NodeResult:
        # Flattened semantics: arrays of objects were indexed as multi-valued
        # dotted columns, so the inner query already addresses path.field.
        r = self.execute(node.query)
        return NodeResult(r.scores * node.boost, r.mask, r.scoring)

    def _exec_MoreLikeThisQuery(self, node: q.MoreLikeThisQuery) -> NodeResult:
        return self.execute(self.ctx.mlt_rewrite(node))

    def _seg_index(self) -> int:
        for i, (host, _dev) in enumerate(self.ctx.snapshot.segments):
            if host is self.host:
                return i
        return 0

    def _exec_PercolateQuery(self, node: q.PercolateQuery) -> NodeResult:
        mask_host = self.ctx.percolate_masks(node)[self._seg_index()]
        mask = jnp.asarray(mask_host) & self.dev.live
        return _const_result(mask, node.boost, scoring=True)

    def _exec_HasChildQuery(self, node: q.HasChildQuery) -> NodeResult:
        mask_host = self.ctx.join_masks(node)[self._seg_index()]
        mask = jnp.asarray(mask_host) & self.dev.live
        return _const_result(mask, node.boost, scoring=True)

    def _exec_HasParentQuery(self, node: q.HasParentQuery) -> NodeResult:
        mask_host = self.ctx.join_masks(node)[self._seg_index()]
        mask = jnp.asarray(mask_host) & self.dev.live
        return _const_result(mask, node.boost, scoring=True)

    def _exec_ParentIdQuery(self, node: q.ParentIdQuery) -> NodeResult:
        mask_host = self.ctx.join_masks(node)[self._seg_index()]
        mask = jnp.asarray(mask_host) & self.dev.live
        return _const_result(mask, node.boost, scoring=True)

    def _exec_HybridQuery(self, node: q.HybridQuery) -> NodeResult:
        # Executor-level fallback (no search pipeline): max combination.
        # The service runs sub-queries separately when a normalization
        # pipeline is active (see search/pipeline.py).
        return self._exec_DisMaxQuery(
            q.DisMaxQuery(queries=node.queries, tie_breaker=0.0, boost=node.boost)
        )

    def _exec_FunctionScoreQuery(self, node: q.FunctionScoreQuery) -> NodeResult:
        base = self.execute(node.query)
        n_pad = self.dev.n_pad
        fvals: list[tuple[jnp.ndarray, jnp.ndarray]] = []  # (value, applies-mask)
        for fn in node.functions:
            applies = base.mask
            if fn.filter is not None:
                applies = applies & self.execute(fn.filter).mask
            val = self._function_value(fn)
            if fn.weight is not None:
                val = val * jnp.float32(fn.weight)
            fvals.append((val, applies))

        if not fvals:
            factor = jnp.ones(n_pad, jnp.float32)
        else:
            mode = node.score_mode
            if mode == "first":
                factor = jnp.ones(n_pad, jnp.float32)
                assigned = jnp.zeros(n_pad, bool)
                for val, applies in fvals:
                    take = applies & ~assigned
                    factor = jnp.where(take, val, factor)
                    assigned = assigned | applies
            elif mode in ("sum", "avg"):
                total = jnp.zeros(n_pad, jnp.float32)
                cnt = jnp.zeros(n_pad, jnp.float32)
                for val, applies in fvals:
                    total = total + jnp.where(applies, val, 0.0)
                    cnt = cnt + applies.astype(jnp.float32)
                factor = jnp.where(cnt > 0, total, 1.0)
                if mode == "avg":
                    factor = jnp.where(cnt > 0, total / jnp.maximum(cnt, 1.0), 1.0)
            elif mode in ("max", "min"):
                init = jnp.full(n_pad, -jnp.inf if mode == "max" else jnp.inf, jnp.float32)
                acc = init
                for val, applies in fvals:
                    pick = jnp.maximum if mode == "max" else jnp.minimum
                    acc = jnp.where(applies, pick(acc, val), acc)
                factor = jnp.where(jnp.isfinite(acc), acc, 1.0)
            else:  # multiply (default)
                factor = jnp.ones(n_pad, jnp.float32)
                for val, applies in fvals:
                    factor = factor * jnp.where(applies, val, 1.0)
        if np.isfinite(node.max_boost):
            factor = jnp.minimum(factor, jnp.float32(node.max_boost))

        qs = base.scores
        bm = node.boost_mode
        if bm == "replace":
            scores = factor
        elif bm == "sum":
            scores = qs + factor
        elif bm == "avg":
            scores = (qs + factor) / 2.0
        elif bm == "max":
            scores = jnp.maximum(qs, factor)
        elif bm == "min":
            scores = jnp.minimum(qs, factor)
        else:  # multiply
            scores = qs * factor
        mask = base.mask
        if node.min_score is not None:
            mask = mask & (scores >= jnp.float32(node.min_score))
        scores = jnp.where(mask, scores, 0.0) * node.boost
        return NodeResult(scores, mask, True)

    def _function_value(self, fn: q.ScoreFunction) -> jnp.ndarray:
        n_pad = self.dev.n_pad
        if fn.kind == "weight":
            return jnp.ones(n_pad, jnp.float32)
        if fn.kind == "random_score":
            # deterministic per-doc hash (reference: seeded random_score)
            idx = jnp.arange(n_pad, dtype=jnp.uint32)
            h = (idx * jnp.uint32(2654435761) + jnp.uint32(fn.seed * 40503 + 1)) & jnp.uint32(0x7FFFFFFF)
            return h.astype(jnp.float32) / jnp.float32(0x7FFFFFFF)
        if fn.kind == "field_value_factor":
            vals, present = self._numeric_doc_values(fn.field)
            if fn.missing is not None:
                vals = jnp.where(present, vals, jnp.float32(fn.missing))
            else:
                vals = jnp.where(present, vals, 1.0)
            v = vals * jnp.float32(fn.factor)
            m = fn.modifier
            if m == "log":
                v = jnp.log10(jnp.maximum(v, 1e-9))
            elif m == "log1p":
                v = jnp.log10(v + 1.0)
            elif m == "log2p":
                v = jnp.log10(v + 2.0)
            elif m == "ln":
                v = jnp.log(jnp.maximum(v, 1e-9))
            elif m == "ln1p":
                v = jnp.log1p(v)
            elif m == "ln2p":
                v = jnp.log(v + 2.0)
            elif m == "square":
                v = v * v
            elif m == "sqrt":
                v = jnp.sqrt(jnp.maximum(v, 0.0))
            elif m == "reciprocal":
                v = 1.0 / jnp.maximum(v, 1e-9)
            return v
        if fn.kind == "decay":
            mapper = self.ctx.mapper_service.field_mapper(fn.field)
            is_date = mapper is not None and mapper.type == "date"
            if is_date:
                origin = float(parse_date_millis(fn.origin)) if fn.origin is not None else 0.0
                scale = float(_duration_millis(fn.scale))
                offset = float(_duration_millis(fn.offset)) if fn.offset else 0.0
            else:
                origin = float(fn.origin if fn.origin is not None else 0.0)
                scale = float(fn.scale)
                offset = float(fn.offset or 0.0)
            vals, present = self._numeric_doc_values(fn.field)
            dist = jnp.maximum(jnp.abs(vals - jnp.float32(origin)) - jnp.float32(offset), 0.0)
            if fn.decay_type == "gauss":
                sigma2 = -(scale**2) / (2.0 * np.log(fn.decay))
                out = jnp.exp(-(dist**2) / jnp.float32(2 * sigma2))
            elif fn.decay_type == "exp":
                lam = np.log(fn.decay) / scale
                out = jnp.exp(jnp.float32(lam) * dist)
            else:  # linear
                s = scale / (1.0 - fn.decay)
                out = jnp.maximum(
                    (jnp.float32(s) - dist) / jnp.float32(s), 0.0
                )
            return jnp.where(present, out, 1.0)
        raise IllegalArgumentException(f"unknown score function [{fn.kind}]")

    def _numeric_doc_values(self, field: str) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(float32 values, present) for a numeric/date field on this segment."""
        nf_dev = self.dev.numeric_fields.get(field)
        nf_host = self.host.numeric_fields.get(field)
        if nf_dev is None or nf_host is None:
            z = jnp.zeros(self.dev.n_pad, jnp.float32)
            return z, jnp.zeros(self.dev.n_pad, bool)
        if nf_host.kind == "int":
            vals = np.zeros(self.dev.n_pad, np.float32)
            vals[: self.host.n_docs] = nf_host.values_i64.astype(np.float64)[: self.host.n_docs]
        else:
            vals = np.zeros(self.dev.n_pad, np.float32)
            vals[: self.host.n_docs] = nf_host.values_f64[: self.host.n_docs]
        return jnp.asarray(vals), nf_dev.present


def _wildcard_to_regex(pattern: str, case_insensitive: bool) -> "re.Pattern":
    out = []
    for ch in pattern:
        if ch == "*":
            out.append(".*")
        elif ch == "?":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("".join(out) + r"\Z", re.IGNORECASE if case_insensitive else 0)


def _fuzziness_distance(fuzziness: str, term: str) -> int:
    f = str(fuzziness).upper()
    if f == "AUTO":
        n = len(term)
        return 0 if n < 3 else (1 if n <= 5 else 2)
    try:
        return int(f)
    except ValueError:
        raise IllegalArgumentException(f"invalid fuzziness [{fuzziness}]")


def _edit_distance_at_most(a: str, b: str, max_d: int) -> bool:
    """OSA (Damerau-Levenshtein with adjacent transpositions = 1 edit) with
    early exit — fuzzy queries default to transpositions=true like Lucene's
    LevenshteinAutomata(..., transpositions)."""
    if max_d == 0:
        return a == b
    la, lb = len(a), len(b)
    prev2: list[int] | None = None
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        row_min = i
        for j in range(1, lb + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (prev2 is not None and i > 1 and j > 1
                    and a[i - 1] == b[j - 2] and a[i - 2] == b[j - 1]):
                cur[j] = min(cur[j], prev2[j - 2] + 1)
            row_min = min(row_min, cur[j])
        if row_min > max_d:
            return False
        prev2, prev = prev, cur
    return prev[lb] <= max_d


def _try_ip(value: str):
    import ipaddress

    try:
        return ipaddress.ip_address(value)
    except ValueError:
        return None


def _parse_geo_origin(origin: Any) -> tuple[float, float]:
    """(lat, lon) from the geo_point literal forms."""
    if isinstance(origin, dict) and "lat" in origin and "lon" in origin:
        return float(origin["lat"]), float(origin["lon"])
    if isinstance(origin, list) and len(origin) >= 2:
        return float(origin[1]), float(origin[0])  # [lon, lat]
    if isinstance(origin, str) and "," in origin:
        parts = origin.split(",")
        return float(parts[0]), float(parts[1])
    raise IllegalArgumentException(f"invalid geo origin [{origin!r}]")


def _parse_distance_meters(v: Any) -> float:
    """"5km" / "500m" / "1mi" ... -> meters (DistanceUnit)."""
    if isinstance(v, (int, float)):
        return float(v)
    m = re.fullmatch(
        r"\s*(\d+(?:\.\d+)?)\s*(mm|cm|m|km|mi|miles|yd|ft|in|nmi|NM)\s*",
        str(v),
    )
    if not m:
        raise IllegalArgumentException(f"invalid distance [{v}]")
    mult = {"mm": 0.001, "cm": 0.01, "m": 1.0, "km": 1000.0,
            "mi": 1609.344, "miles": 1609.344, "yd": 0.9144,
            "ft": 0.3048, "in": 0.0254, "nmi": 1852.0, "NM": 1852.0}
    return float(m.group(1)) * mult[m.group(2)]


def _haversine_m(lat1: float, lon1: float, lat2, lon2):
    """Great-circle distance in meters (GeoUtils.arcDistance)."""
    r = 6371008.8
    p1, p2 = np.radians(lat1), np.radians(lat2)
    dp = p2 - p1
    dl = np.radians(lon2) - np.radians(lon1)
    a = np.sin(dp / 2.0) ** 2 + np.cos(p1) * np.cos(p2) * np.sin(dl / 2.0) ** 2
    return 2.0 * r * np.arcsin(np.sqrt(np.clip(a, 0.0, 1.0)))


def _parse_date_or_now(v: Any) -> int:
    """Date literal or date-math anchored at now ("now", "now-7d")."""
    import time as _time

    s = str(v).strip() if not hasattr(v, "isoformat") else v.isoformat()
    if s.startswith("now"):
        base = int(_time.time() * 1000)
        rest = s[3:]
        if not rest:
            return base
        sign = 1 if rest[0] == "+" else -1
        return base + sign * _duration_millis(rest[1:].split("/")[0])
    return parse_date_millis(v)


def _duration_millis(v: Any) -> int:
    """Parse a date-math duration like "10d", "2h", "30m" to milliseconds."""
    if isinstance(v, (int, float)):
        return int(v)
    m = re.fullmatch(
        r"(\d+(?:\.\d+)?)(nanos|micros|ms|s|m|h|d|w)", str(v).strip()
    )
    if not m:
        raise IllegalArgumentException(f"invalid duration [{v}]")
    n = float(m.group(1))
    mult = {"nanos": 1e-6, "micros": 1e-3, "ms": 1, "s": 1000, "m": 60_000,
            "h": 3_600_000, "d": 86_400_000, "w": 604_800_000}[m.group(2)]
    return int(n * mult) if m.group(2) not in ("nanos", "micros") \
        else n * mult


# --------------------------------------------------------------------------
# Shard-level query phase
# --------------------------------------------------------------------------


@dataclass
class ShardHit:
    score: float
    segment: int          # index into snapshot.segments
    doc: int              # local doc id
    sort_values: list = dc_field(default_factory=list)


@dataclass
class ShardQueryResult:
    hits: list[ShardHit]
    total: int
    max_score: float | None
    # per-segment match masks (host bool arrays) for the aggs phase
    masks: list[np.ndarray] = dc_field(default_factory=list)
    # per-segment score arrays (host f32, n_docs) — kept alongside the masks
    # so score-dependent aggregations (top_hits, sampler, scripted_metric)
    # see the query-phase scores
    score_arrays: list[np.ndarray] = dc_field(default_factory=list)


def execute_query_phase(
    snapshot: SearcherSnapshot,
    mapper_service: MapperService,
    query_node: q.QueryNode,
    size: int,
    sort: list[dict] | None = None,
    need_masks: bool = False,
    min_score: float | None = None,
) -> ShardQueryResult:
    ctx = ShardContext(snapshot, mapper_service)
    masks: list[np.ndarray] = []
    score_arrays: list[np.ndarray] = []
    total = 0
    max_score: float | None = None
    all_hits: list[ShardHit] = []

    for seg_idx, (host, dev) in enumerate(snapshot.segments):
        ex = SegmentExecutor(ctx, host, dev)
        result = ex.execute(query_node)
        if isinstance(result, HostNodeResult) and not sort:
            # host fast path (bare kNN): the selection is already the
            # shard-level top-k cut, computed against the SNAPSHOT's
            # device live mask — re-uploading the scatter arrays just to
            # segment_top_k <= k winners on device would cost more than
            # the rest of the request (a real serving-path tax: one
            # launch + two transfers + a fence, all GIL-serial)
            prof = profile.active()
            t_collect = time.perf_counter_ns()
            mask_h = result.host_mask
            scores_h = result.host_scores
            if min_score is not None:
                mask_h = mask_h & (scores_h >= np.float32(min_score))
            if need_masks:
                masks.append(mask_h[: host.n_docs])
                score_arrays.append(scores_h[: host.n_docs])
            total += int(mask_h.sum())
            if size > 0:
                for d in np.nonzero(mask_h)[0]:
                    v = float(scores_h[d])
                    all_hits.append(ShardHit(v, seg_idx, int(d)))
                    if max_score is None or v > max_score:
                        max_score = v
            if prof is not None:
                prof.collect_ns += time.perf_counter_ns() - t_collect
            continue
        mask = result.mask & dev.live
        if min_score is not None:
            # min_score excludes docs from hits AND total (reference:
            # QueryPhase applies MinScoreCollectorContext before counting)
            mask = mask & (result.scores >= jnp.float32(min_score))
        mask_host = np.asarray(mask)[: host.n_docs]
        if need_masks:
            masks.append(mask_host)
            score_arrays.append(np.asarray(result.scores)[: host.n_docs])
        total += int(mask_host.sum())
        prof = profile.active()
        t_collect = time.perf_counter_ns()
        if size > 0:
            if not sort:
                k = min(size, dev.n_pad)
                masked = jnp.where(mask, result.scores, -jnp.inf)
                from opensearch_tpu.ops.topk import segment_top_k

                vals, ids = segment_top_k(masked, k)
                vals_h, ids_h = np.asarray(vals), np.asarray(ids)
                for v, d in zip(vals_h, ids_h):
                    if np.isfinite(v):
                        all_hits.append(ShardHit(float(v), seg_idx, int(d)))
                        if max_score is None or v > max_score:
                            max_score = float(v)
            else:
                scores_h = np.asarray(result.scores)[: host.n_docs]
                all_hits.extend(
                    _sorted_segment_hits(
                        host, mask_host, scores_h, sort, size, seg_idx, mapper_service
                    )
                )
        if prof is not None:
            # the top-k cut / field sort is this engine's collector
            prof.collect_ns += time.perf_counter_ns() - t_collect

    t_final = time.perf_counter_ns()
    if not sort:
        all_hits.sort(key=lambda h: (-h.score, h.segment, h.doc))
        all_hits = all_hits[:size]
    else:
        all_hits.sort(key=_sort_key_fn(sort))
        all_hits = all_hits[:size]
    final_prof = profile.active()
    if final_prof is not None:
        final_prof.collect_ns += time.perf_counter_ns() - t_final
    return ShardQueryResult(
        hits=all_hits, total=total, max_score=max_score, masks=masks,
        score_arrays=score_arrays,
    )


def _field_sort_values(
    host: HostSegment, field: str, docs: np.ndarray,
    mapper_service: MapperService, mode: str | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """(values float64/int64, present bool) for the requested docs. A field
    absent from this whole segment means every doc's value is missing (the
    reference sorts those by the `missing` policy rather than erroring).
    `mode` picks the multi-value reduction (SortedNumericSortField's
    min/max/sum/avg/median; default min asc / max desc chosen by caller)."""
    nf = host.numeric_fields.get(field)
    if nf is not None:
        mapper = mapper_service.field_mapper(field)
        unsigned = mapper is not None and \
            getattr(mapper, "original_type", None) == "unsigned_long"
        vals = nf.values_i64 if nf.kind == "int" else nf.values_f64
        if unsigned:
            # unbias in exact python-int space (np int64 would overflow)
            def _avg_exact(vv):
                # unsigned_long reduces in BigInteger space: exact
                # half-up rounding (the reference's unsigned sort values)
                s_ = sum(vv)
                n_ = len(vv)
                return (2 * s_ + n_) // (2 * n_)

            def _median_exact(vv):
                sv = sorted(vv)
                n_ = len(sv)
                if n_ % 2:
                    return sv[n_ // 2]
                return (sv[n_ // 2 - 1] + sv[n_ // 2] + 1) // 2

            def _sum_wrap(vv):
                # unsigned sums wrap at 2^64
                return sum(vv) % 2**64

            red = {"min": min, "max": max, "sum": _sum_wrap,
                   "avg": _avg_exact,
                   "median": _median_exact,
                   }.get(mode or "min", min)
            out = np.empty(len(docs), dtype=object)
            for i, d in enumerate(docs):
                if nf.present[d]:
                    vv = [int(x) + 2**63 for x in nf.doc_values(int(d))]
                    out[i] = red(vv) if vv else 0
                else:
                    out[i] = 0
            return out, nf.present[docs]
        if mode and nf.mv_offsets is not None:
            is_int = nf.kind == "int"

            def _sum(a):
                if not is_int:
                    return np.sum(a)
                return sum(int(x) for x in a)  # exact python-int sum

            def _avg(a):
                if not is_int:
                    return float(np.mean(a))
                # long avg: exact sum -> double -> truncate back to long
                # (the reference's double cast)
                return int(float(_sum(a)) / len(a))

            def _median(a):
                sa = np.sort(a)
                n_ = len(sa)
                if n_ % 2:
                    return sa[n_ // 2]
                lo_, hi_ = sa[n_ // 2 - 1], sa[n_ // 2]
                if not is_int:
                    return (float(lo_) + float(hi_)) / 2.0
                return int(float(int(lo_) + int(hi_)) / 2.0)

            red = {"min": np.min, "max": np.max, "sum": _sum,
                   "avg": _avg, "median": _median}.get(mode, np.min)
            out = np.array([
                red(nf.doc_values(int(d))) if nf.present[d] else 0
                for d in docs
            ])
            return out, nf.present[docs]
        return vals[docs], nf.present[docs]
    kf = host.keyword_fields.get(field)
    if kf is not None:
        # ordinal sort within a segment is NOT globally consistent across
        # segments; use the string values for cross-segment correctness
        ords = kf.first_ord[docs]
        return ords, ords >= 0
    return np.zeros(len(docs)), np.zeros(len(docs), bool)


def _sorted_segment_hits(
    host: HostSegment,
    mask: np.ndarray,
    scores: np.ndarray,
    sort: list[dict],
    size: int,
    seg_idx: int,
    mapper_service: MapperService,
) -> list[ShardHit]:
    docs = np.nonzero(mask)[0]
    if len(docs) == 0:
        return []
    hits = []
    sort_cols = []
    for spec in sort:
        fname, order, _missing = _sort_spec(spec)
        if fname == "_score":
            sort_cols.append((scores[docs], np.ones(len(docs), bool), order, None))
        elif fname in ("_doc", "_shard_doc"):
            sort_cols.append((docs.astype(np.float64), np.ones(len(docs), bool), order, None))
        else:
            spec_conf = spec if isinstance(spec, dict) else {}
            conf = spec_conf.get(fname) if isinstance(spec_conf.get(fname), dict) else {}
            mode = conf.get("mode") or ("max" if order == "desc" else "min")
            vals, present = _field_sort_values(host, fname, docs,
                                               mapper_service, mode=mode)
            kf = host.keyword_fields.get(fname)
            sort_cols.append((vals, present, order, kf.ord_values if kf is not None else None))
    for i, d in enumerate(docs):
        sv = []
        for col_i, (vals, present, order, ord_values) in enumerate(sort_cols):
            if not present[i]:
                sv.append(None)
            elif ord_values is not None:
                sv.append(ord_values[int(vals[i])])
            else:
                v = vals[i]
                out_v = (int(v) if isinstance(v, (np.integer, int))
                         else float(v))
                sv.append(out_v)
        hits.append(ShardHit(float(scores[d]), seg_idx, int(d), sort_values=sv))
    keys = _sort_key_fn(sort)
    hits.sort(key=keys)
    return hits[:size]


def _sort_spec(spec: dict | str) -> tuple[str, str, Any]:
    if isinstance(spec, str):
        return spec, ("desc" if spec == "_score" else "asc"), None
    if len(spec) != 1:
        raise ParsingException("each sort entry must have a single field")
    fname, conf = next(iter(spec.items()))
    if isinstance(conf, str):
        return fname, conf, None
    return fname, conf.get("order", "desc" if fname == "_score" else "asc"), conf.get("missing")


def _sort_key_fn(sort: list[dict]):
    specs = [_sort_spec(s) for s in sort]

    def key(hit: ShardHit):
        parts = []
        for i, (fname, order, missing) in enumerate(specs):
            if fname == "_score":
                v = hit.score
                parts.append(-v if order == "desc" else v)
                continue
            if fname == "_doc":
                parts.append((hit.segment, hit.doc) if order == "asc" else (-hit.segment, -hit.doc))
                continue
            v = hit.sort_values[i] if i < len(hit.sort_values) else None
            if v is None and missing not in (None, "_last", "_first"):
                v = missing  # substitute the user-provided missing value
            if v is None:
                # _last (default): sorts after every real value in either
                # order; _first: before
                parts.append((-1, 0) if missing == "_first" else (1, 0))
            elif isinstance(v, str):
                # desc string order via a reflected-comparison wrapper
                parts.append((0, _StrKey(v, order == "desc")))
            else:
                parts.append((0, -v if order == "desc" else v))
        parts.append((hit.segment, hit.doc))
        return tuple(parts)

    return key


class _StrKey:
    __slots__ = ("v", "desc")

    def __init__(self, v: str, desc: bool):
        self.v = v
        self.desc = desc

    def __lt__(self, other: "_StrKey") -> bool:
        return (self.v > other.v) if self.desc else (self.v < other.v)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _StrKey) and self.v == other.v
