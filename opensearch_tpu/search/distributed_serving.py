"""Distributed exact-kNN serving: the on-device cross-shard merge in _search.

This wires parallel/distributed.build_knn_serving_step into the serving
path (VERDICT r2 missing #1): a multi-shard knn query executes ONE
shard_map program over the device mesh — per-shard scoring + top-k on each
device, then all_gather + top_k over ICI — replacing the host-side k-way
merge of the reference's SearchPhaseController.mergeTopDocs
(server/src/main/java/org/opensearch/action/search/SearchPhaseController.java:224)
and its per-shard fan-out (AbstractSearchAsyncAction.java:281).

Layout: at first use after a refresh, each shard's segment vector columns
are flattened into one [n_flat, d] slab (segment-ascending, doc-ascending —
the host merge's tie-break order), stacked to [S, n_flat, d] and device_put
with the shard axis over the mesh's data axis. The slabs are cached per
(index, field, per-shard segment generations); a refresh invalidates only
that index's entry.

Fallback contract: any shape this path cannot serve identically to the host
merge (filters, ANN-indexed segments, mixed similarities) returns None and
the caller keeps the host path — the can-serve gate mirrors how the
reference keeps BKD/points fast paths behind eligibility checks.
"""

from __future__ import annotations

from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from opensearch_tpu.parallel.distributed import build_knn_serving_step
from opensearch_tpu.parallel.mesh import DATA_AXIS
from opensearch_tpu.search.executor import ShardHit, ShardQueryResult

# observability: tests and the multichip dryrun assert the serving path ran
stats = {"distributed_searches": 0, "fallbacks": 0}

# kill switch (tests compare against the host merge; ops can disable)
enabled = True

_BUNDLE_CACHE: dict[tuple, "_IndexBundle"] = {}
_PROGRAM_CACHE: dict[tuple, Any] = {}
_MESH_CACHE: dict[int, Mesh] = {}
_MAX_BUNDLES = 8


class _IndexBundle:
    """[S, n_flat, d] mesh-sharded slabs + host-side flat->segment maps."""

    def __init__(self, vectors, norms_sq, valid, n_flat: int,
                 seg_offsets: list[list[tuple[int, int, int]]]):
        self.vectors = vectors          # jnp [S, n_flat, d] on mesh
        self.norms_sq = norms_sq        # jnp [S, n_flat]
        self.valid = valid              # jnp [S, n_flat]
        self.n_flat = n_flat
        # per shard: [(flat_start, seg_idx, n_docs)] in segment order
        self.seg_offsets = seg_offsets

    def locate(self, shard_idx: int, flat: int) -> tuple[int, int]:
        for start, seg_idx, n_docs in self.seg_offsets[shard_idx]:
            if start <= flat < start + n_docs:
                return seg_idx, flat - start
        raise IndexError(f"flat doc {flat} out of range for shard {shard_idx}")


def _serving_mesh(n_devices: int) -> Mesh:
    mesh = _MESH_CACHE.get(n_devices)
    if mesh is None:
        grid = np.asarray(jax.devices()[:n_devices]).reshape(n_devices)
        mesh = Mesh(grid, (DATA_AXIS,))
        _MESH_CACHE[n_devices] = mesh
    return mesh


def _largest_divisor_at_most(s: int, cap: int) -> int:
    for d in range(min(s, cap), 0, -1):
        if s % d == 0:
            return d
    return 1


def _can_serve(snaps: list, field: str) -> tuple[str, int] | None:
    """Returns (similarity, dims) if every shard can be served exactly,
    else None. ANN-indexed segments fall back: the host path would answer
    them with IVF-PQ, and this path must stay bit-identical to the host."""
    from opensearch_tpu.ops.knn import canonical_similarity

    similarity = None
    dims = None
    any_field = False
    for snap in snaps:
        for host, dev in snap.segments:
            vf = dev.vector_fields.get(field)
            if vf is None:
                continue
            any_field = True
            if vf.ann is not None:
                return None
            sim = canonical_similarity(vf.similarity)
            if similarity is None:
                similarity, dims = sim, vf.dims
            elif sim != similarity or vf.dims != dims:
                return None
    if not any_field:
        return None
    return similarity, dims


def _build_bundle(snaps: list, field: str, dims: int, mesh: Mesh) -> _IndexBundle:
    per_shard_vecs: list[np.ndarray] = []
    per_shard_norms: list[np.ndarray] = []
    per_shard_valid: list[np.ndarray] = []
    seg_offsets: list[list[tuple[int, int, int]]] = []
    for snap in snaps:
        chunks_v, chunks_n, chunks_ok = [], [], []
        offsets: list[tuple[int, int, int]] = []
        pos = 0
        for seg_idx, (host, dev) in enumerate(snap.segments):
            n = host.n_docs
            hvf = host.vector_fields.get(field)
            if hvf is None:
                chunks_v.append(np.zeros((n, dims), np.float32))
                chunks_n.append(np.zeros(n, np.float32))
                chunks_ok.append(np.zeros(n, bool))
            else:
                v = np.asarray(hvf.vectors[:n], np.float32)
                chunks_v.append(v)
                # identical norm formula to index/device.to_device so scores
                # match the host path bit-for-bit
                chunks_n.append(
                    (v.astype(np.float64) ** 2).sum(axis=1).astype(np.float32)
                )
                # dev.live, not host.live: deletes flip host.live in place
                # before refresh, but the host query path masks with the
                # PUBLISHED live bitmap (executor.py uses dev.live) — the
                # bundle must see exactly what the host path sees
                chunks_ok.append(
                    np.asarray(hvf.present[:n], bool)
                    & np.asarray(dev.live)[:n]
                )
            offsets.append((pos, seg_idx, n))
            pos += n
        seg_offsets.append(offsets)
        per_shard_vecs.append(
            np.concatenate(chunks_v) if chunks_v else np.zeros((0, dims), np.float32)
        )
        per_shard_norms.append(
            np.concatenate(chunks_n) if chunks_n else np.zeros(0, np.float32)
        )
        per_shard_valid.append(
            np.concatenate(chunks_ok) if chunks_ok else np.zeros(0, bool)
        )

    max_docs = max((v.shape[0] for v in per_shard_vecs), default=1)
    # bucket to the next power of two: keeps the compiled program stable
    # across refreshes that grow a shard slightly (query-shape cache,
    # SURVEY.md §7 hard part #3)
    n_flat = 1 << max(int(max_docs - 1).bit_length(), 3)

    def pad(a: np.ndarray, fill=0) -> np.ndarray:
        out = np.full((n_flat, *a.shape[1:]), fill, dtype=a.dtype)
        out[: a.shape[0]] = a
        return out

    vecs = np.stack([pad(v) for v in per_shard_vecs])
    norms = np.stack([pad(n) for n in per_shard_norms])
    valid = np.stack([pad(v, fill=False) for v in per_shard_valid])

    sharding = NamedSharding(mesh, P(DATA_AXIS))
    return _IndexBundle(
        vectors=jax.device_put(jnp.asarray(vecs), NamedSharding(mesh, P(DATA_AXIS, None, None))),
        norms_sq=jax.device_put(jnp.asarray(norms), sharding),
        valid=jax.device_put(jnp.asarray(valid), sharding),
        n_flat=n_flat,
        seg_offsets=seg_offsets,
    )


def try_distributed_knn(
    shards: list,
    snaps: list,
    node,
    fetch_k: int,
) -> list[ShardQueryResult] | None:
    """Execute a multi-shard KnnQuery through the on-device merge program.
    Returns per-shard ShardQueryResults shaped exactly like the host path's
    (winning hits attributed to their shards, per-shard matched counts), or
    None when this path cannot reproduce the host result."""
    if node.filter is not None or not shards or len(shards) != len(snaps):
        return None
    s = len(shards)
    if s < 2:
        return None
    served = _can_serve(snaps, node.field)
    if served is None:
        stats["fallbacks"] += 1
        return None
    similarity, dims = served
    if len(node.vector) != dims:
        return None

    n_devices = _largest_divisor_at_most(s, len(jax.devices()))
    mesh = _serving_mesh(n_devices)

    index_name = shards[0].shard_id.index
    cache_key = (
        index_name, node.field, s,
        # engine instance ids make the key immune to delete+recreate cycles
        # (generations restart at 0 on a fresh engine)
        tuple(sh.engine.instance_id for sh in shards),
        tuple(snap.generation for snap in snaps),
        tuple(len(snap.segments) for snap in snaps),
    )
    bundle = _BUNDLE_CACHE.get(cache_key)
    if bundle is None:
        # one live bundle per (index, field): refreshes replace it
        for key in [k for k in _BUNDLE_CACHE if k[:2] == cache_key[:2]]:
            del _BUNDLE_CACHE[key]
        while len(_BUNDLE_CACHE) >= _MAX_BUNDLES:
            del _BUNDLE_CACHE[next(iter(_BUNDLE_CACHE))]
        bundle = _build_bundle(snaps, node.field, dims, mesh)
        _BUNDLE_CACHE[cache_key] = bundle

    k_shard = max(1, min(int(node.k), bundle.n_flat))
    k_final = min(max(k_shard, int(fetch_k)), s * k_shard)
    prog_key = (n_devices, s, bundle.n_flat, dims, k_shard, k_final, similarity)
    program = _PROGRAM_CACHE.get(prog_key)
    if program is None:
        program = build_knn_serving_step(
            mesh, k_shard=k_shard, k_final=k_final, similarity=similarity
        )
        _PROGRAM_CACHE[prog_key] = program

    queries = jnp.asarray([node.vector], jnp.float32)
    with mesh:
        vals, gids, counts = program(
            bundle.vectors, bundle.norms_sq, bundle.valid, queries
        )
    vals = np.asarray(vals)[0]
    gids = np.asarray(gids)[0]
    counts = np.asarray(counts)[:, 0]
    stats["distributed_searches"] += 1

    boost = np.float32(getattr(node, "boost", 1.0))
    per_shard_hits: list[list[ShardHit]] = [[] for _ in range(s)]
    for v, g in zip(vals, gids):
        if not np.isfinite(v):
            continue
        shard_idx, flat = int(g) // bundle.n_flat, int(g) % bundle.n_flat
        seg_idx, doc = bundle.locate(shard_idx, flat)
        per_shard_hits[shard_idx].append(
            ShardHit(float(np.float32(v) * boost), seg_idx, doc)
        )

    results = []
    for shard_idx in range(s):
        hits = per_shard_hits[shard_idx]
        results.append(ShardQueryResult(
            hits=hits,
            total=int(counts[shard_idx]),
            max_score=max((h.score for h in hits), default=None),
        ))
    return results


def clear_caches() -> None:
    _BUNDLE_CACHE.clear()
    _PROGRAM_CACHE.clear()
    _MESH_CACHE.clear()
