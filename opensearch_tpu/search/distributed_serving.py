"""Distributed exact-kNN serving: the on-device cross-shard merge in _search.

This wires parallel/distributed.build_knn_serving_step into the serving
path (VERDICT r2 missing #1): a multi-shard knn query executes ONE
shard_map program over the device mesh — per-shard scoring + top-k on each
device, then all_gather + top_k over ICI — replacing the host-side k-way
merge of the reference's SearchPhaseController.mergeTopDocs
(server/src/main/java/org/opensearch/action/search/SearchPhaseController.java:224)
and its per-shard fan-out (AbstractSearchAsyncAction.java:281).

Layout: at first use after a refresh, each shard's segment vector columns
are flattened into one [n_flat, d] slab (segment-ascending, doc-ascending —
the host merge's tie-break order), stacked to [S, n_flat, d] and device_put
with the shard axis over the mesh's data axis. The slabs are cached per
(index, field, per-shard segment generations); a refresh invalidates only
that index's entry.

Fallback contract: any shape this path cannot serve identically to the host
merge (ANN-indexed segments on unfiltered queries, mixed similarities)
returns None and the caller keeps the host path — the can-serve gate
mirrors how the reference keeps BKD/points fast paths behind eligibility
checks.

Round 5 widening (VERDICT r4 #1): the gates that restricted this path to
unfiltered multi-shard queries, one vector per dispatch, are lifted:
 - FILTERED kNN: the filter (knn-level and per-shard alias filters) is
   evaluated host-side per segment (the same SegmentExecutor the host path
   uses), flattened to a [S, n_flat] mask, ANDed with the bundle's valid
   mask, and the SAME device program runs — pre-filter semantics identical
   to the host (executor.shard_knn_selection:118). Because the host path
   falls back to an exact scan whenever a filter is present, ANN-indexed
   segments are also eligible when filtered.
 - SINGLE-SHARD: s == 1 runs the same program on a 1-device mesh (the
   all_gather degenerates); the streaming executor path is bypassed in
   favor of the resident bundle.
 - BATCHED multi-query: try_distributed_knn_batch dispatches B query
   vectors in ONE program launch ([B, d] padded to a power of two), which
   is what amortizes the ~65 ms tunnel round-trip (bench.py's own
   insight); facade.msearch groups eligible consecutive knn searches into
   one such dispatch.
"""

from __future__ import annotations

import threading
import time
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from opensearch_tpu.cluster.shard_mesh import default_registry as registry
from opensearch_tpu.parallel.distributed import build_knn_serving_step
from opensearch_tpu.parallel.mesh import DATA_AXIS
from opensearch_tpu.search.executor import ShardHit, ShardQueryResult

# observability: tests and the multichip dryrun assert the serving path
# ran. Increment via _count(): searches run on a parallel pool, and a bare
# `dict[k] += 1` drops counts under concurrent read-modify-write.
stats = {
    "distributed_searches": 0,
    "fallbacks": 0,
    "filtered": 0,          # dispatches that carried a filter mask
    "single_shard": 0,      # dispatches with s == 1
    "batched_queries": 0,   # total query vectors sent in B>1 dispatches
}
_STATS_LOCK = threading.Lock()


def _count(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        stats[key] += n

# kill switch (tests compare against the host merge; ops can disable)
enabled = True

_PROGRAM_CACHE: dict[tuple, Any] = {}
_MESH_CACHE: dict[int, Mesh] = {}
# searches run on a parallel pool since the kNN batcher PR: concurrent
# cache misses must not race program-cache insertion (bundle residency has
# its own lock inside the ShardMeshRegistry)
_CACHE_LOCK = threading.Lock()


class MeshLaunchOutcome:
    """What ONE sharded launch produced, for every query it served.

    `per_query[q]` is the per-shard ShardQueryResult list shaped exactly
    like the host path's; `premerged[q]` is the same winning hits as a flat
    [(shard_idx, ShardHit)] list in the DEVICE merge order — which equals
    the host merge's (-score, shard, segment, doc) ordering exactly, so the
    caller can skip its host-side re-sort. `launch_id`/`wall_ns`/`retraced`
    feed per-shard profile attribution (one launch record shared by every
    shard the program covered)."""

    __slots__ = ("per_query", "premerged", "launch_id", "wall_ns",
                 "retraced", "shards")

    def __init__(self, per_query, premerged, launch_id, wall_ns, retraced,
                 shards):
        self.per_query = per_query
        self.premerged = premerged
        self.launch_id = launch_id
        self.wall_ns = wall_ns
        self.retraced = retraced
        self.shards = shards


class _IndexBundle:
    """[S, n_flat, d] mesh-sharded slabs + host-side flat->segment maps."""

    def __init__(self, vectors, norms_sq, valid, n_flat: int,
                 seg_offsets: list[list[tuple[int, int, int]]],
                 allocation=None):
        self.vectors = vectors          # jnp [S, n_flat, d] on mesh
        self.norms_sq = norms_sq        # jnp [S, n_flat]
        self.valid = valid              # jnp [S, n_flat]
        self.n_flat = n_flat
        # per shard: [(flat_start, seg_idx, n_docs)] in segment order
        self.seg_offsets = seg_offsets
        # device-residency ledger handle; the ShardMeshRegistry frees it
        # on eviction/invalidation (and on a lost duplicate-build race)
        self.allocation = allocation

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in
                   (self.vectors, self.norms_sq, self.valid))

    def locate(self, shard_idx: int, flat: int) -> tuple[int, int]:
        for start, seg_idx, n_docs in self.seg_offsets[shard_idx]:
            if start <= flat < start + n_docs:
                return seg_idx, flat - start
        raise IndexError(f"flat doc {flat} out of range for shard {shard_idx}")


def _serving_mesh(n_devices: int) -> Mesh:
    mesh = _MESH_CACHE.get(n_devices)
    if mesh is None:
        grid = np.asarray(jax.devices()[:n_devices]).reshape(n_devices)
        mesh = Mesh(grid, (DATA_AXIS,))
        _MESH_CACHE[n_devices] = mesh
    return mesh


def _largest_divisor_at_most(s: int, cap: int) -> int:
    for d in range(min(s, cap), 0, -1):
        if s % d == 0:
            return d
    return 1


def _can_serve(snaps: list, field: str, *,
               filtered: bool = False) -> tuple[str, int] | None:
    """Returns (similarity, dims) if every shard can be served exactly,
    else None. ANN-indexed segments fall back on UNFILTERED queries: the
    host path would answer those with IVF-PQ, and this path must stay
    bit-identical to the host. With a filter, the host path itself runs an
    exact scan (executor.shard_knn_selection gates ANN on filter is None),
    so ANN segments are eligible here too."""
    from opensearch_tpu.ops.knn import canonical_similarity

    similarity = None
    dims = None
    any_field = False
    for snap in snaps:
        for host, dev in snap.segments:
            vf = dev.vector_fields.get(field)
            if vf is None:
                continue
            any_field = True
            if vf.ann is not None and not filtered:
                return None
            sim = canonical_similarity(vf.similarity)
            if similarity is None:
                similarity, dims = sim, vf.dims
            elif sim != similarity or vf.dims != dims:
                return None
    if not any_field:
        return None
    return similarity, dims


def _build_bundle(snaps: list, field: str, dims: int, mesh: Mesh,
                  index_name: str = "_unknown",
                  generations: tuple = ()) -> _IndexBundle:
    per_shard_vecs: list[np.ndarray] = []
    per_shard_norms: list[np.ndarray] = []
    per_shard_valid: list[np.ndarray] = []
    seg_offsets: list[list[tuple[int, int, int]]] = []
    for snap in snaps:
        chunks_v, chunks_n, chunks_ok = [], [], []
        offsets: list[tuple[int, int, int]] = []
        pos = 0
        for seg_idx, (host, dev) in enumerate(snap.segments):
            n = host.n_docs
            hvf = host.vector_fields.get(field)
            if hvf is None:
                chunks_v.append(np.zeros((n, dims), np.float32))
                chunks_n.append(np.zeros(n, np.float32))
                chunks_ok.append(np.zeros(n, bool))
            else:
                v = np.asarray(hvf.vectors[:n], np.float32)
                chunks_v.append(v)
                # identical norm formula to index/device.to_device so scores
                # match the host path bit-for-bit
                chunks_n.append(
                    (v.astype(np.float64) ** 2).sum(axis=1).astype(np.float32)
                )
                # dev.live, not host.live: deletes flip host.live in place
                # before refresh, but the host query path masks with the
                # PUBLISHED live bitmap (executor.py uses dev.live) — the
                # bundle must see exactly what the host path sees
                chunks_ok.append(
                    np.asarray(hvf.present[:n], bool)
                    & np.asarray(dev.live)[:n]
                )
            offsets.append((pos, seg_idx, n))
            pos += n
        seg_offsets.append(offsets)
        per_shard_vecs.append(
            np.concatenate(chunks_v) if chunks_v else np.zeros((0, dims), np.float32)
        )
        per_shard_norms.append(
            np.concatenate(chunks_n) if chunks_n else np.zeros(0, np.float32)
        )
        per_shard_valid.append(
            np.concatenate(chunks_ok) if chunks_ok else np.zeros(0, bool)
        )

    max_docs = max((v.shape[0] for v in per_shard_vecs), default=1)
    # bucket to the next power of two: keeps the compiled program stable
    # across refreshes that grow a shard slightly (query-shape cache,
    # SURVEY.md §7 hard part #3)
    n_flat = 1 << max(int(max_docs - 1).bit_length(), 3)

    def pad(a: np.ndarray, fill=0) -> np.ndarray:
        out = np.full((n_flat, *a.shape[1:]), fill, dtype=a.dtype)
        out[: a.shape[0]] = a
        return out

    vecs = np.stack([pad(v) for v in per_shard_vecs])
    norms = np.stack([pad(n) for n in per_shard_norms])
    valid = np.stack([pad(v, fill=False) for v in per_shard_valid])

    sharding = NamedSharding(mesh, P(DATA_AXIS))
    bundle = _IndexBundle(
        vectors=jax.device_put(jnp.asarray(vecs), NamedSharding(mesh, P(DATA_AXIS, None, None))),
        norms_sq=jax.device_put(jnp.asarray(norms), sharding),
        valid=jax.device_put(jnp.asarray(valid), sharding),
        n_flat=n_flat,
        seg_offsets=seg_offsets,
    )
    # HBM residency: the slab stays device-resident until the registry
    # evicts it (superseded generation, byte budget, invalidation)
    from opensearch_tpu.telemetry.device_ledger import (
        KIND_MESH_BUNDLE,
        default_ledger,
    )

    bundle.allocation = default_ledger.register(
        KIND_MESH_BUNDLE, bundle.nbytes, index=index_name, field=field,
        generation=tuple(generations),
        device=f"mesh[{len(mesh.devices.flat)}]",
    )
    return bundle


def _filter_valid_mask(
    shards: list,
    snaps: list,
    knn_filter,
    alias_filters: list | None,
    n_flat: int,
) -> np.ndarray:
    """[S, n_flat] bool: per-query-eligible docs under the knn-level filter
    and each shard's alias filter, laid out exactly like the bundle slabs
    (segment-ascending, doc-ascending, zero-padded). Runs the SAME
    SegmentExecutor the host path uses for the filter
    (executor.shard_knn_selection), so pre-filter semantics match."""
    from opensearch_tpu.search.executor import SegmentExecutor, ShardContext

    out = np.zeros((len(snaps), n_flat), bool)
    for si, (shard, snap) in enumerate(zip(shards, snaps)):
        fnodes = [f for f in (
            knn_filter, alias_filters[si] if alias_filters else None
        ) if f is not None]
        ctx = ShardContext(snap, shard.mapper_service)
        pos = 0
        for host, dev in snap.segments:
            n = host.n_docs
            m = np.ones(n, bool)
            for fnode in fnodes:
                ex = SegmentExecutor(ctx, host, dev)
                m &= np.asarray(ex.execute(fnode).mask)[:n]
            out[si, pos:pos + n] = m
            pos += n
    return out


def try_distributed_knn_batch(
    shards: list,
    snaps: list,
    nodes: list,
    fetch_k: int,
    alias_filters: list | None = None,
) -> list[list[ShardQueryResult]] | None:
    """Compatibility wrapper over :func:`mesh_knn_batch` returning only the
    per-query per-shard results (the msearch batching path)."""
    out = mesh_knn_batch(
        shards, snaps, nodes, fetch_k, alias_filters=alias_filters
    )
    return None if out is None else out.per_query


def mesh_knn_batch(
    shards: list,
    snaps: list,
    nodes: list,
    fetch_k: int,
    alias_filters: list | None = None,
) -> MeshLaunchOutcome | None:
    """Execute B KnnQuery nodes (same field/k/filter) in ONE device
    dispatch. Returns a MeshLaunchOutcome (per-query per-shard results,
    device-merged row order, launch attribution), or None when this path
    cannot reproduce the host result."""
    if not shards or len(shards) != len(snaps) or not nodes:
        return None
    s = len(shards)
    first = nodes[0]
    # batch members must share the device program and the filter mask;
    # filters are compared by identity (msearch groups by equal body JSON,
    # the single-query path always has B == 1)
    for node in nodes:
        if (node.field != first.field or int(node.k) != int(first.k)
                or node.filter is not first.filter):
            return None
    has_filter = first.filter is not None or (
        alias_filters is not None and any(f is not None for f in alias_filters)
    )
    served = _can_serve(snaps, first.field, filtered=has_filter)
    if served is None:
        _count("fallbacks")
        return None
    similarity, dims = served
    if any(len(node.vector) != dims for node in nodes):
        return None

    n_devices = _largest_divisor_at_most(s, len(jax.devices()))
    mesh = _serving_mesh(n_devices)

    index_name = shards[0].shard_id.index
    # generation-pinned residency key (ShardMeshRegistry.residency_key):
    # a refresh mid-flight is a different key, so no query is ever merged
    # against another snapshot's slab
    cache_key = registry.residency_key(index_name, first.field, shards, snaps)
    bundle = registry.get(cache_key)
    if bundle is None:
        # build OUTSIDE the registry lock: the device upload can take
        # seconds for a large index and must not stall warm-path queries of
        # other indexes. A same-key race (two cold misses) wastes one
        # duplicate upload at worst — registry.put keeps the cache itself
        # consistent, returns the winning bundle, and frees the loser's
        # ledger allocation.
        bundle = registry.put(
            cache_key,
            _build_bundle(snaps, first.field, dims, mesh,
                          index_name=index_name,
                          generations=cache_key[4]),
        )

    valid = bundle.valid
    if has_filter:
        fmask = _filter_valid_mask(
            shards, snaps, first.filter, alias_filters, bundle.n_flat
        )
        # per-request upload, consumed by this launch: transient in the
        # residency ledger (allocated and freed in one step)
        from opensearch_tpu.telemetry.device_ledger import (
            KIND_QUERY_BATCH,
            default_ledger,
        )

        default_ledger.record_transient(KIND_QUERY_BATCH, fmask.nbytes)
        valid = valid & jax.device_put(
            jnp.asarray(fmask), NamedSharding(mesh, P(DATA_AXIS))
        )

    b = len(nodes)
    # pad B to a power of two: B is a static shape under jit, so raw batch
    # sizes would compile one program per msearch width (query-shape cache,
    # SURVEY.md §7 hard part #3); padding queries are zero vectors whose
    # results are sliced off
    b_pad = 1 << (b - 1).bit_length()
    q_host = np.zeros((b_pad, dims), np.float32)
    for i, node in enumerate(nodes):
        q_host[i] = np.asarray(node.vector, np.float32)

    k_shard = max(1, min(int(first.k), bundle.n_flat))
    k_final = min(max(k_shard, int(fetch_k)), s * k_shard)
    # EXACT-path kernel policy (search.knn.kernel / score_precision): the
    # RESOLVED kernel + precision are part of the program key, so a live
    # flip compiles a fresh mesh program and never re-ranks a batch formed
    # under the old policy. The platform read happens ONCE per program
    # build (pallas off-TPU runs interpret-mode — the parity path).
    from opensearch_tpu.search.ann import (
        default_config as ann_config,
        resolve_kernel,
    )

    exact_kernel = resolve_kernel(ann_config.exact_kernel)
    score_precision = ann_config.score_precision
    fused = (exact_kernel, score_precision) != ("xla", "fp32")
    prog_key = (n_devices, s, bundle.n_flat, dims, k_shard, k_final,
                similarity, b_pad, exact_kernel, score_precision)
    with _CACHE_LOCK:
        program = _PROGRAM_CACHE.get(prog_key)
        retraced = program is None
        if program is None:
            interpret = (exact_kernel == "pallas"
                         and jax.devices()[0].platform != "tpu")
            program = build_knn_serving_step(
                mesh, k_shard=k_shard, k_final=k_final,
                similarity=similarity, kernel=exact_kernel,
                score_precision=score_precision, interpret=interpret,
            )
            _PROGRAM_CACHE[prog_key] = program

    queries = jnp.asarray(q_host)
    t0 = time.perf_counter_ns()
    with mesh:
        vals, gids, counts = program(
            bundle.vectors, bundle.norms_sq, valid, queries
        )
    # host materialization is the fence for this launch (block_until_ready
    # does not block on the tunnel backend — same recipe as bench.py)
    vals = np.asarray(vals)[:b]          # [b, k_final]
    gids = np.asarray(gids)[:b]
    counts = np.asarray(counts)[:, :b]   # [s, b]
    wall_ns = time.perf_counter_ns() - t0
    launch_id = registry.next_launch_id()
    registry.record_launch_wall(wall_ns)
    registry.record_launch_kernel(exact_kernel, score_precision)
    # roofline accounting: ONE sharded launch against the mesh cost model
    # (per-slot scan + on-device all_gather/top_k merge)
    from opensearch_tpu.telemetry import roofline

    launch_params = dict(b=b_pad, s=s, n_flat=bundle.n_flat, d=dims,
                         k_shard=k_shard, devices=n_devices)
    if fused:
        from opensearch_tpu.ops.pallas_knn import fused_pool_width

        launch_params.update(
            precision=score_precision,
            r=fused_pool_width(k_shard, score_precision),
            kernel=exact_kernel,
        )
        mesh_family = "mesh_knn_fused"
        roofline.record_launch(
            f"mesh_knn_fused[{score_precision}]", wall_ns, **launch_params)
    else:
        mesh_family = "mesh_knn"
        roofline.record_launch("mesh_knn", wall_ns, **launch_params)
    from opensearch_tpu.telemetry.device_ledger import (
        KIND_QUERY_BATCH,
        default_ledger,
    )

    default_ledger.record_transient(KIND_QUERY_BATCH, q_host.nbytes)
    # heat touch against the mesh bundle this launch scanned, bytes from
    # the same cost model the roofline fold used (telemetry/device_ledger)
    default_ledger.touch([getattr(bundle, "allocation", None)],
                         family=mesh_family, params=launch_params)
    if retraced:
        # program-cache miss == fresh jit entry for the mesh kernel family;
        # the first launch wall includes the compile
        default_ledger.record_compile(mesh_family, wall_ns)
    _count("distributed_searches")
    if has_filter:
        _count("filtered")
    if s == 1:
        _count("single_shard")
    if b > 1:
        _count("batched_queries", b)

    out: list[list[ShardQueryResult]] = []
    premerged: list[list[tuple[int, ShardHit]]] = []
    for qi, node in enumerate(nodes):
        boost = np.float32(getattr(node, "boost", 1.0))
        per_shard_hits: list[list[ShardHit]] = [[] for _ in range(s)]
        # device row order IS the final merged order: (-score, shard asc,
        # segment asc, doc asc) — see build_knn_serving_step's tie-break
        rows: list[tuple[int, ShardHit]] = []
        for v, g in zip(vals[qi], gids[qi]):
            if not np.isfinite(v):
                continue
            shard_idx, flat = int(g) // bundle.n_flat, int(g) % bundle.n_flat
            seg_idx, doc = bundle.locate(shard_idx, flat)
            hit = ShardHit(float(np.float32(v) * boost), seg_idx, doc)
            per_shard_hits[shard_idx].append(hit)
            rows.append((shard_idx, hit))
        results = []
        for shard_idx in range(s):
            hits = per_shard_hits[shard_idx]
            results.append(ShardQueryResult(
                hits=hits,
                total=int(counts[shard_idx, qi]),
                max_score=max((h.score for h in hits), default=None),
            ))
        out.append(results)
        premerged.append(rows)
    return MeshLaunchOutcome(out, premerged, launch_id, wall_ns, retraced, s)


def clear_caches() -> None:
    registry.clear()
    _PROGRAM_CACHE.clear()
    _MESH_CACHE.clear()
