"""Aggregations: per-segment partials + reduce.

The analog of the reference's two-tier aggregation compute
(search/aggregations/: per-shard Aggregator collectors produce
InternalAggregation partials; InternalAggregations.reduce:162 merges them on
the coordinator). Here: per-segment numpy partials over exact host columns
(int64/float64 — no float32 truncation of dates/longs), restricted by the
query-phase match masks, merged shard-side; the same merge functions serve
the cross-shard reduce in the coordinator layer.

Implemented: terms (keyword/numeric/boolean), min, max, sum, avg,
value_count, stats, cardinality (exact), histogram, date_histogram
(fixed + calendar month/quarter/year), range, filter, filters, missing,
global — all with arbitrarily nested sub-aggregations.

Device offload note: the masks arrive from the device query phase; the
bucket/metric math here is host numpy for exactness. The hot aggregations
(terms on keyword ords = bincount, stats = masked reductions) have direct
device formulations planned in ops/ for the large-corpus path.
"""

from __future__ import annotations

import datetime as _dt
import logging
import time
from typing import Any, Callable

import numpy as np

from opensearch_tpu.common.errors import IllegalArgumentException, ParsingException
from opensearch_tpu.index.mapper import MapperService, parse_date_millis
from opensearch_tpu.index.segment import HostSegment
from opensearch_tpu.common.settings import parse_time_millis

logger = logging.getLogger(__name__)

AGG_TYPES = {
    "terms", "min", "max", "sum", "avg", "value_count", "stats", "cardinality",
    "histogram", "date_histogram", "range", "filter", "filters", "missing", "global",
    "nested", "reverse_nested",
}

# extension registry populated by aggs_ext (extended metric/bucket families);
# fn(conf, sub, segments, ms, masks, filter_fn, ext)
EXTENSION_AGGS: dict[str, Callable] = {}

# the reference's search.max_buckets MultiBucketConsumerService limit
MAX_BUCKETS = 65_536

# cross-node exact-merge cap for value-shipping partials (cardinality,
# percentiles) — beyond this the wire cost of exactness is unreasonable and
# a sketch (HLL++/TDigest) is the right tool
MAX_PARTIAL_VALUES = 100_000


class TooManyBucketsException(IllegalArgumentException):
    error_type = "too_many_buckets_exception"

    def __init__(self, limit: int):
        super().__init__(
            f"Trying to create too many buckets. Must be less than or equal "
            f"to: [{limit}]."
        )

# executor callback: (query_node_body, segment_index) -> bool mask [n_docs]
FilterFn = Callable[[dict, int], np.ndarray]


def compute_aggs(
    segments: list[HostSegment],
    mapper_service: MapperService,
    aggs_body: dict,
    masks: list[np.ndarray],
    filter_fn: FilterFn | None = None,
    ext: dict | None = None,
) -> dict:
    from opensearch_tpu.search.aggs_pipeline import PIPELINE_TYPES
    from opensearch_tpu.search import profile as search_profile

    prof = search_profile.active()
    out = {}
    for name, body in aggs_body.items():
        # pipeline aggs run at final reduce (aggs_pipeline.apply_pipeline_aggs),
        # mirroring the reference where they reduce coordinator-side
        if any(k in PIPELINE_TYPES for k in body):
            continue
        t0 = time.perf_counter_ns() if prof is not None else 0
        out[name] = _compute_one(
            name, body, segments, mapper_service, masks, filter_fn, ext
        )
        if prof is not None:
            # real per-aggregation collector wall time for the profile
            # response (replaces the emulated constants)
            prof.record_agg(name, time.perf_counter_ns() - t0)
    return out


def _split_body(body: dict) -> tuple[str, dict, dict | None]:
    sub = body.get("aggs") or body.get("aggregations")
    agg_keys = [k for k in body if k in AGG_TYPES or k in EXTENSION_AGGS]
    if len(agg_keys) != 1:
        raise ParsingException(
            f"aggregation must have exactly one known type, got {sorted(body)}"
        )
    return agg_keys[0], body[agg_keys[0]], sub


def _column(seg: HostSegment, field: str, ms: MapperService | None):
    """(values, present) for a numeric column. unsigned_long is STORED
    biased by -2^63 so the int64 column keeps 64-bit order (mapper.py);
    every aggregation read must unbias back to uint64 here — raw biased
    values surface as huge negatives (the r4 full-suite sweep's largest
    failure cluster)."""
    nf = seg.numeric_fields.get(field)
    if nf is None:
        return None, None
    vals = nf.values_i64 if nf.kind == "int" else nf.values_f64
    if nf.kind == "int" and ms is not None:
        mapper = ms.field_mapper(field) if hasattr(ms, "field_mapper") else None
        if getattr(mapper, "original_type", None) == "unsigned_long":
            vals = vals.view(np.uint64) + np.uint64(1 << 63)
    return vals, nf.present


def _field_values(
    seg: HostSegment, field: str, mask: np.ndarray, mapper_service: MapperService
) -> np.ndarray:
    """Masked exact values of a numeric-ish field (int64/float64/uint64)."""
    vals, present = _column(seg, field, mapper_service)
    if vals is not None:
        return vals[mask & present]
    return np.zeros(0)


def _compute_one(
    name: str,
    body: dict,
    segments: list[HostSegment],
    ms: MapperService,
    masks: list[np.ndarray],
    filter_fn: FilterFn | None,
    ext: dict | None = None,
) -> dict:
    typ, conf, sub = _split_body(body)
    # parameter-validation errors quote the aggregation name
    ext = dict(ext) if ext else {}
    ext["agg_name"] = name
    out = _dispatch_one(typ, conf, sub, segments, ms, masks, filter_fn, ext)
    # meta echoes back verbatim on every aggregation response
    # (InternalAggregation.getMetadata)
    meta = body.get("meta")
    if meta is not None and isinstance(out, dict):
        out["meta"] = meta
    return out


def _dispatch_one(
    typ: str,
    conf: dict,
    sub: dict | None,
    segments: list[HostSegment],
    ms: MapperService,
    masks: list[np.ndarray],
    filter_fn: FilterFn | None,
    ext: dict | None = None,
) -> dict:
    if typ in ("min", "max", "sum", "avg", "value_count", "stats"):
        return _metric(typ, conf, segments, ms, masks, ext)
    if typ == "cardinality":
        return _cardinality(conf, segments, ms, masks, ext)
    if typ == "terms":
        return _terms(conf, sub, segments, ms, masks, filter_fn, ext)
    if typ == "histogram":
        return _histogram(conf, sub, segments, ms, masks, filter_fn, ext, date=False)
    if typ == "date_histogram":
        return _histogram(conf, sub, segments, ms, masks, filter_fn, ext, date=True)
    if typ == "range":
        return _range_agg(conf, sub, segments, ms, masks, filter_fn, ext)
    if typ == "filter":
        return _filter_agg(conf, sub, segments, ms, masks, filter_fn, ext)
    if typ == "filters":
        return _filters_agg(conf, sub, segments, ms, masks, filter_fn, ext)
    if typ == "missing":
        return _missing_agg(conf, sub, segments, ms, masks, filter_fn, ext)
    if typ == "nested":
        return _nested_agg(conf, sub, segments, ms, masks, filter_fn, ext)
    if typ == "reverse_nested":
        return _reverse_nested_agg(conf, sub, segments, ms, masks,
                                   filter_fn, ext)
    if typ == "global":
        g_masks = [s.live.copy() for s in segments]
        out = {"doc_count": int(sum(m.sum() for m in g_masks))}
        if sub:
            out.update(compute_aggs(segments, ms, sub, g_masks, filter_fn, ext))
        return out
    fn = EXTENSION_AGGS.get(typ)
    if fn is not None:
        return fn(conf, sub, segments, ms, masks, filter_fn, ext or {})
    raise ParsingException(f"unknown aggregation type [{typ}]")


def _sub_aggs(
    sub: dict | None,
    segments: list[HostSegment],
    ms: MapperService,
    bucket_masks: list[np.ndarray],
    filter_fn: FilterFn | None,
    ext: dict | None = None,
) -> dict:
    if not sub:
        return {}
    return compute_aggs(segments, ms, sub, bucket_masks, filter_fn, ext)


# -- metrics ----------------------------------------------------------------


def _metric(typ, conf, segments, ms, masks, ext=None) -> dict:
    field = conf.get("field")
    if field is None:
        raise IllegalArgumentException(
            f"Required one of fields [field, script], but none were "
            f"specified. [{(ext or {}).get('agg_name', typ)}]")
    chunks = [
        _field_values(seg, field, masks[i], ms) for i, seg in enumerate(segments)
    ]
    vals = np.concatenate(chunks) if chunks else np.zeros(0)
    mapper = ms.field_mapper(field)
    is_date = mapper is not None and mapper.type == "date"
    # numeric-only metric aggs over non-numeric columns 400 in the
    # reference (ValuesSourceConfig type resolution); value_count counts
    # values of ANY type
    if typ != "value_count" and mapper is not None and \
            mapper.type in ("text", "keyword") and \
            not any(seg.numeric_fields.get(field) is not None
                    for seg in segments):
        raise IllegalArgumentException(
            f"Field [{field}] of type [{mapper.original_type or mapper.type}]"
            f" is not supported for aggregation [{typ}]"
        )
    if typ == "value_count" and mapper is not None and \
            mapper.type in ("text", "keyword"):
        count = 0
        for i, seg in enumerate(segments):
            kf = seg.keyword_fields.get(field)
            if kf is not None:
                count += int(masks[i][kf.mv_docs].sum())
                continue
            tf = seg.text_fields.get(field)
            if tf is not None:
                pres = getattr(tf, "present", None)
                if pres is not None:
                    count += int((masks[i] & pres).sum())
        return {"value": count}
    # `missing` substitutes a value for every in-bucket doc without one
    # (ValuesSourceConfig.missing)
    missing_val = conf.get("missing")
    if missing_val is not None:
        n_miss = 0
        for i, seg in enumerate(segments):
            nf = seg.numeric_fields.get(field)
            pres = nf.present if nf is not None \
                else np.zeros(seg.n_docs, bool)
            n_miss += int((masks[i] & ~pres).sum())
        if n_miss:
            if is_date and isinstance(missing_val, str):
                mv = float(parse_date_millis(missing_val))
            else:
                mv = float(missing_val)
            vals = np.concatenate(
                [vals.astype(np.float64), np.full(n_miss, mv)])
    n = len(vals)
    # cross-node partial mode (InternalAvg carries sum+count on the wire;
    # the coordinator reduce divides — search/reduce.py strips the key)
    partial = bool(ext and ext.get("partial"))

    def fmt(v):
        if v is None:
            return None
        return float(v)

    if typ == "value_count":
        return {"value": n}
    if n == 0:
        if typ == "stats":
            return {"count": 0, "min": None, "max": None, "avg": None, "sum": 0.0}
        out = {"value": None if typ != "sum" else 0.0}
        if typ == "avg" and partial:
            out["_p_count"] = 0
            out["_p_sum"] = 0.0
        return out
    s = float(vals.sum(dtype=np.float64))
    if typ == "min":
        return {"value": fmt(vals.min())}
    if typ == "max":
        return {"value": fmt(vals.max())}
    if typ == "sum":
        return {"value": s}
    if typ == "avg":
        out = {"value": s / n}
        if partial:
            out["_p_count"] = n
            out["_p_sum"] = s
        return out
    return {
        "count": n,
        "min": fmt(vals.min()),
        "max": fmt(vals.max()),
        "avg": s / n,
        "sum": s,
    }


def _cardinality(conf, segments, ms, masks, ext=None) -> dict:
    field = conf["field"]
    pt = conf.get("precision_threshold")
    if pt is not None and int(pt) < 0:
        name = (ext or {}).get("agg_name", "cardinality")
        raise IllegalArgumentException(
            f"[precisionThreshold] must be greater than or equal to 0. "
            f"Found [{int(pt)}] in [{name}]")
    # exact distinct count (the reference uses HLL++ with precision_threshold;
    # HLL sketch merge is the planned device path for large corpora)
    seen: set = set()
    missing_val = conf.get("missing")
    for i, seg in enumerate(segments):
        kf = seg.keyword_fields.get(field)
        if kf is not None:
            m = masks[i]
            entry_mask = m[kf.mv_docs]
            for o in np.unique(kf.mv_ords[entry_mask]):
                seen.add(kf.ord_values[int(o)])
            if missing_val is not None and bool(
                    (m & ~(kf.first_ord >= 0)).any()):
                seen.add(missing_val)
            continue
        vals = _field_values(seg, field, masks[i], ms)
        seen.update(vals.tolist())
        if missing_val is not None:
            nf = seg.numeric_fields.get(field)
            pres = nf.present if nf is not None \
                else np.zeros(seg.n_docs, bool)
            if bool((masks[i] & ~pres).any()):
                seen.add(missing_val)
    out: dict[str, Any] = {"value": len(seen)}
    if ext and ext.get("partial"):
        # wire partial: the distinct-value set itself (exact; the reference
        # ships HLL++ sketches — sketch merge is the large-corpus path)
        if len(seen) > MAX_PARTIAL_VALUES:
            raise IllegalArgumentException(
                f"cardinality over [{len(seen)}] distinct values exceeds the "
                f"cross-node exact-merge cap [{MAX_PARTIAL_VALUES}]"
            )
        out["_p_values"] = sorted(seen, key=lambda v: (str(type(v)), v))
    return out


# -- terms ------------------------------------------------------------------


def _terms(conf, sub, segments, ms, masks, filter_fn, ext=None) -> dict:
    field = conf["field"]
    size = int(conf.get("size", 10))
    min_doc_count = int(conf.get("min_doc_count", 1))
    if ext and ext.get("partial"):
        # per-node over-fetch so the coordinator cut is accurate — the
        # reference's shard_size default (size * 1.5 + 10)
        size = int(conf.get("shard_size", size + (size >> 1) + 10))
    # merge per-segment counts keyed by value
    counts: dict[Any, int] = {}
    is_keyword = any(field in seg.keyword_fields for seg in segments)
    for i, seg in enumerate(segments):
        kf = seg.keyword_fields.get(field)
        if kf is not None:
            entry_mask = masks[i][kf.mv_docs]
            seg_counts = np.bincount(
                kf.mv_ords[entry_mask], minlength=len(kf.ord_values)
            )
            for o in np.nonzero(seg_counts)[0]:
                key = kf.ord_values[int(o)]
                counts[key] = counts.get(key, 0) + int(seg_counts[o])
        else:
            vals = _field_values(seg, field, masks[i], ms)
            uniq, c = np.unique(vals, return_counts=True)
            for v, n in zip(uniq.tolist(), c.tolist()):
                counts[v] = counts.get(v, 0) + n

    mapper = ms.field_mapper(field)
    vt = conf.get("value_type")
    is_bool = (mapper is not None and mapper.type == "boolean") \
        or vt == "boolean"
    is_date = (mapper is not None and mapper.type == "date") or vt == "date"

    # `missing`: docs in the bucket without a value count under the
    # substitute key (also the whole story for unmapped fields, where
    # `value_type` declares the key's rendering); the per-segment missing
    # masks stick around so the bucket's sub-aggs run over those docs
    missing_conf = conf.get("missing")
    missing_key = None
    missing_masks: list[np.ndarray] | None = None
    if missing_conf is not None:
        missing_masks = []
        for i, seg in enumerate(segments):
            kf = seg.keyword_fields.get(field)
            nf = seg.numeric_fields.get(field)
            if kf is not None:
                present = np.zeros(seg.n_docs, bool)
                present[kf.mv_docs] = True
            elif nf is not None:
                present = nf.present[:seg.n_docs]
            else:
                present = np.zeros(seg.n_docs, bool)
            missing_masks.append(masks[i] & ~present)
        n_missing = int(sum(m.sum() for m in missing_masks))
        if n_missing:
            key = missing_conf
            if is_bool or isinstance(key, bool):
                key = 1.0 if key in (True, "true", 1) else 0.0
            elif is_date and isinstance(key, str):
                key = float(parse_date_millis(key))
            elif isinstance(key, (int, float)):
                key = float(key)
            missing_key = key
            counts[key] = counts.get(key, 0) + n_missing

    # include/exclude: exact lists, regex strings, or the partition form
    # (IncludeExclude; partitions hash the term like the reference so a
    # term lands in exactly one partition)

    def _match_key(k) -> str:
        # date terms include/exclude by formatted value; compare in ms;
        # integral doubles canonicalize like the partition hash does
        if is_date and not isinstance(k, str):
            return str(int(k))
        if isinstance(k, float) and k.is_integer():
            return str(int(k))
        return str(k)

    def _spec_values(vals) -> set:
        if is_date:
            return {str(parse_date_millis(v)) for v in vals}
        return {str(v) for v in vals}

    include = conf.get("include")
    exclude = conf.get("exclude")
    if isinstance(include, dict):
        num = int(include.get("num_partitions", 1))
        part = int(include.get("partition", 0))
        from opensearch_tpu.common.hashing import murmur3_x86_32

        def _pkey(k) -> str:
            # numeric terms hash their canonical long/double string form
            if isinstance(k, float) and k.is_integer():
                return str(int(k))
            return str(k)

        # seed 31 = IncludeExclude.HASH_PARTITIONING_SEED; floorMod over
        # the SIGNED 32-bit hash, matching the reference exactly
        def _part(k) -> int:
            h = murmur3_x86_32(_pkey(k).encode(), seed=31)
            if h >= 1 << 31:
                h -= 1 << 32
            return h % num

        counts = {k: c for k, c in counts.items() if _part(k) == part}
    elif isinstance(include, list):
        want = _spec_values(include)
        counts = {k: c for k, c in counts.items() if _match_key(k) in want}
    elif isinstance(include, str):
        import re as _re

        rx = _re.compile(include)
        counts = {k: c for k, c in counts.items()
                  if rx.fullmatch(str(k))}
    if isinstance(exclude, list):
        drop = _spec_values(exclude)
        counts = {k: c for k, c in counts.items()
                  if _match_key(k) not in drop}
    elif isinstance(exclude, str):
        import re as _re

        rx = _re.compile(exclude)
        counts = {k: c for k, c in counts.items()
                  if not rx.fullmatch(str(k))}
    if min_doc_count > 0:
        counts = {k: c for k, c in counts.items() if c >= min_doc_count}
    # order: {"_count": "desc"} | {"_key": "asc"} | {"<sub-agg-path>": dir},
    # or a list of such single-entry dicts (multi-criteria)
    order_conf = conf.get("order", {"_count": "desc"})
    if isinstance(order_conf, dict):
        order_specs = list(order_conf.items())
    elif isinstance(order_conf, list):
        order_specs = [next(iter(o.items())) for o in order_conf]
    else:
        raise ParsingException(f"invalid terms order [{order_conf!r}]")
    needs_sub_order = any(k not in ("_count", "_key") for k, _ in order_specs)

    def _bucket_masks_for(key) -> list[np.ndarray]:
        bm = _value_masks(segments, field, key, masks, ms)
        if missing_key is not None and key == missing_key:
            # the missing bucket's sub-aggs cover the value-less docs too
            bm = [b | mm for b, mm in zip(bm, missing_masks)]
        return bm

    # compute sub-aggs per bucket up-front when ordering needs them (or
    # lazily after the cut otherwise)
    sub_results: dict[Any, dict] = {}
    if sub and needs_sub_order:
        for key in counts:
            sub_results[key] = _sub_aggs(
                sub, segments, ms, _bucket_masks_for(key), filter_fn, ext)

    def _agg_path_value(key: Any, path: str) -> Any:
        name, _, prop = path.partition(".")
        result = sub_results.get(key, {}).get(name)
        if result is None:
            raise ParsingException(f"terms order references unknown agg [{path}]")
        v = result.get(prop or "value")
        return v if v is not None else float("-inf")

    def sort_key(kv):
        key, count = kv
        parts = []
        for okey, odir in order_specs:
            desc = odir == "desc"
            if okey == "_count":
                parts.append(-count if desc else count)
            elif okey == "_key":
                parts.append(_KeyOrd(key, desc))
            else:
                v = _agg_path_value(key, okey)
                parts.append(-v if desc else v)
        parts.append(_KeyOrd(key, False))  # stable tiebreak: key asc
        return tuple(parts)

    items = sorted(counts.items(), key=sort_key)
    top = items[:size]
    other = sum(c for _, c in items[size:])

    buckets = []
    for key, count in top:
        bucket: dict[str, Any] = {}
        if is_bool:
            bucket["key"] = int(key)
            bucket["key_as_string"] = "true" if key else "false"
        elif is_date and not isinstance(key, str):
            bucket["key"] = int(key)
            bucket["key_as_string"] = _iso_ms(int(key))
        elif isinstance(key, str):
            bucket["key"] = key
        else:
            bucket["key"] = int(key) if float(key).is_integer() and not is_keyword else key
        bucket["doc_count"] = count
        if sub:
            if key in sub_results:
                bucket.update(sub_results[key])
            else:
                bucket.update(_sub_aggs(
                    sub, segments, ms, _bucket_masks_for(key), filter_fn,
                    ext))
        buckets.append(bucket)
    return {
        "doc_count_error_upper_bound": 0,
        "sum_other_doc_count": other,
        "buckets": buckets,
    }


def _iso_ms(ms_val: int) -> str:
    """Epoch ms -> "2016-05-03T00:00:00.000Z" (the date formatter the
    reference renders bucket key_as_string with)."""
    import datetime as _dt

    kdt = _dt.datetime.fromtimestamp(ms_val / 1000, _dt.timezone.utc)
    return kdt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{ms_val % 1000:03d}Z"


class _KeyOrd:
    """Orderable wrapper for bucket keys (str or numeric) with direction."""

    __slots__ = ("v", "desc")

    def __init__(self, v, desc: bool):
        self.v = v
        self.desc = desc

    def __lt__(self, other: "_KeyOrd") -> bool:
        return (self.v > other.v) if self.desc else (self.v < other.v)

    def __eq__(self, other) -> bool:
        return isinstance(other, _KeyOrd) and self.v == other.v


def _value_masks(segments, field, key, masks,
                 ms=None) -> list[np.ndarray]:
    out = []
    for i, seg in enumerate(segments):
        kf = seg.keyword_fields.get(field)
        if kf is not None:
            o = kf.ord_dict.get(key if isinstance(key, str) else str(key))
            m = np.zeros(seg.n_docs, bool)
            if o is not None:
                hit_docs = kf.mv_docs[kf.mv_ords == o]
                m[hit_docs] = True
            out.append(masks[i] & m)
            continue
        vals, present = _column(seg, field, ms)
        if vals is not None:
            out.append(masks[i] & present & (vals == key))
        else:
            out.append(np.zeros(seg.n_docs, bool))
    return out


# -- histogram --------------------------------------------------------------

_CALENDAR_UNITS = {"month", "1M", "quarter", "1q", "year", "1y",
                   "week", "1w"}
# calendar word units with fixed duration: translate to fixed-interval form
_CALENDAR_FIXED = {"second": "1s", "minute": "1m", "hour": "1h", "day": "1d"}


def _histogram(conf, sub, segments, ms, masks, filter_fn, ext=None, date: bool = False) -> dict:
    field = conf["field"]
    if date:
        interval_conf = (
            conf.get("fixed_interval") or conf.get("calendar_interval")
            or conf.get("interval")
        )
        if interval_conf is None:
            raise ParsingException("date_histogram requires an interval")
        # calendar word units of fixed duration ("day", "hour", ...)
        # translate to the fixed-interval form
        interval_conf = _CALENDAR_FIXED.get(str(interval_conf),
                                            interval_conf)
        calendar = str(interval_conf) in _CALENDAR_UNITS or conf.get("calendar_interval") in _CALENDAR_UNITS
    else:
        interval_conf = conf["interval"]
        calendar = False
    raw_offset = conf.get("offset", 0)
    # date offsets come as duration strings ("6h", "-1d"); numeric histograms
    # take plain numbers
    offset = float(parse_time_millis(raw_offset)) if date else float(raw_offset)
    min_doc_count = int(conf.get("min_doc_count", 1 if not date else 0))
    interval = None
    if not calendar:
        interval = parse_time_millis(interval_conf) if date else float(interval_conf)

    hard = conf.get("hard_bounds") or None
    if hard is not None and date:
        hard = {k: parse_date_millis(v) for k, v in hard.items()}

    # RANGE-typed fields: each doc's [lo, hi] interval counts in EVERY
    # bucket it intersects (RangeHistogramAggregator); hard_bounds clamps
    # the enumerated span
    field_mapper = ms.field_mapper(field) if hasattr(ms, "field_mapper") \
        else None
    from opensearch_tpu.index.mapper import RANGE_TYPES as _RT

    if field_mapper is not None and field_mapper.type in _RT:
        return _histogram_over_ranges(
            conf, sub, segments, ms, masks, filter_fn, ext,
            field=field, date=date, calendar=calendar,
            interval_conf=interval_conf, interval=interval, offset=offset,
            hard=hard)

    # collect (key -> count) and per-key masks lazily for sub-aggs
    key_counts: dict[float, int] = {}
    per_seg_keys: list[np.ndarray] = []   # bucket key per masked doc
    per_seg_docs: list[np.ndarray] = []
    for i, seg in enumerate(segments):
        col, present = _column(seg, field, ms)
        if col is None:
            per_seg_keys.append(np.zeros(0))
            per_seg_docs.append(np.zeros(0, np.int64))
            continue
        m = masks[i] & present
        docs = np.nonzero(m)[0]
        vals = col[docs]
        if date:
            mapper = ms.field_mapper(field) if hasattr(ms, "field_mapper") else None
            if mapper is not None and \
                    getattr(mapper, "resolution", "millis") == "nanos":
                # bucket date_nanos in MILLIS space like the reference
                # (nanos keys would explode the bucket count)
                vals = vals // 1_000_000
        if calendar:
            keys = _calendar_keys(vals, str(interval_conf))
        else:
            keys = np.floor((vals.astype(np.float64) - offset) / interval) * interval + offset
        per_seg_keys.append(keys)
        per_seg_docs.append(docs)
        uniq, c = np.unique(keys, return_counts=True)
        for k_, n_ in zip(uniq.tolist(), c.tolist()):
            key_counts[k_] = key_counts.get(k_, 0) + n_

    # empty-bucket fill: min_doc_count=0 emits every bucket between the
    # observed (or extended_bounds) min and max key, like the reference's
    # InternalHistogram.addEmptyBuckets at reduce time
    if min_doc_count == 0:
        eb = conf.get("extended_bounds") or {}
        eb_min = eb.get("min")
        eb_max = eb.get("max")
        if date:
            eb_min = parse_date_millis(eb_min) if eb_min is not None else None
            eb_max = parse_date_millis(eb_max) if eb_max is not None else None

        def _floor_key(v: float) -> float:
            if calendar:
                return float(_calendar_keys(np.asarray([v]), str(interval_conf))[0])
            return float(np.floor((v - offset) / interval) * interval + offset)

        lo = min(key_counts) if key_counts else None
        hi = max(key_counts) if key_counts else None
        if eb_min is not None:
            lo = _floor_key(eb_min) if lo is None else min(lo, _floor_key(eb_min))
        if eb_max is not None:
            hi = _floor_key(eb_max) if hi is None else max(hi, _floor_key(eb_max))
        if lo is not None and hi is not None:
            if calendar:
                unit = str(interval_conf)
                k = lo
                n_fill = 0
                while k <= hi:
                    key_counts.setdefault(k, 0)
                    k = _calendar_next(k, unit)
                    n_fill += 1
                    if n_fill > MAX_BUCKETS:
                        raise TooManyBucketsException(MAX_BUCKETS)
            else:
                # integer bucket ordinals so fill keys are bit-identical to
                # the floor-computed doc keys (no arange accumulation drift)
                n0 = int(round((lo - offset) / interval))
                n1 = int(round((hi - offset) / interval))
                if n1 - n0 + 1 > MAX_BUCKETS:
                    raise TooManyBucketsException(MAX_BUCKETS)
                for k in (np.arange(n0, n1 + 1) * interval + offset).tolist():
                    key_counts.setdefault(k, 0)
    if len(key_counts) > MAX_BUCKETS:
        raise TooManyBucketsException(MAX_BUCKETS)

    buckets = []
    for key in sorted(key_counts):
        count = key_counts[key]
        if count < min_doc_count:
            continue
        bucket: dict[str, Any] = {"key": int(key) if date else key, "doc_count": count}
        if date:
            kdt = _dt.datetime.fromtimestamp(key / 1000, _dt.timezone.utc)
            bucket["key_as_string"] = (
                kdt.strftime("%Y-%m-%dT%H:%M:%S.")
                + f"{int(key) % 1000:03d}Z"
            )
        if sub:
            bucket_masks = []
            for i, seg in enumerate(segments):
                bm = np.zeros(seg.n_docs, bool)
                sel = per_seg_docs[i][per_seg_keys[i] == key]
                bm[sel] = True
                bucket_masks.append(bm)
            bucket.update(_sub_aggs(sub, segments, ms, bucket_masks, filter_fn, ext))
        buckets.append(bucket)
    return {"buckets": buckets}


def _calendar_next(key_ms: float, unit: str) -> float:
    if unit in ("week", "1w"):
        return key_ms + 7 * 86_400_000
    dt = _dt.datetime.fromtimestamp(key_ms / 1000, _dt.timezone.utc)
    months = {"month": 1, "1M": 1, "quarter": 3, "1q": 3}.get(unit, 12)
    month0 = dt.month - 1 + months
    nxt = dt.replace(year=dt.year + month0 // 12, month=month0 % 12 + 1)
    return nxt.timestamp() * 1000


def _histogram_over_ranges(conf, sub, segments, ms, masks, filter_fn, ext,
                           *, field, date, calendar, interval_conf,
                           interval, offset, hard) -> dict:
    """(date_)histogram over a RANGE field: the doc's stored [lo, hi]
    interval contributes to every bucket it intersects
    (RangeHistogramAggregator); hard_bounds clamps the span AND enumerates
    every bucket inside the bounds (empties included)."""
    lo_f, hi_f = f"{field}#lo", f"{field}#hi"
    hmin = hard.get("min") if hard else None
    hmax = hard.get("max") if hard else None

    def bucket_of(v: float) -> float:
        if calendar:
            return float(_calendar_keys(np.asarray([v]),
                                        str(interval_conf))[0])
        return float(np.floor((v - offset) / interval) * interval + offset)

    def next_key(k: float) -> float:
        if calendar:
            return _calendar_next(k, str(interval_conf))
        return k + interval

    key_counts: dict[float, int] = {}
    doc_lists: dict[float, list] = {}
    for i, seg in enumerate(segments):
        lo_nf = seg.numeric_fields.get(lo_f)
        hi_nf = seg.numeric_fields.get(hi_f)
        if lo_nf is None or hi_nf is None:
            continue
        lo_vals = lo_nf.values_i64 if lo_nf.kind == "int" else lo_nf.values_f64
        hi_vals = hi_nf.values_i64 if hi_nf.kind == "int" else hi_nf.values_f64
        m = masks[i] & lo_nf.present[:seg.n_docs]
        for d in np.nonzero(m)[0].tolist():
            lo, hi = float(lo_vals[d]), float(hi_vals[d])
            if hmin is not None:
                lo = max(lo, float(hmin))
            if hmax is not None:
                hi = min(hi, float(hmax))
            if hi < lo:
                continue
            if hmin is None and hmax is None and (hi - lo) > 1e15:
                # unbounded side without hard_bounds would enumerate the
                # whole int64 domain; clamp like the reference refuses
                raise IllegalArgumentException(
                    f"[{field}] range is unbounded; set [hard_bounds] on "
                    f"the histogram")
            k = bucket_of(lo)
            n_keys = 0
            while k <= hi:
                key_counts[k] = key_counts.get(k, 0) + 1
                doc_lists.setdefault(k, []).append((i, d))
                k = next_key(k)
                n_keys += 1
                if n_keys > MAX_BUCKETS:
                    raise TooManyBucketsException(MAX_BUCKETS)
    # hard bounds enumerate the FULL bounded span, empties included
    if hmin is not None and hmax is not None:
        k = bucket_of(float(hmin))
        n_keys = 0
        while k <= float(hmax):
            key_counts.setdefault(k, 0)
            k = next_key(k)
            n_keys += 1
            if n_keys > MAX_BUCKETS:
                raise TooManyBucketsException(MAX_BUCKETS)

    buckets = []
    for key in sorted(key_counts):
        bucket: dict[str, Any] = {
            "key": int(key) if date else key,
            "doc_count": key_counts[key],
        }
        if date:
            bucket["key_as_string"] = _iso_ms(int(key))
        if sub:
            bucket_masks = [np.zeros(s.n_docs, bool) for s in segments]
            for i, d in doc_lists.get(key, []):
                bucket_masks[i][d] = True
            bucket.update(_sub_aggs(sub, segments, ms, bucket_masks,
                                    filter_fn, ext))
        buckets.append(bucket)
    return {"buckets": buckets}


def _calendar_keys(vals_ms: np.ndarray, unit: str) -> np.ndarray:
    out = np.empty(len(vals_ms), np.float64)
    for i, v in enumerate(vals_ms):
        dt = _dt.datetime.fromtimestamp(float(v) / 1000, _dt.timezone.utc)
        if unit in ("month", "1M"):
            key_dt = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        elif unit in ("week", "1w"):
            # ISO weeks snap to Monday (DateHistogramInterval.WEEK)
            key_dt = (dt - _dt.timedelta(days=dt.weekday())).replace(
                hour=0, minute=0, second=0, microsecond=0)
        elif unit in ("quarter", "1q"):
            key_dt = dt.replace(
                month=(dt.month - 1) // 3 * 3 + 1,
                day=1, hour=0, minute=0, second=0, microsecond=0,
            )
        else:  # year
            key_dt = dt.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
        out[i] = key_dt.timestamp() * 1000
    return out


# -- range / filter family --------------------------------------------------


def _range_agg(conf, sub, segments, ms, masks, filter_fn, ext=None) -> dict:
    field = conf["field"]
    ranges = conf["ranges"]
    mapper = ms.field_mapper(field)
    is_date = mapper is not None and mapper.type == "date"
    buckets = []
    for r in ranges:
        frm = r.get("from")
        to = r.get("to")
        if is_date:
            frm = parse_date_millis(frm) if frm is not None else None
            to = parse_date_millis(to) if to is not None else None
        count = 0
        bucket_masks = []
        for i, seg in enumerate(segments):
            vals, present = _column(seg, field, ms)
            if vals is None:
                bucket_masks.append(np.zeros(seg.n_docs, bool))
                continue
            m = masks[i] & present
            if frm is not None:
                m = m & (vals >= frm)
            if to is not None:
                m = m & (vals < to)
            bucket_masks.append(m)
            count += int(m.sum())
        key = r.get("key")
        if key is None:
            # numeric range keys render bounds as doubles ("*-50.0",
            # InternalRange.Bucket key generation); dates keep raw form
            def _kfmt(v):
                if v is None:
                    return "*"
                if not is_date and isinstance(v, (int, float)):
                    return str(float(v))
                return str(v)
            key = f"{_kfmt(frm)}-{_kfmt(to)}"
        bucket: dict[str, Any] = {"key": key, "doc_count": count}
        if frm is not None:
            bucket["from"] = float(frm)
        if to is not None:
            bucket["to"] = float(to)
        if sub:
            bucket.update(_sub_aggs(sub, segments, ms, bucket_masks, filter_fn, ext))
        buckets.append(bucket)
    return {"buckets": buckets}


def _run_filter(filter_fn, body, segments, masks) -> list[np.ndarray]:
    if filter_fn is None:
        raise IllegalArgumentException("filter aggregations need a filter executor")
    return [
        masks[i] & filter_fn(body, i)[: seg.n_docs] for i, seg in enumerate(segments)
    ]


def _filter_agg(conf, sub, segments, ms, masks, filter_fn, ext=None) -> dict:
    f_masks = _run_filter(filter_fn, conf, segments, masks)
    out = {"doc_count": int(sum(m.sum() for m in f_masks))}
    out.update(_sub_aggs(sub, segments, ms, f_masks, filter_fn, ext))
    return out


def _filters_agg(conf, sub, segments, ms, masks, filter_fn, ext=None) -> dict:
    named = conf.get("filters")
    other = conf.get("other_bucket") or conf.get("other_bucket_key")
    anonymous = isinstance(named, list)
    entries = (list(enumerate(named)) if anonymous
               else list(named.items()))
    buckets: dict[str, Any] = {}
    ordered: list[dict] = []
    matched_any = [np.zeros(seg.n_docs, bool) for seg in segments]
    for fname, body in entries:
        f_masks = _run_filter(filter_fn, body, segments, masks)
        for i, m in enumerate(f_masks):
            matched_any[i] |= m
        bucket = {"doc_count": int(sum(m.sum() for m in f_masks))}
        bucket.update(_sub_aggs(sub, segments, ms, f_masks, filter_fn, ext))
        if anonymous:
            ordered.append(bucket)
        else:
            buckets[fname] = bucket
    if other:
        rest = [masks[i] & ~matched_any[i] for i in range(len(segments))]
        bucket = {"doc_count": int(sum(m.sum() for m in rest))}
        bucket.update(_sub_aggs(sub, segments, ms, rest, filter_fn, ext))
        key = (conf.get("other_bucket_key")
               if isinstance(conf.get("other_bucket_key"), str) else "_other_")
        if anonymous:
            ordered.append(bucket)
        else:
            buckets[key] = bucket
    # anonymous form renders a bucket ARRAY (FiltersAggregator.Keyed=false)
    return {"buckets": ordered if anonymous else buckets}


def _count_nested_objects(obj, parts: list[str]) -> int:
    """Number of nested objects reachable at `parts` inside one _source."""
    if not parts:
        if isinstance(obj, dict):
            return 1
        if isinstance(obj, list):
            return sum(1 for x in obj if isinstance(x, dict))
        return 0
    head = parts[0]
    if isinstance(obj, dict):
        return _count_nested_objects(obj.get(head), parts[1:])
    if isinstance(obj, list):
        return sum(_count_nested_objects(x, parts) for x in obj)
    return 0


def _nested_agg(conf, sub, segments, ms, masks, filter_fn, ext=None) -> dict:
    """nested aggregation (bucket/nested/NestedAggregator). This engine
    flattens nested docs into the parent (index/mapper.py nested_paths);
    doc_count here is the REAL nested-object count (from _source), while
    sub-aggregations run over the flattened multi-valued columns — which
    preserves per-object value attribution for terms/metrics."""
    import json as _json

    path = conf.get("path")
    if not path:
        raise ParsingException("[nested] requires [path]")
    paths = set(getattr(ms, "nested_paths", None) or set())
    # multi-index views: any index mapping the path as nested qualifies
    if hasattr(ms, "services"):
        for svc in ms.services:
            paths |= getattr(svc, "nested_paths", set())
    if path not in paths:
        raise IllegalArgumentException(
            f"[nested] nested object under path [{path}] is not of nested "
            f"type")
    parts = path.split(".")
    total = 0
    for i, seg in enumerate(segments):
        for d in np.nonzero(masks[i])[0]:
            try:
                src = _json.loads(seg.sources[int(d)])
            except Exception as e:  # noqa: BLE001 - malformed _source: skip
                logger.debug(
                    "nested agg: unparseable _source for doc %d: %s", d, e)
                continue
            total += _count_nested_objects(src, parts)
    out = {"doc_count": total}
    if sub:
        out.update(compute_aggs(segments, ms, sub, masks, filter_fn, ext))
    return out


def _reverse_nested_agg(conf, sub, segments, ms, masks, filter_fn,
                        ext=None) -> dict:
    """reverse_nested: join back to parent docs. Flattened storage means
    the masks already address parent docs — doc_count is the parent-doc
    count of the enclosing bucket."""
    out = {"doc_count": int(sum(int(m.sum()) for m in masks))}
    if sub:
        out.update(compute_aggs(segments, ms, sub, masks, filter_fn, ext))
    return out


def _missing_agg(conf, sub, segments, ms, masks, filter_fn, ext=None) -> dict:
    field = conf["field"]
    m_masks = []
    for i, seg in enumerate(segments):
        present = np.zeros(seg.n_docs, bool)
        nf = seg.numeric_fields.get(field)
        if nf is not None:
            present |= nf.present
        kf = seg.keyword_fields.get(field)
        if kf is not None:
            present |= kf.first_ord >= 0
        tf = seg.text_fields.get(field)
        if tf is not None:
            present |= tf.doc_len > 0
        vf = seg.vector_fields.get(field)
        if vf is not None:
            present |= vf.present
        m_masks.append(masks[i] & ~present)
    out = {"doc_count": int(sum(m.sum() for m in m_masks))}
    out.update(_sub_aggs(sub, segments, ms, m_masks, filter_fn, ext))
    return out


# register extended aggregation families (populates EXTENSION_AGGS)
from opensearch_tpu.search import aggs_ext as _aggs_ext  # noqa: E402,F401
