"""Fetch phase sub-phases: per-hit document assembly.

The analog of the reference's FetchPhase + fetch/subphase/* chain
(search/fetch/FetchPhase.java:99 runs 17 sub-phases per winning doc:
FetchSourcePhase, HighlightPhase, FetchDocValuesPhase, FetchFieldsPhase,
ExplainPhase, FetchVersionsPhase, SeqNoPrimaryTermPhase, ScriptFieldsPhase…).
Here each sub-phase is a small function over (hit dict, host segment, doc);
the service composes them per request.

The highlighter is the plain-highlighter model (fetch/subphase/highlight/
PlainHighlighter.java): re-analyze the stored text, mark tokens the query's
per-field term predicates accept, emit merged fragments.
"""

from __future__ import annotations

import re
from typing import Any, Callable

from opensearch_tpu.common.errors import ParsingException
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.search import query_dsl as q

_WORD_RE = re.compile(r"\w+", re.UNICODE)


# --------------------------------------------------------------------------
# query term extraction (per-field predicates for highlighting)
# --------------------------------------------------------------------------


def _wildcard_rx(pattern: str) -> re.Pattern:
    parts = []
    for ch in pattern:
        parts.append(".*" if ch == "*" else "." if ch == "?" else re.escape(ch))
    return re.compile("".join(parts) + r"\Z")


def field_term_predicates(
    node: q.QueryNode, ms: MapperService
) -> dict[str, list[Callable[[str], bool]]]:
    """field -> [predicate over analyzed token] for every leaf query."""
    out: dict[str, list[Callable[[str], bool]]] = {}

    def add(field: str, pred: Callable[[str], bool]) -> None:
        out.setdefault(field, []).append(pred)

    def term_set_pred(terms: list[str]) -> Callable[[str], bool]:
        tset = {t.lower() for t in terms}
        return lambda tok: tok.lower() in tset

    def walk(n: q.QueryNode) -> None:
        if isinstance(n, (q.MatchQuery, q.MatchPhraseQuery,
                          q.MatchPhrasePrefixQuery, q.MatchBoolPrefixQuery)):
            add(n.field, term_set_pred(ms.analyze_query_text(n.field, n.query)))
        elif isinstance(n, q.MultiMatchQuery):
            for f in n.fields:
                add(f, term_set_pred(ms.analyze_query_text(f, n.query)))
        elif isinstance(n, q.TermQuery):
            add(n.field, term_set_pred([str(n.value)]))
        elif isinstance(n, q.TermsQuery):
            add(n.field, term_set_pred([str(v) for v in n.values]))
        elif isinstance(n, q.PrefixQuery):
            p = n.value.lower()
            add(n.field, lambda tok, p=p: tok.lower().startswith(p))
        elif isinstance(n, (q.WildcardQuery,)):
            rx = _wildcard_rx(n.value.lower())
            add(n.field, lambda tok, rx=rx: rx.match(tok.lower()) is not None)
        elif isinstance(n, q.RegexpQuery):
            try:
                rx = re.compile(n.value)
            except re.error:
                return
            add(n.field, lambda tok, rx=rx: rx.fullmatch(tok) is not None)
        elif isinstance(n, q.FuzzyQuery):
            from opensearch_tpu.search.executor import (
                _edit_distance_at_most,
                _fuzziness_distance,
            )

            v = n.value
            d = _fuzziness_distance(n.fuzziness, v)
            add(n.field,
                lambda tok, v=v, d=d: _edit_distance_at_most(v, tok, d))
        elif isinstance(n, q.BoolQuery):
            for sub in (*n.must, *n.should, *n.filter):
                walk(sub)  # must_not terms are not highlighted
        elif isinstance(n, q.DisMaxQuery) or isinstance(n, q.HybridQuery):
            for sub in n.queries:
                walk(sub)
        elif isinstance(n, q.BoostingQuery):
            if n.positive is not None:
                walk(n.positive)
        elif isinstance(n, q.ConstantScoreQuery):
            if n.filter is not None:
                walk(n.filter)
        elif isinstance(n, q.FunctionScoreQuery):
            if n.query is not None:
                walk(n.query)
        elif isinstance(n, q.NestedQuery):
            if n.query is not None:
                walk(n.query)
        elif isinstance(n, (q.QueryStringQuery, q.SimpleQueryStringQuery)):
            from opensearch_tpu.search.query_string import (
                parse_query_string,
                parse_simple_query_string,
            )

            fields = n.fields or [
                name for name, m in ms.mappers.items()
                if m.type in ("text", "keyword")
            ]
            parse = (parse_simple_query_string
                     if isinstance(n, q.SimpleQueryStringQuery) else parse_query_string)
            try:
                walk(parse(n.query, fields, n.default_operator))
            except ParsingException:
                pass

    walk(node)
    return out


# --------------------------------------------------------------------------
# highlight
# --------------------------------------------------------------------------

DEFAULT_FRAGMENT_SIZE = 100
DEFAULT_NUM_FRAGMENTS = 5


def highlight_field(
    text: str,
    preds: list[Callable[[str], bool]],
    ms: MapperService,
    field: str,
    pre_tag: str = "<em>",
    post_tag: str = "</em>",
    fragment_size: int = DEFAULT_FRAGMENT_SIZE,
    number_of_fragments: int = DEFAULT_NUM_FRAGMENTS,
) -> list[str]:
    """Plain highlighter: token spans whose analyzed form any predicate
    accepts are wrapped; fragments are windows around match clusters."""
    spans: list[tuple[int, int]] = []
    # memoize analysis + predicate decisions per distinct raw token — a
    # 1000-word field has far fewer distinct words than words, and each
    # analyze call builds the full chain (plain-highlighter token stream
    # equivalent without per-word re-analysis)
    decided: dict[str, bool] = {}
    for m in _WORD_RE.finditer(text):
        raw = m.group(0)
        hit = decided.get(raw)
        if hit is None:
            analyzed = ms.analyze_query_text(field, raw)
            tok = analyzed[0] if analyzed else raw.lower()
            hit = any(p(tok) or p(raw) for p in preds)
            decided[raw] = hit
        if hit:
            spans.append((m.start(), m.end()))
    if not spans:
        return []
    if number_of_fragments == 0:
        # whole-field highlighting
        return [_apply_tags(text, spans, pre_tag, post_tag)]
    # group spans into fragments of ~fragment_size chars
    fragments: list[tuple[int, int, list[tuple[int, int]]]] = []
    for s, e in spans:
        if fragments and s - fragments[-1][0] < fragment_size:
            fs, _fe, group = fragments[-1]
            fragments[-1] = (fs, max(_fe, e), group + [(s, e)])
        else:
            fragments.append((s, e, [(s, e)]))
    out = []
    for fs, fe, group in fragments[:number_of_fragments]:
        # expand the window to fragment_size, snapping to word boundaries
        lo = max(0, fs - max(0, (fragment_size - (fe - fs)) // 2))
        hi = min(len(text), lo + max(fragment_size, fe - fs))
        while lo > 0 and text[lo - 1].isalnum():
            lo -= 1
        while hi < len(text) and text[hi].isalnum():
            hi += 1
        rel = [(s - lo, e - lo) for s, e in group if s >= lo and e <= hi]
        out.append(_apply_tags(text[lo:hi], rel, pre_tag, post_tag))
    return out


def _apply_tags(text: str, spans: list[tuple[int, int]],
                pre: str, post: str) -> str:
    parts = []
    last = 0
    for s, e in spans:
        parts.append(text[last:s])
        parts.append(pre)
        parts.append(text[s:e])
        parts.append(post)
        last = e
    parts.append(text[last:])
    return "".join(parts)


def compute_highlight(
    body_highlight: dict,
    preds_by_field: dict[str, list[Callable[[str], bool]]],
    source: dict,
    ms: MapperService,
) -> dict[str, list[str]]:
    fields_conf = body_highlight.get("fields") or {}
    if isinstance(fields_conf, list):  # ["f1", {"f2": {...}}] form
        norm: dict[str, dict] = {}
        for f in fields_conf:
            if isinstance(f, str):
                norm[f] = {}
            else:
                norm.update(f)
        fields_conf = norm
    pre = (body_highlight.get("pre_tags") or ["<em>"])[0]
    post = (body_highlight.get("post_tags") or ["</em>"])[0]
    require_match = body_highlight.get("require_field_match", True)
    out: dict[str, list[str]] = {}
    flat = _flatten_source(source)
    for fname, conf in fields_conf.items():
        conf = conf or {}
        preds = preds_by_field.get(fname, [])
        if not preds and not require_match:
            preds = [p for ps in preds_by_field.values() for p in ps]
        if not preds:
            continue
        values = flat.get(fname)
        if values is None:
            continue
        if not isinstance(values, list):
            values = [values]
        frags: list[str] = []
        for v in values:
            if not isinstance(v, str):
                continue
            frags.extend(highlight_field(
                v, preds, ms, fname,
                pre_tag=conf.get("pre_tags", [pre])[0] if "pre_tags" in conf else pre,
                post_tag=conf.get("post_tags", [post])[0] if "post_tags" in conf else post,
                fragment_size=int(conf.get("fragment_size", DEFAULT_FRAGMENT_SIZE)),
                number_of_fragments=int(conf.get("number_of_fragments",
                                                 DEFAULT_NUM_FRAGMENTS)),
            ))
        if frags:
            out[fname] = frags
    return out


def _flatten_source(obj: dict, prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    for k, v in obj.items():
        full = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_source(v, f"{full}."))
        else:
            out[full] = v
    return out


# --------------------------------------------------------------------------
# docvalue_fields / fields
# --------------------------------------------------------------------------


def docvalue_fields_for_doc(
    specs: list, host, doc: int, ms: MapperService
) -> dict[str, list]:
    """Columnar reads straight from the segment arrays (FetchDocValuesPhase:
    values come from doc-values, not _source)."""
    out: dict[str, list] = {}
    for spec in specs:
        if isinstance(spec, str):
            fname, fmt = spec, None
        else:
            fname, fmt = spec.get("field"), spec.get("format")
        if fname is None:
            continue
        vals = _doc_column_values(host, doc, fname, ms, fmt)
        if vals:
            # repeated specs for one field accumulate (the reference emits
            # one entry per requested format)
            out.setdefault(fname, []).extend(vals)
    return out


def _doc_column_values(host, doc: int, fname: str, ms: MapperService,
                       fmt: str | None) -> list:
    mapper = ms.field_mapper(fname)
    nf = host.numeric_fields.get(fname)
    if nf is not None and nf.present[doc]:
        vals = nf.doc_values(doc)
        if fmt and set(fmt) <= set("#,.0"):
            # decimal pattern (java DecimalFormat subset): '#.0' -> 1 place
            places = len(fmt.split(".")[1]) if "." in fmt else 0
            return [f"{float(v):.{places}f}" for v in vals]
        if nf.kind == "int":
            if mapper is not None and \
                    getattr(mapper, "original_type", None) == "unsigned_long":
                return [int(v) + 2**63 for v in vals]
            if mapper is not None and mapper.type == "date":
                if mapper.resolution == "nanos":
                    return [_format_date_nanos(int(v), fmt) for v in vals]
                return [_format_date_ms(int(v), fmt) for v in vals]
            if mapper is not None and mapper.type == "boolean":
                return [bool(v) for v in vals]
            return [int(v) for v in vals]
        return [float(v) for v in vals]
    kf = host.keyword_fields.get(fname)
    if kf is not None:
        s, e = int(kf.mv_offsets[doc]), int(kf.mv_offsets[doc + 1])
        return [kf.ord_values[int(o)] for o in kf.mv_ords[s:e]]
    return []


# joda/java-time pattern letters -> strftime (the common subset)
_JODA_MAP = [
    ("uuuu", "%Y"), ("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"),
    ("dd", "%d"), ("HH", "%H"), ("mm", "%M"), ("ss", "%S"),
]


def _format_date_nanos(ns_value: int, fmt: str | None) -> Any:
    """date_nanos doc-value rendering: 9-digit fractional ISO by default
    (strict_date_optional_time_nanos); epoch_millis renders fractional
    millis ("1540815132123.456789"); millis-resolution formats truncate."""
    from datetime import datetime, timezone

    if fmt == "epoch_millis":
        frac_ns = ns_value % 1_000_000
        ms = ns_value // 1_000_000
        if frac_ns:
            return f"{ms}.{frac_ns:06d}".rstrip("0")
        return str(ms)
    dt = datetime.fromtimestamp(ns_value // 1_000_000_000, tz=timezone.utc)
    if fmt in ("strict_date_optional_time", "date_optional_time"):
        ms_part = (ns_value // 1_000_000) % 1000
        return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{ms_part:03d}Z"
    if fmt and fmt not in ("strict_date_optional_time_nanos",):
        # custom java-time pattern with nanosecond fraction support
        out = fmt.replace("'", "")
        out = out.replace("XXX", "Z").replace("XX", "Z").replace("X", "Z")
        if "SSSSSSSSS" in out:
            out = out.replace("SSSSSSSSS", f"{ns_value % 1_000_000_000:09d}")
        elif "SSSSSS" in out:
            out = out.replace("SSSSSS", f"{ns_value % 1_000_000:06d}")
        elif "SSS" in out:
            out = out.replace("SSS", f"{(ns_value // 1_000_000) % 1000:03d}")
        for joda, strf in _JODA_MAP:
            out = out.replace(joda, strf)
        if "%" in out:
            return dt.strftime(out)
    frac = ns_value % 1_000_000_000
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{frac:09d}".rstrip("0").ljust(3, "0") + "Z"


def _format_date_ms(ms_value: int, fmt: str | None) -> Any:
    from datetime import datetime, timezone

    if fmt == "epoch_millis":
        return str(ms_value)
    dt = datetime.fromtimestamp(ms_value / 1000.0, tz=timezone.utc)
    if fmt is None or fmt.startswith("strict_date") or fmt == "date_optional_time":
        return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{ms_value % 1000:03d}Z"
    # joda-style custom pattern
    out = fmt.replace("'", "")
    out = out.replace("XXX", "Z").replace("XX", "Z").replace("X", "Z")
    if "SSSSSSSSS" in out:
        out = out.replace("SSSSSSSSS", f"{ms_value % 1000:03d}000000")
    elif "SSSSSS" in out:
        out = out.replace("SSSSSS", f"{ms_value % 1000:03d}000")
    elif "SSS" in out:
        out = out.replace("SSS", f"{ms_value % 1000:03d}")
    for joda, strf in _JODA_MAP:
        out = out.replace(joda, strf)
    if "%" in out:
        return dt.strftime(out)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{ms_value % 1000:03d}Z"


def fields_option_for_doc(
    specs: list, source: dict, host, doc: int, ms: MapperService
) -> dict[str, list]:
    """The `fields` request option (FetchFieldsPhase): values from _source
    with wildcard patterns, always arrays, doc-values fallback."""
    import fnmatch

    flat = _flatten_source(source)
    # fields mapped as ranges keep their object shape ({gte, lte}) instead
    # of flattening, and malformed-ignored values are omitted
    from opensearch_tpu.index.mapper import RANGE_TYPES

    for fname in list(source):
        m = ms.field_mapper(fname)
        if m is not None and m.type in RANGE_TYPES:
            flat = {k: v for k, v in flat.items()
                    if not k.startswith(f"{fname}.")}
            flat[fname] = source[fname]
    ig = host.keyword_fields.get("_ignored")
    ignored: set = set()
    if ig is not None:
        s_, e_ = int(ig.mv_offsets[doc]), int(ig.mv_offsets[doc + 1])
        ignored = {ig.ord_values[int(o)] for o in ig.mv_ords[s_:e_]}
    out: dict[str, list] = {}
    for spec in specs:
        if isinstance(spec, str):
            pattern, fmt = spec, None
        else:
            pattern, fmt = spec.get("field"), spec.get("format")
        if pattern is None:
            continue
        matched = False
        for key, val in flat.items():
            if fnmatch.fnmatch(key, pattern):
                matched = True
                if key in out or key in ignored:
                    continue  # first spec wins; _ignored values are absent
                vals = val if isinstance(val, list) else [val]
                mapper = ms.field_mapper(key)
                if mapper is not None and mapper.type == "date" and fmt:
                    from opensearch_tpu.index.mapper import parse_date_millis

                    vals = [_format_date_ms(parse_date_millis(v), fmt) for v in vals]
                elif mapper is not None and mapper.type == "token_count":
                    # derived fields read from doc-values, not _source
                    vals = _doc_column_values(host, doc, key, ms, fmt) or vals
                out[key] = list(vals)
        if not matched and "*" not in pattern:
            vals = _doc_column_values(host, doc, pattern, ms, fmt)
            if vals:
                out[pattern] = vals
    return out


# --------------------------------------------------------------------------
# explain
# --------------------------------------------------------------------------


def explain_for_hit(score: float, query_node: q.QueryNode) -> dict:
    """Simplified explanation tree (ExplainPhase): the top-level value is
    exact; the breakdown names the query shape rather than replaying every
    BM25 sub-term."""
    return {
        "value": score,
        "description": f"score({type(query_node).__name__})",
        "details": [],
    }
