"""Intervals query: minimal-interval algebra over position postings.

The analog of the reference's intervals query
(server/src/main/java/org/opensearch/index/query/IntervalQueryBuilder.java +
IntervalsSourceProvider.java — Lucene's o.a.l.queries.intervals): sources
(match / prefix / wildcard / fuzzy / regexp / all_of / any_of) produce
per-document lists of (start, end) position intervals; combinators compose
them (ordered / unordered / unordered_no_overlap, max_gaps); filters
restrict them (containing / contained_by / overlapping / before / after and
negations).

Execution model: the device-side postings mask narrows candidates (docs
holding at least one involved term); interval verification is host work
over the segment's position CSR (`HostTextField.term_positions`) — the same
split the engine uses for phrase queries. Interval lists per doc are tiny
(bounded by per-doc tf), so exhaustive minimal-interval enumeration with a
work cap replaces Lucene's lazy iterator stack.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Callable

from opensearch_tpu.common.errors import ParsingException

Interval = tuple[int, int]  # inclusive (start, end) token positions

# combination work cap: product of sub-interval list sizes beyond which a
# combinator falls back to greedy (first-match) evaluation
_MAX_COMBINATIONS = 200_000


# --------------------------------------------------------------------------
# AST
# --------------------------------------------------------------------------


@dataclass
class IntervalSource:
    filter: "IntervalFilter | None" = None


@dataclass
class MatchSource(IntervalSource):
    query: str = ""
    mode: str = "unordered"       # ordered | unordered | unordered_no_overlap
    max_gaps: int = -1
    analyzer: str | None = None
    use_field: str | None = None


@dataclass
class ExpandSource(IntervalSource):
    """Term-set expansion source (prefix/wildcard/regexp/fuzzy)."""

    kind: str = "prefix"
    pattern: str = ""
    case_insensitive: bool = False
    fuzziness: Any = "AUTO"
    prefix_length: int = 0
    use_field: str | None = None


@dataclass
class TermSource(IntervalSource):
    """Single un-analyzed term (span_term's literal semantics)."""

    term: str = ""


@dataclass
class FirstSource(IntervalSource):
    """span_first: intervals ending at position < end."""

    source: IntervalSource | None = None
    end: int = 0


@dataclass
class AllOfSource(IntervalSource):
    sources: list[IntervalSource] = dc_field(default_factory=list)
    mode: str = "unordered"
    max_gaps: int = -1


@dataclass
class AnyOfSource(IntervalSource):
    sources: list[IntervalSource] = dc_field(default_factory=list)


@dataclass
class IntervalFilter:
    kind: str                      # containing | contained_by | not_* | ...
    source: IntervalSource


# --------------------------------------------------------------------------
# Parsing (IntervalsSourceProvider.fromXContent analog)
# --------------------------------------------------------------------------

_FILTER_KINDS = {
    "containing", "contained_by", "not_containing", "not_contained_by",
    "overlapping", "not_overlapping", "before", "after",
}


def _parse_mode(conf: dict, default: str = "unordered") -> str:
    mode = conf.get("mode")
    if mode is None and "ordered" in conf:
        mode = "ordered" if conf["ordered"] else "unordered"
    if mode is None:
        return default
    if mode not in ("ordered", "unordered", "unordered_no_overlap"):
        raise ParsingException(f"unknown intervals mode [{mode}]")
    return mode


def _parse_filter(conf: Any) -> IntervalFilter:
    if not isinstance(conf, dict) or len(conf) != 1:
        raise ParsingException("[intervals] filter must define exactly one rule")
    kind, sub = next(iter(conf.items()))
    if kind not in _FILTER_KINDS:
        raise ParsingException(f"unknown intervals filter [{kind}]")
    return IntervalFilter(kind=kind, source=parse_intervals_source(sub))


def parse_intervals_source(conf: Any) -> IntervalSource:
    if not isinstance(conf, dict) or len(conf) != 1:
        raise ParsingException(
            "[intervals] source must define exactly one rule "
            "(match/prefix/wildcard/fuzzy/regexp/all_of/any_of)"
        )
    kind, body = next(iter(conf.items()))
    if not isinstance(body, dict):
        raise ParsingException(f"[intervals] [{kind}] body must be an object")
    filt = _parse_filter(body["filter"]) if "filter" in body else None
    if kind == "match":
        if "query" not in body:
            raise ParsingException("[intervals] match requires [query]")
        return MatchSource(
            query=str(body["query"]),
            mode=_parse_mode(body),
            max_gaps=int(body.get("max_gaps", -1)),
            analyzer=body.get("analyzer"),
            use_field=body.get("use_field"),
            filter=filt,
        )
    if kind == "prefix":
        if "prefix" not in body:
            raise ParsingException("[intervals] prefix requires [prefix]")
        return ExpandSource(kind="prefix", pattern=str(body["prefix"]),
                            use_field=body.get("use_field"), filter=filt)
    if kind == "wildcard":
        if "pattern" not in body:
            raise ParsingException("[intervals] wildcard requires [pattern]")
        return ExpandSource(kind="wildcard", pattern=str(body["pattern"]),
                            use_field=body.get("use_field"), filter=filt)
    if kind == "regexp":
        if "pattern" not in body:
            raise ParsingException("[intervals] regexp requires [pattern]")
        return ExpandSource(
            kind="regexp", pattern=str(body["pattern"]),
            case_insensitive=bool(body.get("case_insensitive", False)),
            use_field=body.get("use_field"), filter=filt,
        )
    if kind == "fuzzy":
        if "term" not in body:
            raise ParsingException("[intervals] fuzzy requires [term]")
        return ExpandSource(
            kind="fuzzy", pattern=str(body["term"]),
            fuzziness=body.get("fuzziness", "AUTO"),
            prefix_length=int(body.get("prefix_length", 0)),
            use_field=body.get("use_field"), filter=filt,
        )
    if kind == "all_of":
        subs = body.get("intervals")
        if not isinstance(subs, list) or not subs:
            raise ParsingException("[intervals] all_of requires [intervals]")
        return AllOfSource(
            sources=[parse_intervals_source(s) for s in subs],
            mode=_parse_mode(body),
            max_gaps=int(body.get("max_gaps", -1)),
            filter=filt,
        )
    if kind == "any_of":
        subs = body.get("intervals")
        if not isinstance(subs, list) or not subs:
            raise ParsingException("[intervals] any_of requires [intervals]")
        return AnyOfSource(
            sources=[parse_intervals_source(s) for s in subs], filter=filt,
        )
    raise ParsingException(f"unknown intervals source [{kind}]")


# --------------------------------------------------------------------------
# Evaluation
# --------------------------------------------------------------------------


class IntervalContext:
    """Per-(segment, query) evaluation context.

    `analyze(text, analyzer)` -> list[str]; `expand(src)` -> terms of the
    segment vocabulary matched by an expansion source (cached per segment);
    `positions(term, doc)` -> ascending position list.
    """

    def __init__(
        self,
        analyze: Callable[[str, str | None], list[str]],
        vocab: list[str],
        positions: Callable[[str, int], Any],
        edit_distance_at_most: Callable[[str, str, int], bool],
        fuzziness_distance: Callable[[Any, str], int],
    ):
        self.analyze = analyze
        self.vocab = vocab
        self.positions = positions
        self._edit_distance_at_most = edit_distance_at_most
        self._fuzziness_distance = fuzziness_distance
        self._expand_cache: dict[int, list[str]] = {}

    def expand(self, src: ExpandSource) -> list[str]:
        cached = self._expand_cache.get(id(src))
        if cached is not None:
            return cached
        if src.kind == "prefix":
            match = lambda t: t.startswith(src.pattern)  # noqa: E731
        elif src.kind == "wildcard":
            rx = re.compile(
                "".join(
                    ".*" if c == "*" else "." if c == "?" else re.escape(c)
                    for c in src.pattern
                ),
                re.IGNORECASE if src.case_insensitive else 0,
            )
            match = lambda t: rx.fullmatch(t) is not None  # noqa: E731
        elif src.kind == "regexp":
            rx = re.compile(
                src.pattern, re.IGNORECASE if src.case_insensitive else 0
            )
            match = lambda t: rx.fullmatch(t) is not None  # noqa: E731
        else:  # fuzzy
            value = src.pattern
            max_d = self._fuzziness_distance(src.fuzziness, value)
            plen = src.prefix_length

            def match(t: str) -> bool:
                if plen and t[:plen] != value[:plen]:
                    return False
                if abs(len(t) - len(value)) > max_d:
                    return False
                return self._edit_distance_at_most(value, t, max_d)

        out = [t for t in self.vocab if match(t)]
        self._expand_cache[id(src)] = out
        return out

    def leaf_terms(self, src: IntervalSource) -> set[str]:
        """All terms the source may touch (candidate-doc pre-filter)."""
        out: set[str] = set()
        if isinstance(src, MatchSource):
            out.update(self.analyze(src.query, src.analyzer))
        elif isinstance(src, TermSource):
            out.add(src.term)
        elif isinstance(src, FirstSource):
            if src.source is not None:
                out.update(self.leaf_terms(src.source))
        elif isinstance(src, ExpandSource):
            out.update(self.expand(src))
        elif isinstance(src, (AllOfSource, AnyOfSource)):
            for s in src.sources:
                out.update(self.leaf_terms(s))
        if src.filter is not None:
            out.update(self.leaf_terms(src.filter.source))
        return out


def _minimal(intervals: list[Interval]) -> list[Interval]:
    """Drop duplicates and intervals strictly containing another interval
    (Lucene's minimal-interval semantics), return sorted by (start, end)."""
    if not intervals:
        return []
    uniq = sorted(set(intervals))
    out: list[Interval] = []
    for s, e in uniq:
        if any(s <= s2 and e2 <= e and (s2, e2) != (s, e) for s2, e2 in uniq):
            continue
        out.append((s, e))
    return out


def _combine(
    lists: list[list[Interval]], mode: str, max_gaps: int
) -> list[Interval]:
    """All minimal combined intervals choosing one interval per sub-list."""
    if any(not lst for lst in lists):
        return []
    if mode == "unordered_no_overlap" and len(lists) > 2:
        # Lucene builds n-ary no-overlap as a left fold of pairwise
        # combinations (Intervals.unorderedNoOverlaps is binary); the fold
        # order is observable — the YAML suite's "cold wet it" case counts
        # on it — so reproduce it exactly.
        acc = lists[0]
        for nxt in lists[1:]:
            acc = _combine([acc, nxt], mode, max_gaps)
            if not acc:
                return []
        return acc
    total = 1
    for lst in lists:
        total *= len(lst)
        if total > _MAX_COMBINATIONS:
            break
    results: list[Interval] = []

    if total > _MAX_COMBINATIONS:
        # greedy fallback: take the earliest legal interval per sub-list
        # (keeps existence checks sound for pathological docs at the cost
        # of minimality)
        chosen: list[Interval] = []
        last_end = -1
        for lst in lists:
            nxt = (next((iv for iv in lst if iv[0] > last_end), None)
                   if mode == "ordered" else lst[0])
            if nxt is None:
                return []
            chosen.append(nxt)
            last_end = nxt[1]
        iv = _score_combo(chosen, mode, max_gaps)
        return [iv] if iv is not None else []

    def rec(i: int, chosen: list[Interval]) -> None:
        if i == len(lists):
            iv = _score_combo(chosen, mode, max_gaps)
            if iv is not None:
                results.append(iv)
            return
        for iv in lists[i]:
            rec(i + 1, chosen + [iv])

    rec(0, [])
    return _minimal(results)


def _score_combo(
    chosen: list[Interval], mode: str, max_gaps: int
) -> Interval | None:
    """Validate one choice of sub-intervals; return the combined interval."""
    if mode == "ordered":
        for a, b in zip(chosen, chosen[1:]):
            if b[0] <= a[1]:
                return None
        gaps = sum(b[0] - a[1] - 1 for a, b in zip(chosen, chosen[1:]))
        if 0 <= max_gaps < gaps:
            return None
        return (chosen[0][0], chosen[-1][1])
    # unordered: overlap (even identical spans from different sub-sources)
    # is allowed — Lucene's UnorderedIntervalsSource positions each
    # sub-iterator independently, and the YAML suite's nested-combination
    # cases count on a single occurrence satisfying two sub-sources
    srt = sorted(chosen)
    if mode == "unordered_no_overlap":
        for a, b in zip(srt, srt[1:]):
            if b[0] <= a[1]:
                return None
    gaps = sum(max(0, b[0] - a[1] - 1) for a, b in zip(srt, srt[1:]))
    if 0 <= max_gaps < gaps:
        return None
    return (srt[0][0], srt[-1][1])


def _apply_filter(
    intervals: list[Interval], filt: IntervalFilter, ctx: IntervalContext,
    doc: int,
) -> list[Interval]:
    f_ivs = evaluate(filt.source, ctx, doc)
    kind = filt.kind

    def keep(iv: Interval) -> bool:
        s, e = iv
        if kind == "containing":
            return any(s <= fs and fe <= e for fs, fe in f_ivs)
        if kind == "not_containing":
            return not any(s <= fs and fe <= e for fs, fe in f_ivs)
        if kind == "contained_by":
            return any(fs <= s and e <= fe for fs, fe in f_ivs)
        if kind == "not_contained_by":
            return not any(fs <= s and e <= fe for fs, fe in f_ivs)
        if kind == "overlapping":
            return any(s <= fe and fs <= e for fs, fe in f_ivs)
        if kind == "not_overlapping":
            return not any(s <= fe and fs <= e for fs, fe in f_ivs)
        if kind == "before":
            return any(e < fs for fs, _fe in f_ivs)
        if kind == "after":
            return any(s > fe for _fs, fe in f_ivs)
        raise ParsingException(f"unknown intervals filter [{kind}]")

    return [iv for iv in intervals if keep(iv)]


def evaluate(
    src: IntervalSource, ctx: IntervalContext, doc: int
) -> list[Interval]:
    """Minimal intervals of `src` in local doc `doc`."""
    if isinstance(src, TermSource):
        out = _minimal([(int(p), int(p)) for p in ctx.positions(src.term, doc)])
    elif isinstance(src, FirstSource):
        inner = evaluate(src.source, ctx, doc) if src.source else []
        out = [iv for iv in inner if iv[1] < src.end]
    elif isinstance(src, MatchSource):
        terms = ctx.analyze(src.query, src.analyzer)
        if not terms:
            out = []
        else:
            lists = [
                [(int(p), int(p)) for p in ctx.positions(t, doc)]
                for t in terms
            ]
            out = _combine(lists, src.mode, src.max_gaps) if len(lists) > 1 \
                else _minimal(lists[0])
    elif isinstance(src, ExpandSource):
        ivs = [
            (int(p), int(p))
            for t in ctx.expand(src)
            for p in ctx.positions(t, doc)
        ]
        out = _minimal(ivs)
    elif isinstance(src, AllOfSource):
        lists = [evaluate(s, ctx, doc) for s in src.sources]
        out = _combine(lists, src.mode, src.max_gaps)
    elif isinstance(src, AnyOfSource):
        ivs = [iv for s in src.sources for iv in evaluate(s, ctx, doc)]
        out = _minimal(ivs)
    else:  # pragma: no cover
        raise ParsingException(f"unknown intervals source [{type(src)}]")
    if src.filter is not None:
        out = _apply_filter(out, src.filter, ctx, doc)
    return out
