"""Priority lanes: interactive vs background request classification.

The tail-latency control plane's first lever (ISSUE 11): under hostile
mixed traffic a flood of background work (bulk, msearch fan-outs, scroll
pages, force-merges) must never occupy every serving slot while an
interactive query waits — FusionANNS' serving argument applied to pool
scheduling. Every request is classified ONCE at its boundary (the REST
dispatch in rest/http.py, the search[node]/msearch[node] handlers in
cluster/cluster_node.py) into one of two lanes:

- ``interactive`` — a user is waiting: plain ``_search`` / ``_count``.
- ``background`` — throughput traffic that tolerates latency: ``_bulk``,
  ``_msearch``, scroll start/continuation, ``_forcemerge``.

The lane then follows the request through every queueing point:

1. **pool slots** — rest/http.py and ClusterNode._offload_search keep a
   RESERVED interactive pool; background work runs on its own smaller
   pool, so a background flood can saturate only its own workers.
2. **the kNN dispatch batcher** — the active lane rides a contextvar into
   ``search/batcher.py``: background entries may wait out a longer batch
   deadline (they earn bigger merges), while an interactive entry's own
   (auto-tuned, short) deadline flushes any bucket it joins — background
   queueing can never extend an interactive query's wait.
3. **shedding** — the background lane's queue is BOUNDED
   (``search.lanes.background_max_queue``); past the bound it sheds 429
   (the QueuePressure contract) instead of queueing without bound. The
   interactive lane never sheds here (wlm admission owns interactive
   fairness).

``search.lanes.enabled`` (dynamic) is the kill switch: disabled, every
request runs the shared interactive pool exactly as before this change —
the bench's control-plane-off configuration.
"""

from __future__ import annotations

import contextvars
import threading

from opensearch_tpu.common.settings import Property, Setting

INTERACTIVE = "interactive"
BACKGROUND = "background"
LANES = (INTERACTIVE, BACKGROUND)

# registered metric names (constants, never built at the record site —
# tpulint TPU013); per-lane series vary by LABEL under these families
LANE_QUEUE_DEPTH_MS = "search.lane.queue_depth"
LANE_SHED_TOTAL = "search.lane.shed"
SEARCH_TOOK_MS = "search.took_ms"

# -- settings (registered dynamic in cluster/cluster_settings.py) -----------

LANES_ENABLED_SETTING = Setting.bool_setting(
    "search.lanes.enabled", True,
    Property.NODE_SCOPE, Property.DYNAMIC,
)
BACKGROUND_MAX_QUEUE_SETTING = Setting.int_setting(
    "search.lanes.background_max_queue", 256,
    Property.NODE_SCOPE, Property.DYNAMIC, min_value=0,
)

LANE_SETTINGS = (LANES_ENABLED_SETTING, BACKGROUND_MAX_QUEUE_SETTING)


class LaneConfig:
    """Process-wide lane policy (the batcher/registry adapter shape):
    dynamic-settings updates retune it live; readers read racily by
    design — a request classified under the old policy completes under
    it, which is the dynamic-settings contract."""

    def __init__(self, enabled: bool | None = None,
                 background_max_queue: int | None = None):
        from opensearch_tpu.common.settings import Settings

        self.enabled = (enabled if enabled is not None
                        else LANES_ENABLED_SETTING.default(Settings.EMPTY))
        self.background_max_queue = (
            background_max_queue if background_max_queue is not None
            else BACKGROUND_MAX_QUEUE_SETTING.default(Settings.EMPTY))

    def configure(self, *, enabled: bool | None = None,
                  background_max_queue: int | None = None) -> None:
        if enabled is not None:
            self.enabled = bool(enabled)
        if background_max_queue is not None:
            self.background_max_queue = max(0, int(background_max_queue))

    def apply_settings(self, flat: dict) -> None:
        """Pick this config's keys out of a flat effective-settings map
        (the cluster-settings update consumer)."""
        from opensearch_tpu.common.settings import Settings

        s = Settings.from_flat({
            st.key: flat[st.key] for st in LANE_SETTINGS if st.key in flat
        })
        self.configure(
            enabled=LANES_ENABLED_SETTING.get(s),
            background_max_queue=BACKGROUND_MAX_QUEUE_SETTING.get(s),
        )


default_config = LaneConfig()

# -- the active lane (contextvar, like the profiler / upload_scope) ----------

_lane_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "opensearch_tpu_request_lane", default=None
)


class lane_scope:
    """Context manager stamping the current request's lane; everything
    below (the dispatch batcher, metrics records) reads it without
    signature changes through the service/executor stack."""

    __slots__ = ("lane", "_token")

    def __init__(self, lane: str):
        self.lane = lane if lane in LANES else INTERACTIVE
        self._token = None

    def __enter__(self) -> "lane_scope":
        self._token = _lane_var.set(self.lane)
        return self

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _lane_var.reset(self._token)


def active_lane() -> str:
    """The lane of the executing request; unclassified work (engine
    publishes, recovery, tests driving internals directly) counts as
    interactive — the conservative default."""
    return _lane_var.get() or INTERACTIVE


# -- classification ----------------------------------------------------------

# last path segments that mark a request background at the REST boundary
_BACKGROUND_TAILS = frozenset({
    "_bulk", "_msearch", "_forcemerge", "scroll",
})


def classify_rest(path: str, query: dict) -> str:
    """Lane of one REST request, from its path shape alone: msearch /
    bulk / scroll (start via ?scroll= or continuation via /_search/scroll)
    / force-merge are background; everything else — including plain
    ``_search`` and ``_count`` — is interactive. An explicit ``?lane=``
    overrides (an operator marking a reporting query background)."""
    explicit = query.get("lane")
    if explicit in LANES:
        return explicit
    if "scroll" in query:
        return BACKGROUND
    tail = path.rstrip("/").rsplit("/", 1)[-1]
    return BACKGROUND if tail in _BACKGROUND_TAILS else INTERACTIVE


class LaneTracker:
    """Per-pool-owner lane bookkeeping: live queue depth, lifetime
    submitted/completed/shed counters, one cell per lane. Feeds the
    `tail.lanes` stats section and the `search.lane.*` metric families."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cells: dict[str, dict[str, int]] = {
            lane: {"submitted": 0, "completed": 0, "shed": 0, "depth": 0}
            for lane in LANES
        }

    def try_submit(self, lane: str, max_queue: int | None = None) -> bool:
        """Account one submission; returns False (a shed) when the lane's
        live depth is at `max_queue` — the caller must 429, not queue."""
        cell = self._cells[lane if lane in LANES else INTERACTIVE]
        with self._lock:
            if max_queue is not None and cell["depth"] >= max_queue:
                cell["shed"] += 1
                return False
            cell["submitted"] += 1
            cell["depth"] += 1
        return True

    def complete(self, lane: str) -> None:
        cell = self._cells[lane if lane in LANES else INTERACTIVE]
        with self._lock:
            cell["completed"] += 1
            cell["depth"] = max(0, cell["depth"] - 1)

    def depth(self, lane: str) -> int:
        cell = self._cells[lane if lane in LANES else INTERACTIVE]
        with self._lock:
            return cell["depth"]

    def snapshot(self) -> dict:
        with self._lock:
            return {lane: dict(cell) for lane, cell in self._cells.items()}


def record_lane_metrics(metrics, lane: str, depth: int) -> None:
    """Queue-depth observation at submit time (a distribution beats a
    point-in-time gauge for tail analysis) under the constant family
    name, lane as a LABEL (TPU013)."""
    if metrics is None:
        return
    metrics.histogram(LANE_QUEUE_DEPTH_MS, labels={"lane": lane}).record(
        depth)


def record_lane_shed(metrics, lane: str) -> None:
    if metrics is None:
        return
    metrics.counter(LANE_SHED_COUNTERS[lane]).add(1)


# counter names are constants per lane (counters have no label support;
# the family split is the two-member lane enum, not unbounded cardinality)
LANE_SHED_COUNTERS = {
    INTERACTIVE: "search.lane.shed.interactive",
    BACKGROUND: "search.lane.shed.background",
}
