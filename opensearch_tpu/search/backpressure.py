"""Search backpressure: admission control + overrun cancellation.

The analog of SearchBackpressureService + the admission-control package
(SURVEY.md §2.2 "Backpressure & admission control": search/backpressure/
SearchBackpressureService cancels the most resource-heavy search tasks when
the node is under duress; ratelimitting/admissioncontrol gates actions on
saturation). Single-node model: a concurrency gate sheds load at admission
(429) and a reaper cancels searches that exceed the runtime budget, using
the task manager's cooperative cancellation.
"""

from __future__ import annotations

import threading

from opensearch_tpu.common.errors import (
    RejectedExecutionException,
    ResourceNotFoundException,
)

DEFAULT_MAX_CONCURRENT = 256
DEFAULT_MAX_RUNTIME_MS = 60_000
SEARCH_ACTION = "indices:data/read/search"


class SearchBackpressureService:
    def __init__(self, task_manager, max_concurrent: int = DEFAULT_MAX_CONCURRENT,
                 max_runtime_ms: int = DEFAULT_MAX_RUNTIME_MS):
        self._tasks = task_manager
        self.max_concurrent = max_concurrent
        self.max_runtime_ms = max_runtime_ms
        # admit() runs on every searching thread at once (the parallel
        # search pool, the data worker's scroll/PIT path, and the http
        # search pool all call it); the counters are read-modify-write
        self._stats_lock = threading.Lock()
        self.rejections = 0
        self.cancellations = 0

    def _active_searches(self):
        return [
            t for t in self._tasks.list_tasks(SEARCH_ACTION) if not t.cancelled
        ]

    def admit(self) -> None:
        """Called before registering a new search task."""
        if len(self._active_searches()) >= self.max_concurrent:
            # before shedding, try to reclaim capacity from overrunners
            if not self.cancel_overrunning():
                with self._stats_lock:
                    self.rejections += 1
                raise RejectedExecutionException(
                    "rejected execution of search: node search capacity "
                    f"saturated [{self.max_concurrent} concurrent searches]"
                )

    def cancel_overrunning(self) -> list[int]:
        """Cancel searches past the runtime budget (worst offender first)."""
        overrunners = sorted(
            (
                t for t in self._active_searches()
                if t.running_time_nanos > self.max_runtime_ms * 1_000_000
            ),
            key=lambda t: -t.running_time_nanos,
        )
        cancelled: list[int] = []
        for t in overrunners:
            try:
                cancelled.extend(self._tasks.cancel(
                    t.id,
                    reason="elapsed time exceeded the search backpressure budget",
                ))
            except ResourceNotFoundException:
                pass  # finished between list and cancel: capacity freed anyway
        with self._stats_lock:
            self.cancellations += len(cancelled)
        return cancelled

    def stats(self) -> dict:
        with self._stats_lock:
            rejections, cancellations = self.rejections, self.cancellations
        return {
            "mode": "enforced",
            "active_searches": len(self._active_searches()),
            "limits": {
                "max_concurrent": self.max_concurrent,
                "max_runtime_ms": self.max_runtime_ms,
            },
            "rejections": rejections,
            "cancellations": cancellations,
        }
