"""Lucene-style query_string / simple_query_string mini-language parsers.

The analog of the reference's QueryStringQueryParser / SimpleQueryStringParser
(server/.../index/query/QueryStringQueryBuilder.java,
SimpleQueryStringBuilder.java — which delegate to Lucene's classic and simple
query parsers). Both produce trees of the same QueryNode types as the JSON
DSL, so execution is shared with every other query.

Supported subset:
- query_string: AND/OR/NOT (and &&/||/!), parentheses, field:term,
  quoted phrases, wildcard terms (* and ?), prefix terms (trailing *),
  bare terms combined with default_operator.
- simple_query_string: + (AND), | (OR), - (NOT), quoted phrases,
  trailing-* prefix, parentheses; invalid syntax degrades to terms
  (the "simple" contract: never throws on user input).
"""

from __future__ import annotations

import re

from opensearch_tpu.common.errors import ParsingException
from opensearch_tpu.search import query_dsl as q

_TOKEN_RE = re.compile(
    r"""
    \s*(
        \(|\)                          # parens
        | "(?:[^"\\]|\\.)*"(?:~\d+)?   # quoted phrase (+ optional ~N slop)
        | /(?:[^/\\]|\\.)*/            # /regex/ literal
        | (?:[^\s()":]+:)              # field prefix
        | [^\s()"]+                    # bare term
    )
    """,
    re.VERBOSE,
)


def _tokenize(s: str) -> list[str]:
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if m is None:
            break
        out.append(m.group(1))
        pos = m.end()
    return out


def _term_node(field: str, text: str) -> q.QueryNode:
    if text.startswith('"'):
        # "..." or "..."~N (sloppy phrase, classic parser's proximity)
        m = re.fullmatch(r'("(?:[^"\\]|\\.)*")(?:~(\d+))?', text)
        if m is not None:
            return q.MatchPhraseQuery(
                field=field,
                query=m.group(1)[1:-1].replace('\\"', '"'),
                slop=int(m.group(2)) if m.group(2) else 0,
            )
    if text.startswith("/") and text.endswith("/") and len(text) >= 2:
        # /regex/ syntax (classic parser's RegexpQuery clause)
        return q.RegexpQuery(field=field, value=text[1:-1])
    if "*" in text or "?" in text:
        return q.WildcardQuery(field=field, value=text)
    m = re.fullmatch(r"(.+)~(\d+(?:\.\d+)?)?", text)
    if m is not None:
        # term~ (AUTO) or term~N; N goes through Lucene's
        # FuzzyQuery.floatToEdits: >=1 caps at 2 edits, a fraction is a
        # legacy minimum-similarity converted to edits by term length
        fuzz = "AUTO"
        if m.group(2):
            f = float(m.group(2))
            if f >= 1.0:
                edits = int(min(f, 2))
            elif f == 0.0:
                edits = 0
            else:
                edits = min(int((1.0 - f) * len(m.group(1))), 2)
            fuzz = str(edits)
        return q.FuzzyQuery(field=field, value=m.group(1), fuzziness=fuzz)
    return q.MatchQuery(field=field, query=text)


def _multi_field(fields: list[str], text: str) -> q.QueryNode:
    if len(fields) == 1:
        return _term_node(fields[0], text)
    return q.DisMaxQuery(queries=[_term_node(f, text) for f in fields])


class _QSParser:
    def __init__(self, tokens: list[str], fields: list[str], default_op: str):
        self.tokens = tokens
        self.i = 0
        self.fields = fields
        self.default_op = default_op

    def peek(self) -> str | None:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def next(self) -> str:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def parse(self) -> q.QueryNode:
        node = self.parse_or()
        if self.peek() is not None:
            raise ParsingException(f"unexpected token [{self.peek()}] in query_string")
        return node

    def parse_or(self) -> q.QueryNode:
        parts = [self.parse_and()]
        while self.peek() in ("OR", "||"):
            self.next()
            parts.append(self.parse_and())
        if len(parts) == 1:
            return parts[0]
        return q.BoolQuery(should=parts, minimum_should_match=1)

    def parse_and(self) -> q.QueryNode:
        # Lucene classic-parser semantics: NOT produces a prohibited clause
        # on the ENCLOSING boolean (brown NOT dog == should:[brown],
        # must_not:[dog]), not a standalone negative query.
        clauses = [self.parse_not()]          # list of (negated, node)
        explicit_and = False
        while True:
            t = self.peek()
            if t in ("AND", "&&"):
                self.next()
                explicit_and = True
                clauses.append(self.parse_not())
                continue
            if t is None or t in ("OR", "||", ")"):
                break
            clauses.append(self.parse_not())
        positives = [n for neg, n in clauses if not neg]
        negatives = [n for neg, n in clauses if neg]
        if len(clauses) == 1 and negatives:
            return q.BoolQuery(must_not=negatives)
        if len(positives) == 1 and not negatives:
            return positives[0]
        if explicit_and or self.default_op == "and":
            return q.BoolQuery(must=positives, must_not=negatives)
        return q.BoolQuery(
            should=positives, must_not=negatives,
            minimum_should_match=1 if positives else None,
        )

    def parse_not(self) -> tuple[bool, q.QueryNode]:
        """Returns (negated, node)."""
        t = self.peek()
        if t in ("NOT", "!"):
            self.next()
            neg, node = self.parse_not()
            return (not neg, node)
        # leading -/!/+ operators apply even when glued to a field prefix
        # token ("-status:deleted" tokenizes as ["-status:", "deleted"])
        if t is not None and len(t) > 1 and t[0] in "-!":
            self.next()
            self.tokens.insert(self.i, t[1:])
            neg, node = self.parse_not()
            return (not neg, node)
        if t is not None and len(t) > 1 and t[0] == "+":
            self.next()
            self.tokens.insert(self.i, t[1:])
            return self.parse_not()
        return (False, self.parse_primary())

    def parse_primary(self) -> q.QueryNode:
        t = self.peek()
        if t is None:
            raise ParsingException("unexpected end of query_string")
        if t == "(":
            self.next()
            node = self.parse_or()
            if self.peek() != ")":
                raise ParsingException("unbalanced parentheses in query_string")
            self.next()
            return node
        t = self.next()
        if t.endswith(":") and len(t) > 1:
            field = t[:-1]
            nxt = self.peek()
            if nxt == "(":
                # field:(a OR b) — rescope a sub-expression to one field
                self.next()
                sub = _QSParser(self._collect_group(), [field], self.default_op)
                return sub.parse()
            if nxt is None:
                raise ParsingException(f"missing value after [{field}:]")
            if nxt[:1] in "[{":
                # field:[a TO b] / field:{a TO b} (classic-parser range
                # syntax; brackets inclusive, braces exclusive, * open)
                return self._parse_range_syntax(field)
            return _term_node(field, self.next())
        return _multi_field(self.fields, t)

    def _parse_range_syntax(self, field: str) -> q.QueryNode:
        open_tok = self.next()
        inc_lo = open_tok[0] == "["
        parts = [open_tok[1:]] if len(open_tok) > 1 else []
        close_tok = None
        while self.peek() is not None:
            t = self.next()
            if t.endswith("]") or t.endswith("}"):
                close_tok = t
                break
            parts.append(t)
        if close_tok is None:
            raise ParsingException(
                f"unclosed range syntax after [{field}:]")
        inc_hi = close_tok.endswith("]")
        if len(close_tok) > 1:
            parts.append(close_tok[:-1])
        vals = [p for p in parts if p and p.upper() != "TO"]
        if len(vals) != 2:
            raise ParsingException(
                f"range syntax after [{field}:] needs [lo TO hi], "
                f"got {vals}")
        lo = None if vals[0] == "*" else vals[0]
        hi = None if vals[1] == "*" else vals[1]
        return q.RangeQuery(
            field=field,
            gte=lo if inc_lo else None,
            gt=None if inc_lo else lo,
            lte=hi if inc_hi else None,
            lt=None if inc_hi else hi,
        )

    def _collect_group(self) -> list[str]:
        depth, out = 1, []
        while self.i < len(self.tokens):
            t = self.next()
            if t == "(":
                depth += 1
            elif t == ")":
                depth -= 1
                if depth == 0:
                    return out
            out.append(t)
        raise ParsingException("unbalanced parentheses in query_string")


def parse_query_string(
    query: str, fields: list[str], default_operator: str = "or"
) -> q.QueryNode:
    tokens = _tokenize(query)
    if not tokens:
        return q.MatchNoneQuery()
    return _QSParser(tokens, fields, default_operator).parse()


# --------------------------------------------------------------------------
# simple_query_string: +/|/- flavor, never throws on bad syntax
# --------------------------------------------------------------------------


def parse_simple_query_string(
    query: str, fields: list[str], default_operator: str = "or"
) -> q.QueryNode:
    try:
        return _SQSParser(_tokenize(query), fields, default_operator).parse()
    except ParsingException:
        # "simple" contract: degrade to a bag-of-terms match
        terms = [t for t in re.split(r"[\s+|()-]+", query) if t and t != '"']
        if not terms:
            return q.MatchNoneQuery()
        return q.BoolQuery(
            should=[_multi_field(fields, t) for t in terms],
            minimum_should_match=1,
        )


class _SQSParser(_QSParser):
    def parse_or(self) -> q.QueryNode:
        parts = [self.parse_and()]
        while self.peek() == "|":
            self.next()
            parts.append(self.parse_and())
        if len(parts) == 1:
            return parts[0]
        return q.BoolQuery(should=parts, minimum_should_match=1)

    def parse_and(self) -> q.QueryNode:
        clauses = [self.parse_not()]
        explicit_and = False
        while True:
            t = self.peek()
            if t == "+":
                self.next()
                explicit_and = True
                clauses.append(self.parse_not())
                continue
            if t is None or t in ("|", ")"):
                break
            clauses.append(self.parse_not())
        positives = [n for neg, n in clauses if not neg]
        negatives = [n for neg, n in clauses if neg]
        if len(clauses) == 1 and negatives:
            return q.BoolQuery(must_not=negatives)
        if len(positives) == 1 and not negatives:
            return positives[0]
        if explicit_and or self.default_op == "and":
            return q.BoolQuery(must=positives, must_not=negatives)
        return q.BoolQuery(
            should=positives, must_not=negatives,
            minimum_should_match=1 if positives else None,
        )

    def parse_not(self) -> tuple[bool, q.QueryNode]:
        t = self.peek()
        if t == "-":
            self.next()
            neg, node = self.parse_not()
            return (not neg, node)
        if t is not None and len(t) > 1 and t[0] == "-":
            self.next()
            self.tokens.insert(self.i, t[1:])
            neg, node = self.parse_not()
            return (not neg, node)
        return (False, self.parse_primary())

    def parse_primary(self) -> q.QueryNode:  # type: ignore[override]
        t = self.peek()
        if t is None:
            raise ParsingException("unexpected end of simple_query_string")
        if t == "(":
            self.next()
            node = self.parse_or()
            if self.peek() != ")":
                raise ParsingException("unbalanced parens")
            self.next()
            return node
        t = self.next()
        if t in ("+", "|", "-", ")"):
            raise ParsingException(f"unexpected [{t}]")
        # no field:term syntax in simple_query_string; ':' is part of the term
        if t.endswith(":"):
            t = t[:-1]
        return _multi_field(self.fields, t)
