"""Coordinator-adjacent search phases: can_match, rescore, collapse.

- can_match: shard skipping by provable non-match — range/term constraints
  against per-segment numeric min/max (the reference's coordinator
  pre-filter, action/search/CanMatchPreFilterSearchPhase.java, backed by
  the min/max rewrite of range queries over BKD metadata).
- rescore: second-pass re-ranking of the top window
  (search/rescore/RescorePhase.java + QueryRescorer: combined =
  query_weight * first + rescore_query_weight * second per score_mode).
- collapse: first-hit-per-group on a field (search/collapse/
  CollapseContext.java reduced to its serving core).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from opensearch_tpu.common.errors import ParsingException
from opensearch_tpu.search import query_dsl as q
from opensearch_tpu.telemetry import tracing


# --------------------------------------------------------------------- #
# can_match
# --------------------------------------------------------------------- #


def _range_constraints(node: Any) -> list[q.RangeQuery]:
    """Conjunctive range constraints provable from the query root."""
    if isinstance(node, q.RangeQuery):
        return [node]
    if isinstance(node, q.BoolQuery):
        out: list[q.RangeQuery] = []
        for child in list(node.must) + list(node.filter):
            out.extend(_range_constraints(child))
        return out
    return []


def _segment_minmax(host, field: str) -> tuple[float, float] | None:
    cache = getattr(host, "_minmax_cache", None)
    if cache is None:
        cache = {}
        host._minmax_cache = cache
    if field in cache:
        return cache[field]
    nf = host.numeric_fields.get(field)
    out = None
    if nf is not None:
        vals = nf.values_i64 if nf.kind == "int" else nf.values_f64
        present = nf.present & host.live[: len(nf.present)]
        if present.any():
            v = vals[present]
            # int columns stay python ints: epoch NANOS overflow float64's
            # mantissa and a rounded max can wrongly prove "no match"
            if nf.kind == "int":
                out = (int(v.min()), int(v.max()))
            else:
                out = (float(v.min()), float(v.max()))
        else:
            out = "empty"
    cache[field] = out
    return out


def can_match(snapshot, mapper_service, node: Any) -> bool:
    """False only when the shard PROVABLY has no matching doc. Unknown
    fields/types return True (conservative, like the reference's rewrite
    returning MatchAllDocs when it cannot prove otherwise)."""
    constraints = _range_constraints(node)
    if not constraints:
        return True
    with tracing.span("search.can_match",
                      {"constraints": len(constraints)}) as span:
        matched = _can_match_constrained(snapshot, mapper_service, constraints)
        span.set_attribute("matched", matched)
    return matched


def _can_match_constrained(snapshot, mapper_service, constraints) -> bool:
    if not snapshot.segments:
        # a shard with buffered-but-unrefreshed docs still can't serve them;
        # empty searchable set only provably non-matching if no constraint
        # is needed — keep executing (cheap on an empty shard)
        return True
    for rq in constraints:
        mapper = mapper_service.field_mapper(rq.field)
        if mapper is None or mapper.type not in (
            "long", "integer", "short", "byte", "double", "float", "date",
        ):
            continue
        lo, hi = None, None
        try:
            if mapper.type == "date":
                from opensearch_tpu.index.mapper import (
                    parse_date_millis,
                    parse_date_nanos,
                )

                conv = (parse_date_nanos
                        if getattr(mapper, "resolution", "millis") == "nanos"
                        else parse_date_millis)
            elif getattr(mapper, "original_type", None) == "unsigned_long":
                # biased int64 storage (see mapper unsigned_long handling)
                conv = lambda v: int(str(v), 10) - 2**63  # noqa: E731
            else:
                conv = float
            if rq.gte is not None:
                lo = conv(rq.gte)
            if rq.gt is not None:
                lo = conv(rq.gt)
            if rq.lte is not None:
                hi = conv(rq.lte)
            if rq.lt is not None:
                hi = conv(rq.lt)
        except (TypeError, ValueError):
            continue
        any_segment_matches = False
        for host, _dev in snapshot.segments:
            mm = _segment_minmax(host, rq.field)
            if mm is None:
                # field absent in this segment: range can't match here
                continue
            if mm == "empty":
                continue
            smin, smax = mm
            if lo is not None:
                bound_ok = smax > lo if rq.gt is not None else smax >= lo
                if not bound_ok:
                    continue
            if hi is not None:
                bound_ok = smin < hi if rq.lt is not None else smin <= hi
                if not bound_ok:
                    continue
            any_segment_matches = True
            break
        if not any_segment_matches:
            return False
    return True


# --------------------------------------------------------------------- #
# rescore
# --------------------------------------------------------------------- #

_SCORE_MODES = {
    "total": lambda a, b: a + b,
    "multiply": lambda a, b: a * b,
    "avg": lambda a, b: (a + b) / 2.0,
    "max": max,
    "min": min,
}


def apply_rescore(rescore_body, merged, per_shard_results, shards):
    """Re-rank the top window of `merged` ([(shard_idx, ShardHit)] sorted by
    score desc). Each rescore stage computes the rescore query's scores for
    window docs and combines per score_mode; hits outside the window keep
    their order below the window (RescorePhase contract)."""
    stages = rescore_body if isinstance(rescore_body, list) else [rescore_body]
    with tracing.span("search.rescore", {"stages": len(stages)}):
        merged = _apply_rescore_stages(
            stages, merged, per_shard_results, shards)
    return merged


def _apply_rescore_stages(stages, merged, per_shard_results, shards):
    from opensearch_tpu.search.executor import SegmentExecutor, ShardContext

    for stage in stages:
        if not isinstance(stage, dict) or "query" not in stage:
            raise ParsingException("[rescore] requires a [query] object")
        window = int(stage.get("window_size", 10))
        conf = stage["query"]
        rq_body = conf.get("rescore_query")
        if rq_body is None:
            raise ParsingException("[rescore] requires [query.rescore_query]")
        qw = float(conf.get("query_weight", 1.0))
        rw = float(conf.get("rescore_query_weight", 1.0))
        mode = str(conf.get("score_mode", "total"))
        combine = _SCORE_MODES.get(mode)
        if combine is None:
            raise ParsingException(f"unknown rescore score_mode [{mode}]")
        rq_node = q.parse_query(rq_body)

        # lazily computed rescore scores per (shard_idx, segment)
        score_cache: dict[tuple[int, int], tuple[np.ndarray, np.ndarray]] = {}

        def rescore_scores(shard_idx: int, seg_idx: int):
            key = (shard_idx, seg_idx)
            if key not in score_cache:
                shard, snapshot, _res = per_shard_results[shard_idx]
                host, dev = snapshot.segments[seg_idx]
                ctx = ShardContext(snapshot, shard.mapper_service)
                result = SegmentExecutor(ctx, host, dev).execute(rq_node)
                score_cache[key] = (
                    np.asarray(result.scores), np.asarray(result.mask)
                )
            return score_cache[key]

        head = merged[:window]
        rescored = []
        for shard_idx, hit in head:
            scores, mask = rescore_scores(shard_idx, hit.segment)
            if mask[hit.doc]:
                new = combine(qw * hit.score, rw * float(scores[hit.doc]))
            else:
                new = qw * hit.score
            from dataclasses import replace

            rescored.append((shard_idx, replace(hit, score=new)))
        rescored.sort(
            key=lambda sh: (-sh[1].score, sh[0], sh[1].segment, sh[1].doc)
        )
        merged = rescored + merged[window:]
    return merged


# --------------------------------------------------------------------- #
# collapse
# --------------------------------------------------------------------- #


def doc_field_value(host, field: str, doc: int, mapper_service):
    kf = host.keyword_fields.get(field)
    if kf is not None:
        o = int(kf.first_ord[doc])
        return kf.ord_values[o] if o >= 0 else None
    nf = host.numeric_fields.get(field)
    if nf is not None:
        if not nf.present[doc]:
            return None
        v = nf.values_i64[doc] if nf.kind == "int" else nf.values_f64[doc]
        return int(v) if nf.kind == "int" else float(v)
    return None


def apply_collapse(collapse_body, merged, per_shard_results):
    """Keep the first (best-ranked) hit per distinct field value; docs
    without the field each form their own group (reference: null group).
    `inner_hits` specs expand each kept hit's group (CollapseContext +
    ExpandSearchPhase — here the group members are already in hand, so the
    expansion is a sort+slice instead of a follow-up msearch)."""
    if not isinstance(collapse_body, dict) or not collapse_body.get("field"):
        raise ParsingException("[collapse] requires a [field]")
    with tracing.span("search.collapse",
                      {"field": collapse_body["field"]}):
        return _apply_collapse_inner(collapse_body, merged, per_shard_results)


def _apply_collapse_inner(collapse_body, merged, per_shard_results):
    field = collapse_body["field"]
    inner_specs = collapse_body.get("inner_hits") or []
    if isinstance(inner_specs, dict):
        inner_specs = [inner_specs]
    groups: dict = {}
    hit_values = []
    for shard_idx, hit in merged:
        shard, snapshot, _res = per_shard_results[shard_idx]
        host, _dev = snapshot.segments[hit.segment]
        value = doc_field_value(host, field, hit.doc, shard.mapper_service)
        hit_values.append(value)
        if value is not None:
            groups.setdefault(value, []).append((shard_idx, hit))
    seen: set = set()
    out = []
    values = []
    inner = []
    for (shard_idx, hit), value in zip(merged, hit_values):
        if value is not None:
            if value in seen:
                continue
            seen.add(value)
        out.append((shard_idx, hit))
        values.append(value)
        if not inner_specs:
            inner.append(None)
            continue
        members = groups.get(value, [(shard_idx, hit)])
        per_name = {}
        for spec in inner_specs:
            if "collapse" in spec:
                from opensearch_tpu.common.errors import ParseException

                raise ParseException(
                    "cannot use `collapse` inside `inner_hits`"
                )
            name = spec.get("name") or field
            cand = list(members)
            sort = spec.get("sort")
            if sort:
                sort_l = [sort] if isinstance(sort, (str, dict)) else list(sort)
                cand.sort(key=_inner_sort_key(sort_l, per_shard_results))
            else:
                cand.sort(key=lambda sh: (-sh[1].score, sh[0],
                                          sh[1].segment, sh[1].doc))
            frm = int(spec.get("from", 0))
            sel = cand[frm: frm + int(spec.get("size", 3))]
            per_name[name] = {"total": len(members), "hits": sel,
                              "spec": spec}
        inner.append(per_name)
    return out, field, values, inner


def _inner_sort_key(sort_l, per_shard_results):
    from opensearch_tpu.search.executor import _sort_spec, _StrKey

    specs = [_sort_spec(sp) for sp in sort_l]

    def key(sh):
        s_i, h_ = sh
        shard, snapshot, _res = per_shard_results[s_i]
        host, _dev = snapshot.segments[h_.segment]
        parts = []
        for fname, order, missing in specs:
            if fname == "_score":
                parts.append(-h_.score if order == "desc" else h_.score)
                continue
            v = doc_field_value(host, fname, h_.doc, shard.mapper_service)
            if v is None:
                parts.append((-1, 0) if missing == "_first" else (1, 0))
            elif isinstance(v, str):
                parts.append((0, _StrKey(v, order == "desc")))
            else:
                parts.append((0, -v if order == "desc" else v))
        parts.append((s_i, h_.segment, h_.doc))
        return tuple(parts)

    return key
