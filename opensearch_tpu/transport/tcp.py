"""TcpTransport: the real L2 layer — asyncio TCP RPC between node processes.

The production implementation of the interface established by
testing/sim.MockTransport, so Coordinator/ClusterNode run over real
sockets unchanged. Reimplements the semantics of the reference's netty
transport (transport/TcpTransport.java:119 framing, TransportService.java:
sendRequest:923 request/response correlation + timeouts, handler registry
:336, TransportHandshaker; modules/transport-netty4/Netty4Transport.java:92)
as a from-scratch asyncio design:

- frames: [u32 big-endian length][JSON body]; body carries
  {"t": "req"|"res"|"err", "id": corr-id, "action": name,
   "sender": node-id, "payload": ...}
- one persistent outbound connection per target node, opened lazily and
  re-opened on failure (ClusterConnectionManager analog); a HANDSHAKE
  frame is exchanged on connect and validates cluster name + protocol
  version before any request flows
- request/response correlation by id with a per-request timeout timer;
  timed-out ids are tombstoned so a late response is dropped, not
  delivered to a recycled callback
- handlers run on the event loop, single-threaded — the same execution
  model the sim's task queue provides; a handler may return a
  DeferredResponse to answer later (replicated-write acks)

Everything is callback-style (on_response/on_failure), matching the
coordinator's continuation-passing design; `LoopScheduler` is the
wall-clock twin of the sim's DeterministicTaskQueue.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import struct
from typing import Any, Callable

from opensearch_tpu.transport.base import (
    TRACE_HEADER,
    DeferredResponse,
    handler_trace_scope,
    trace_header,
)

logger = logging.getLogger(__name__)

PROTOCOL_VERSION = 2
_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024  # hard cap, like the reference's 2GB guard

# pending-reply backlog bound (TPU009: every long-lived transport buffer
# needs a bound + shed). Each entry holds two callbacks and a timer; a
# peer that stops answering must shed new requests fast instead of
# accreting correlation state until the process dies.
DEFAULT_MAX_PENDING = 10_000


class TransportBacklogFull(Exception):
    """Shed signal: the pending-reply table is at capacity."""

# frame kinds (first byte after the length prefix)
_KIND_JSON = 0x00    # [len][0x00][json]
_KIND_BINARY = 0x01  # [len][0x01][u32 json_len][json][raw bytes]
# a JSON payload/result dict may carry raw bytes under this key; the codec
# ships them out-of-band (no base64) — the data-plane path segment
# replication needs (VERDICT r2 weak #9 / missing #2)
BINARY_KEY = "_binary"


class RemoteTransportException(Exception):
    """An error raised by the remote handler, carried back over the wire."""


class LoopScheduler:
    """scheduler contract (schedule + .random) on an asyncio loop."""

    class _Handle:
        def __init__(self, timer: asyncio.TimerHandle):
            self._timer = timer

        def cancel(self) -> None:
            self._timer.cancel()

        @property
        def cancelled(self) -> bool:
            return self._timer.cancelled()

    def __init__(self, loop: asyncio.AbstractEventLoop, seed: int | None = None):
        self.loop = loop
        self.random = random.Random(seed)

    def schedule(self, delay_ms: int, fn: Callable[[], None]) -> "LoopScheduler._Handle":
        return self._Handle(self.loop.call_later(max(delay_ms, 0) / 1000.0, fn))


class _Connection:
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.peer_id: str | None = None
        self.closed = False

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                self.writer.close()
            except Exception as e:  # noqa: BLE001 - best-effort close
                logger.debug("connection close failed: %s", e)


def _extract_binary(body: dict) -> tuple[dict, bytes | None]:
    """Pull raw bytes out of payload/result dicts (one level deep)."""
    blob = None
    out = body
    for key in ("payload", "result"):
        inner = body.get(key)
        if isinstance(inner, dict) and isinstance(inner.get(BINARY_KEY), (bytes, bytearray)):
            inner = dict(inner)
            blob = bytes(inner.pop(BINARY_KEY))
            out = dict(body)
            out[key] = inner
            out["_bin_at"] = key
            return out, blob
    if isinstance(body.get(BINARY_KEY), (bytes, bytearray)):
        out = dict(body)
        blob = bytes(out.pop(BINARY_KEY))
        out["_bin_at"] = "."
    return out, blob


def encode_frame(body: dict) -> bytes:
    body, blob = _extract_binary(body)
    payload = json.dumps(body, separators=(",", ":")).encode()
    if blob is None:
        if len(payload) + 1 > MAX_FRAME:
            raise ValueError(
                f"frame of {len(payload)} bytes exceeds MAX_FRAME — "
                "chunk the payload"
            )
        return _LEN.pack(len(payload) + 1) + bytes([_KIND_JSON]) + payload
    total = 1 + 4 + len(payload) + len(blob)
    if total > MAX_FRAME:
        # fail on the SENDER with a clear error instead of poisoning the
        # receiver's stream (callers chunk large transfers per segment)
        raise ValueError(
            f"binary frame of {total} bytes exceeds MAX_FRAME — "
            "chunk the payload"
        )
    return (
        _LEN.pack(total)
        + bytes([_KIND_BINARY])
        + _LEN.pack(len(payload))
        + payload
        + blob
    )


async def read_frame(reader: asyncio.StreamReader) -> dict | None:
    try:
        header = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds MAX_FRAME")
    try:
        raw = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return None
    kind, raw = raw[0], raw[1:]
    if kind == _KIND_JSON:
        return json.loads(raw)
    (jlen,) = _LEN.unpack(raw[:4])
    body = json.loads(raw[4: 4 + jlen])
    blob = raw[4 + jlen:]
    at = body.pop("_bin_at", ".")
    if at == ".":
        body[BINARY_KEY] = blob
    else:
        body[at][BINARY_KEY] = blob
    return body


class TcpTransport:
    """One per node process. `seeds` maps node_id -> (host, port) — the
    file-based seed-hosts provider analog (DiscoveryModule.java:85)."""

    def __init__(
        self,
        node_id: str,
        host: str,
        port: int,
        seeds: dict[str, tuple[str, int]],
        *,
        loop: asyncio.AbstractEventLoop | None = None,
        timeout_ms: int = 10_000,
        cluster_name: str = "opensearch-tpu",
        max_pending: int = DEFAULT_MAX_PENDING,
    ):
        self.node_id = node_id
        self.host = host
        self.port = port
        self.seeds = dict(seeds)
        self.timeout_ms = timeout_ms
        self.cluster_name = cluster_name
        self.max_pending = max_pending
        self.loop = loop or asyncio.get_event_loop()
        self.handlers: dict[str, Callable[[str, Any], Any]] = {}
        self._server: asyncio.base_events.Server | None = None
        self._outbound: dict[str, _Connection] = {}
        self._connecting: dict[str, asyncio.Future] = {}
        self._inbound: set[_Connection] = set()
        self._pending: dict[int, tuple[Callable | None, Callable | None, Any]] = {}
        self._req_id = 0
        self.stats = {"sent": 0, "dropped": 0, "delivered": 0, "rx": 0,
                      "late_dropped": 0, "shed": 0}
        self._closed = False

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._accept, self.host, self.port
        )

    async def aclose(self) -> None:
        self._closed = True
        # close live connections BEFORE awaiting the listener: inbound
        # handler tasks only exit when their socket dies, and (Python 3.12)
        # Server.wait_closed blocks until every handler finished
        for conn in list(self._outbound.values()) + list(self._inbound):
            conn.close()
        self._outbound.clear()
        self._inbound.clear()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                pass
        for rid in list(self._pending):
            self._fail_pending(rid, ConnectionError("transport closed"))

    # -- interface parity with MockTransport -------------------------------

    def register(self, node_id: str, action: str, handler: Callable) -> None:
        # signature kept identical to the sim's (node_id first) so wiring
        # code is transport-agnostic; a TcpTransport only serves one node
        assert node_id == self.node_id, f"{node_id} != {self.node_id}"
        self.handlers[action] = handler

    def send(
        self,
        sender: str,
        target: str,
        action: str,
        payload: Any,
        on_response: Callable[[Any], None] | None = None,
        on_failure: Callable[[Exception], None] | None = None,
        timeout_ms: int | None = None,
    ) -> None:
        if self._closed:
            # a closed transport must behave like a dead process: nothing
            # leaves the node (otherwise a shut-down leader keeps
            # heartbeating over fresh dials and drags followers back)
            if on_failure is not None:
                self.loop.call_soon(
                    on_failure, ConnectionError("transport closed")
                )
            return
        self.stats["sent"] += 1
        if target == self.node_id:
            # loopback: dispatch on the loop without a socket (the
            # reference's localNodeConnection)
            self.loop.call_soon(self._dispatch_local, sender, action, payload,
                               on_response, on_failure)
            return
        if len(self._pending) >= self.max_pending:
            # shed instead of accreting correlation state without bound
            # (the QueuePressure contract at the transport layer): the
            # caller gets an immediate failure it can retry/degrade on
            self.stats["shed"] += 1
            if on_failure is not None:
                self.loop.call_soon(on_failure, TransportBacklogFull(
                    f"{len(self._pending)} requests in flight "
                    f"(max_pending={self.max_pending})"
                ))
            return
        self._req_id += 1
        rid = self._req_id
        timer = self.loop.call_later(
            (timeout_ms or self.timeout_ms) / 1000.0,
            lambda: self._fail_pending(
                rid, TimeoutError(f"{action} to {target} timed out")
            ),
        )
        self._pending[rid] = (on_response, on_failure, timer)
        body = {
            "t": "req", "id": rid, "action": action,
            "sender": sender, "payload": payload,
        }
        trace = trace_header()
        if trace is not None:
            body[TRACE_HEADER] = trace
        try:
            frame = encode_frame(body)
        except Exception as e:  # noqa: BLE001 - any encode failure
            # oversized payload (ValueError) or unserializable payload
            # (TypeError from json.dumps): fail THIS request's listener
            # now — a raise escaping send() would leave the pending entry
            # (and the caller's callbacks) dangling until the timeout
            # timer, then fail the request a second time through it
            # (the callback-leak class TPU008 hunts)
            self._fail_pending(rid, e)
            return
        self.loop.create_task(self._send_frame(target, rid, frame))

    # -- outbound ----------------------------------------------------------

    async def _send_frame(self, target: str, rid: int, frame: bytes) -> None:
        try:
            conn = await self._get_connection(target)
            conn.writer.write(frame)
            await conn.writer.drain()
        except Exception as e:  # noqa: BLE001 - any IO failure fails the req
            self._drop_connection(target)
            self._fail_pending(rid, ConnectionError(f"send to {target}: {e}"))

    async def _get_connection(self, target: str) -> _Connection:
        conn = self._outbound.get(target)
        if conn is not None and not conn.closed:
            return conn
        # collapse concurrent dials into one
        fut = self._connecting.get(target)
        if fut is None:
            fut = self.loop.create_task(self._dial(target))
            self._connecting[target] = fut
            fut.add_done_callback(
                lambda _: self._connecting.pop(target, None)
            )
        return await asyncio.shield(fut)

    async def _dial(self, target: str) -> _Connection:
        if self._closed:
            raise ConnectionError("transport closed")
        addr = self.seeds.get(target)
        if addr is None:
            raise ConnectionError(f"no address for node [{target}]")
        reader, writer = await asyncio.open_connection(addr[0], addr[1])
        conn = _Connection(reader, writer)
        # handshake before any request (TransportHandshaker analog)
        conn.writer.write(encode_frame({
            "t": "handshake", "sender": self.node_id,
            "cluster": self.cluster_name, "version": PROTOCOL_VERSION,
        }))
        await conn.writer.drain()
        reply = await asyncio.wait_for(read_frame(conn.reader),
                                       self.timeout_ms / 1000.0)
        if (
            reply is None
            or reply.get("t") != "handshake"
            or reply.get("cluster") != self.cluster_name
            or reply.get("version") != PROTOCOL_VERSION
        ):
            conn.close()
            raise ConnectionError(f"handshake with {target} failed: {reply}")
        conn.peer_id = reply.get("sender")
        self._outbound[target] = conn
        self.loop.create_task(self._read_responses(target, conn))
        return conn

    def _drop_connection(self, target: str) -> None:
        conn = self._outbound.pop(target, None)
        if conn is not None:
            conn.close()

    async def _read_responses(self, target: str, conn: _Connection) -> None:
        """Response frames come back on the same connection the request
        went out on (full-duplex, pipelined — no per-request socket)."""
        try:
            while not conn.closed:
                frame = await read_frame(conn.reader)
                if frame is None:
                    break
                self._handle_response(frame)
        except ValueError:
            # oversized/corrupt frame: the stream is unrecoverable — drop
            # the connection (a fresh dial resyncs) instead of leaving a
            # dead reader behind a live-looking socket
            pass
        finally:
            self._drop_connection(target)

    def _handle_response(self, frame: dict) -> None:
        rid = frame.get("id")
        entry = self._pending.pop(rid, None)
        if entry is None:
            # timed out earlier; the id is tombstoned (popped) so the late
            # response is dropped instead of firing a recycled callback
            self.stats["late_dropped"] += 1
            return
        on_response, on_failure, timer = entry
        timer.cancel()
        if frame.get("t") == "err":
            if on_failure is not None:
                on_failure(RemoteTransportException(str(frame.get("error"))))
        elif on_response is not None:
            on_response(frame.get("payload"))

    def _fail_pending(self, rid: int, error: Exception) -> None:
        entry = self._pending.pop(rid, None)
        if entry is None:
            return
        self.stats["dropped"] += 1
        on_response, on_failure, timer = entry
        timer.cancel()
        if on_failure is not None:
            on_failure(error)

    # -- inbound -----------------------------------------------------------

    async def _accept(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        conn = _Connection(reader, writer)
        self._inbound.add(conn)
        try:
            hello = await asyncio.wait_for(read_frame(reader),
                                           self.timeout_ms / 1000.0)
            if (
                hello is None
                or hello.get("t") != "handshake"
                or hello.get("cluster") != self.cluster_name
                or hello.get("version") != PROTOCOL_VERSION
            ):
                return
            conn.peer_id = hello.get("sender")
            writer.write(encode_frame({
                "t": "handshake", "sender": self.node_id,
                "cluster": self.cluster_name, "version": PROTOCOL_VERSION,
            }))
            await writer.drain()
            while not conn.closed:
                frame = await read_frame(reader)
                if frame is None:
                    break
                if frame.get("t") == "req":
                    self._handle_request(conn, frame)
        except (asyncio.TimeoutError, ConnectionError, OSError, ValueError):
            pass
        finally:
            self._inbound.discard(conn)
            conn.close()

    def _handle_request(self, conn: _Connection, frame: dict) -> None:
        self.stats["rx"] += 1
        rid = frame["id"]
        action = frame.get("action")
        sender = frame.get("sender", "?")
        handler = self.handlers.get(action)

        def respond(result: Any, error: Exception | None) -> None:
            if conn.closed:
                return
            if error is not None:
                body = {"t": "err", "id": rid, "error": f"{type(error).__name__}: {error}"}
            else:
                body = {"t": "res", "id": rid, "payload": result}
            try:
                frame = encode_frame(body)
            except ValueError as e:
                # unshippable response (e.g. over MAX_FRAME): tell the
                # caller instead of dying silently
                frame = encode_frame({"t": "err", "id": rid,
                                      "error": f"ValueError: {e}"})
            conn.writer.write(frame)
            # no drain await: the loop flushes; backpressure is handled by
            # the OS buffer for responses (they are small control messages)

        if handler is None:
            respond(None, RuntimeError(f"no handler for {action} on {self.node_id}"))
            return
        self.stats["delivered"] += 1
        try:
            # restore the sender's trace context so spans the handler opens
            # stitch into the caller's trace tree (cross-node propagation)
            with handler_trace_scope(frame.get(TRACE_HEADER)):
                result = handler(sender, frame.get("payload"))
        except Exception as e:  # noqa: BLE001 - remote errors travel back
            respond(None, e)
            return
        if isinstance(result, DeferredResponse):
            result.on_done(lambda d: respond(d.result, d.error))
        else:
            respond(result, None)

    # -- loopback ----------------------------------------------------------

    def _dispatch_local(self, sender: str, action: str, payload: Any,
                        on_response, on_failure) -> None:
        handler = self.handlers.get(action)
        if handler is None:
            if on_failure is not None:
                on_failure(RuntimeError(f"no handler for {action}"))
            return
        self.stats["delivered"] += 1
        try:
            result = handler(sender, payload)
        except Exception as e:  # noqa: BLE001
            if on_failure is not None:
                on_failure(e)
            return

        def finish(res: Any, err: Exception | None) -> None:
            if err is not None:
                if on_failure is not None:
                    on_failure(err)
            elif on_response is not None:
                on_response(res)

        if isinstance(result, DeferredResponse):
            result.on_done(lambda d: finish(d.result, d.error))
        else:
            finish(result, None)
