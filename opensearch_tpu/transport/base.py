"""Transport contracts shared by the real TCP transport and the sim.

The interface both implement (established by testing/sim.MockTransport so
Coordinator/ClusterNode run unchanged over either):

    register(node_id, action, handler)   handler(sender, payload) -> result
    send(sender, target, action, payload, on_response=None, on_failure=None)

plus the scheduler contract (schedule(delay_ms, fn) -> cancellable with
.cancel(), and .random: random.Random) established by
testing/sim.DeterministicTaskQueue.

`DeferredResponse` extends the handler contract for operations that cannot
answer synchronously — the primary of a replicated write must wait for
replica acks before acknowledging (the reference's ReplicationOperation:
respond only when all in-sync copies answered, TransportReplicationAction
.java:111). A handler returns a DeferredResponse instead of a dict; the
transport ships the response frame when set_result fires.
"""

from __future__ import annotations

from typing import Any, Callable

# transport message header key carrying the trace context (trace_id +
# span_id). Both transports inject it at send() and restore it around the
# receiving handler — the ThreadContext header relay of the reference,
# reduced to the one header distributed tracing needs.
TRACE_HEADER = "_trace"


def trace_header() -> dict | None:
    """The sender-side trace context to attach to an outgoing message
    (None when the send happens outside any span)."""
    from opensearch_tpu.telemetry.tracing import current_trace_context

    return current_trace_context()


def handler_trace_scope(trace_ctx: dict | None):
    """Receiver-side scope restoring a propagated trace context around the
    handler invocation; no-op for untraced messages."""
    from opensearch_tpu.telemetry.tracing import restore_trace_context

    return restore_trace_context(trace_ctx)


class DeferredResponse:
    """A response the handler will produce later (on the same event loop /
    task queue — no cross-thread use)."""

    def __init__(self) -> None:
        self._done = False
        self._result: Any = None
        self._error: Exception | None = None
        self._listeners: list[Callable[["DeferredResponse"], None]] = []

    def set_result(self, result: Any) -> None:
        if self._done:
            return
        self._done = True
        self._result = result
        for listener in self._listeners:
            listener(self)

    def set_exception(self, error: Exception) -> None:
        if self._done:
            return
        self._done = True
        self._error = error
        for listener in self._listeners:
            listener(self)

    # -- transport side ----------------------------------------------------

    def on_done(self, listener: Callable[["DeferredResponse"], None]) -> None:
        """Register a completion listener (multiple allowed: the transport
        ships the response AND the handler may chain follow-up work)."""
        self._listeners.append(listener)
        if self._done:
            listener(self)

    @property
    def error(self) -> Exception | None:
        return self._error

    @property
    def result(self) -> Any:
        return self._result
