"""Gateway: node-local durable cluster state.

The analog of the reference's GatewayMetaState / PersistedClusterStateService
(server/src/main/java/org/opensearch/gateway/PersistedClusterStateService.java:137
— cluster state stored durably on every cluster-manager-eligible node,
recovered behind a quorum barrier by GatewayService on full-cluster restart).

The reference persists into a local Lucene index with incremental writes of
changed IndexMetadata; here the state is small structured metadata, so the
store is one atomic JSON document per save: write to a temp file, fsync,
rename over the live file (rename is atomic on POSIX), fsync the directory.
Every save is write-ahead with respect to the coordination state machine:
CoordinationState persists the term BEFORE a vote leaves the node and the
accepted state BEFORE the publish ack — a crash between the two cannot
produce a double vote or a regressed accept.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from opensearch_tpu.cluster.state import ClusterState

STATE_FILE = "cluster_state.json"


class GatewayStore:
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.saves = 0

    @property
    def _live(self) -> Path:
        return self.path / STATE_FILE

    def save(self, term: int, state: ClusterState) -> None:
        doc = {"current_term": term, "accepted_state": state.to_dict()}
        tmp = self.path / f".{STATE_FILE}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._live)
        dir_fd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        self.saves += 1

    def load(self) -> tuple[int, ClusterState] | None:
        if not self._live.exists():
            return None
        with open(self._live) as f:
            doc = json.load(f)
        return int(doc["current_term"]), ClusterState.from_dict(
            doc["accepted_state"]
        )
