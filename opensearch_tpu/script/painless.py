"""Painless-subset interpreter: tokenizer, recursive-descent parser, evaluator.

The analog of the reference's sandboxed script language
(modules/lang-painless: ANTLR grammar -> AST -> ASM bytecode with per-context
allowlists). Here the language is interpreted over a closed set of value
types and namespaces — there is no route from a script to the host runtime:
no imports, no attribute access on arbitrary Python objects (only dicts,
lists, strings, numbers and the Doc/FieldValues views), no dunder names.

Supported syntax (covers the idiomatic scripts in the reference's docs/tests):
  literals, arithmetic, comparison, &&/||/!, ternary, parentheses,
  member access (a.b / a['b'] / a[0]), method calls on strings/lists/maps,
  Math.*, doc['field'].value / .values / .size(), params.x, _score,
  ctx._source.field assignment (=, +=, -=, *=, /=), local variable
  declarations (`def x = ...`, `double y = ...`), if/else blocks,
  return, `;`-separated statements, string concatenation with +.
"""

from __future__ import annotations

import math
import re
from typing import Any

from opensearch_tpu.common.errors import OpenSearchTpuException


class ScriptException(OpenSearchTpuException):
    status = 400
    error_type = "script_exception"


_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|//[^\n]*)
  | (?P<num>\d+\.\d+[fFdD]?|\d+[lLfFdD]?)
  | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><=|>=|==|!=|&&|\|\||\+=|-=|\*=|/=|%=|\+\+|--|[-+*/%<>=!?:;.,(){}\[\]])
""", re.VERBOSE)

_TYPE_NAMES = {"def", "int", "long", "float", "double", "boolean", "String",
               "Object", "List", "Map", "var"}
_KEYWORDS = {"true", "false", "null", "if", "else", "return", "for", "while"}


def tokenize(src: str) -> list[tuple[str, str]]:
    out: list[tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise ScriptException(f"unexpected character [{src[pos]}] at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append((kind, m.group(0)))
    return out


# -- AST nodes (plain tuples: (kind, ...)) ---------------------------------


class Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self, k: int = 0):
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else (None, None)

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, value: str):
        kind, v = self.peek()
        if v != value:
            raise ScriptException(f"expected [{value}] but found [{v}]")
        return self.next()

    def at_end(self) -> bool:
        return self.i >= len(self.toks)

    # -- statements --------------------------------------------------------

    def parse_program(self):
        stmts = []
        while not self.at_end():
            stmts.append(self.parse_statement())
        return ("block", stmts)

    def parse_block(self):
        if self.peek()[1] == "{":
            self.next()
            stmts = []
            while self.peek()[1] != "}":
                if self.at_end():
                    raise ScriptException("unclosed block")
                stmts.append(self.parse_statement())
            self.next()
            return ("block", stmts)
        return self.parse_statement()

    def parse_statement(self):
        kind, v = self.peek()
        if v == ";":
            self.next()
            return ("nop",)
        if v == "return":
            self.next()
            if self.peek()[1] in (";", None):
                expr = ("lit", None)
            else:
                expr = self.parse_expr()
            if self.peek()[1] == ";":
                self.next()
            return ("return", expr)
        if v == "if":
            self.next()
            self.expect("(")
            cond = self.parse_expr()
            self.expect(")")
            then = self.parse_block()
            other = None
            if self.peek()[1] == "else":
                self.next()
                other = self.parse_block()
            return ("if", cond, then, other)
        if v == "while":
            self.next()
            self.expect("(")
            cond = self.parse_expr()
            self.expect(")")
            body = self.parse_block()
            return ("while", cond, body)
        if v == "for":
            self.next()
            self.expect("(")
            # enhanced for: `for (def x : iter)` / `for (x in iter)`
            probe = 0
            if self.peek()[1] in _TYPE_NAMES:
                probe = 1
            if (self.peek(probe)[0] == "name"
                    and self.peek(probe + 1)[1] in (":", "in")):
                for _ in range(probe):
                    self.next()
                var = self.next()[1]
                self.next()  # ':' or 'in'
                it = self.parse_expr()
                self.expect(")")
                body = self.parse_block()
                return ("foreach", var, it, body)
            init = self.parse_statement()
            cond = self.parse_expr()
            self.expect(";")
            update = self.parse_statement()
            self.expect(")")
            body = self.parse_block()
            return ("cfor", init, cond, update, body)
        if v == "break":
            self.next()
            if self.peek()[1] == ";":
                self.next()
            return ("break",)
        if v == "continue":
            self.next()
            if self.peek()[1] == ";":
                self.next()
            return ("continue",)
        # typed local declaration: `def x = expr` / `double y = expr`
        if v in _TYPE_NAMES and self.peek(1)[0] == "name" and self.peek(2)[1] == "=":
            self.next()
            name = self.next()[1]
            self.expect("=")
            expr = self.parse_expr()
            if self.peek()[1] == ";":
                self.next()
            return ("assign", ("name", name), expr)
        expr = self.parse_expr()
        nk, nv = self.peek()
        if nv in ("++", "--"):
            self.next()
            if self.peek()[1] == ";":
                self.next()
            return ("augassign", expr, nv[0], ("lit", 1))
        if nv in ("=", "+=", "-=", "*=", "/=", "%="):
            self.next()
            rhs = self.parse_expr()
            if self.peek()[1] == ";":
                self.next()
            if nv == "=":
                return ("assign", expr, rhs)
            return ("augassign", expr, nv[0], rhs)
        if nv == ";":
            self.next()
        return ("expr", expr)

    # -- expressions -------------------------------------------------------

    def parse_expr(self):
        return self.parse_ternary()

    def parse_ternary(self):
        cond = self.parse_or()
        if self.peek()[1] == "?":
            self.next()
            a = self.parse_expr()
            self.expect(":")
            b = self.parse_expr()
            return ("ternary", cond, a, b)
        return cond

    def _binop_level(self, sub, ops):
        node = sub()
        while self.peek()[1] in ops:
            op = self.next()[1]
            node = ("binop", op, node, sub())
        return node

    def parse_or(self):
        return self._binop_level(self.parse_and, ("||",))

    def parse_and(self):
        return self._binop_level(self.parse_eq, ("&&",))

    def parse_eq(self):
        return self._binop_level(self.parse_cmp, ("==", "!="))

    def parse_cmp(self):
        return self._binop_level(self.parse_add, ("<", "<=", ">", ">="))

    def parse_add(self):
        return self._binop_level(self.parse_mul, ("+", "-"))

    def parse_mul(self):
        return self._binop_level(self.parse_unary, ("*", "/", "%"))

    def parse_unary(self):
        kind, v = self.peek()
        if v in ("!", "-"):
            self.next()
            return ("unary", v, self.parse_unary())
        if v == "+":
            self.next()
            return self.parse_unary()
        return self.parse_postfix()

    def parse_postfix(self):
        node = self.parse_primary()
        while True:
            kind, v = self.peek()
            if v == ".":
                self.next()
                nkind, name = self.next()
                if nkind != "name":
                    raise ScriptException(f"expected member name, found [{name}]")
                if "__" in name:
                    raise ScriptException(f"illegal member name [{name}]")
                if self.peek()[1] == "(":
                    args = self.parse_args()
                    node = ("call", node, name, args)
                else:
                    node = ("member", node, name)
            elif v == "[":
                self.next()
                idx = self.parse_expr()
                self.expect("]")
                node = ("index", node, idx)
            elif v == "(" and node[0] == "name":
                args = self.parse_args()
                node = ("fncall", node[1], args)
            else:
                return node

    def parse_args(self):
        self.expect("(")
        args = []
        while self.peek()[1] != ")":
            args.append(self.parse_expr())
            if self.peek()[1] == ",":
                self.next()
        self.expect(")")
        return args

    def parse_primary(self):
        kind, v = self.next() if not self.at_end() else (None, None)
        if kind == "num":
            raw = v.rstrip("lLfFdD")
            return ("lit", float(raw) if "." in raw else int(raw))
        if kind == "str":
            body = v[1:-1]
            return ("lit", body.replace("\\'", "'").replace('\\"', '"')
                    .replace("\\\\", "\\").replace("\\n", "\n"))
        if kind == "name":
            if v == "true":
                return ("lit", True)
            if v == "false":
                return ("lit", False)
            if v == "null":
                return ("lit", None)
            if "__" in v:
                raise ScriptException(f"illegal identifier [{v}]")
            return ("name", v)
        if v == "(":
            node = self.parse_expr()
            self.expect(")")
            return node
        if v == "[":
            items = []
            while self.peek()[1] != "]":
                items.append(self.parse_expr())
                if self.peek()[1] == ",":
                    self.next()
            self.expect("]")
            return ("list", items)
        raise ScriptException(f"unexpected token [{v}]")


def compile_script(source: str):
    """source -> AST (cached by ScriptService)."""
    return Parser(tokenize(source)).parse_program()


# --------------------------------------------------------------------------
# runtime values
# --------------------------------------------------------------------------


class FieldValues:
    """doc['field'] — the script doc-values view (sorted multi-values)."""

    __slots__ = ("_vals",)

    def __init__(self, vals: list):
        self._vals = vals

    @property
    def value(self):
        if not self._vals:
            raise ScriptException(
                "A document doesn't have a value for a field! "
                "Use doc[<field>].size()==0 to check if a document is missing a field!"
            )
        return self._vals[0]

    @property
    def values(self):
        return list(self._vals)

    @property
    def empty(self):
        return not self._vals

    @property
    def length(self):
        return len(self._vals)

    def methods(self, name: str, args: list):
        if name == "size":
            return len(self._vals)
        if name == "isEmpty":
            return not self._vals
        if name == "contains":
            return args[0] in self._vals
        if name == "get":
            return self._vals[int(args[0])]
        raise ScriptException(f"unknown method [{name}] on doc values")


class DocView:
    """doc — lazy per-document columnar access."""

    __slots__ = ("_host", "_doc", "_ms")

    def __init__(self, host, doc: int, mapper_service):
        self._host = host
        self._doc = doc
        self._ms = mapper_service

    def __getitem__(self, field: str) -> FieldValues:
        from opensearch_tpu.search.fetch import _doc_column_values

        return FieldValues(
            _doc_column_values(self._host, self._doc, field, self._ms, None)
        )

    def methods(self, name: str, args: list):
        if name == "containsKey":
            f = args[0]
            return (f in self._host.numeric_fields or f in self._host.keyword_fields
                    or f in self._host.text_fields or f in self._host.vector_fields)
        raise ScriptException(f"unknown method [{name}] on doc")


_MATH = {
    "log": math.log, "log10": math.log10, "max": max, "min": min,
    "abs": abs, "pow": math.pow, "sqrt": math.sqrt, "floor": math.floor,
    "ceil": math.ceil, "exp": math.exp, "round": round,
    "E": math.e, "PI": math.pi,
}


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class Evaluator:
    def __init__(self, env: dict[str, Any]):
        self.env = dict(env)
        self._loop_iters = 0

    # -- statements --------------------------------------------------------

    def run(self, node) -> Any:
        try:
            last = self._stmt(node)
        except _Return as r:
            return r.value
        except (_Break, _Continue):
            raise ScriptException("break/continue outside of a loop")
        except ScriptException:
            raise
        except (KeyError, ValueError, IndexError, TypeError, AttributeError,
                ZeroDivisionError, OverflowError, re.error) as e:
            # user-script runtime faults surface as 400 script_exception,
            # never a raw 500 (PainlessError semantics)
            raise ScriptException(f"runtime error in script: {e}")
        return last

    def _stmt(self, node) -> Any:
        kind = node[0]
        if kind == "block":
            last = None
            for s in node[1]:
                last = self._stmt(s)
            return last
        if kind == "nop":
            return None
        if kind == "return":
            raise _Return(self.eval(node[1]))
        if kind == "if":
            if _truthy(self.eval(node[1])):
                return self._stmt(node[2])
            if node[3] is not None:
                return self._stmt(node[3])
            return None
        if kind == "assign":
            value = self.eval(node[2])
            self._store(node[1], value)
            return None
        if kind == "augassign":
            cur = self.eval(node[1])
            value = _binop(node[2], cur, self.eval(node[3]))
            self._store(node[1], value)
            return None
        if kind == "expr":
            return self.eval(node[1])
        if kind == "while":
            n = 0
            while _truthy(self.eval(node[1])):
                self._bump_loop(n)
                n += 1
                try:
                    self._stmt(node[2])
                except _Break:
                    break
                except _Continue:
                    continue
            return None
        if kind == "foreach":
            it = self.eval(node[2])
            if it is None:
                raise ScriptException("cannot iterate over null")
            if isinstance(it, dict):
                it = list(it.keys())
            for n, item in enumerate(it):
                self._bump_loop(n)
                self.env[node[1]] = item
                try:
                    self._stmt(node[3])
                except _Break:
                    break
                except _Continue:
                    continue
            return None
        if kind == "cfor":
            self._stmt(node[1])
            n = 0
            while _truthy(self.eval(node[2])):
                self._bump_loop(n)
                n += 1
                try:
                    self._stmt(node[4])
                except _Break:
                    break
                except _Continue:
                    pass
                self._stmt(node[3])
            return None
        if kind == "break":
            raise _Break()
        if kind == "continue":
            raise _Continue()
        raise ScriptException(f"unknown statement [{kind}]")

    def _bump_loop(self, _n: int) -> None:
        # the reference compiles in a loop counter that throws after too many
        # iterations (CompilerSettings MAX_LOOP_COUNTER); same guard here
        self._loop_iters += 1
        if self._loop_iters > 1_000_000:
            raise ScriptException("loop limit exceeded [1000000]")

    def _store(self, target, value) -> None:
        kind = target[0]
        if kind == "name":
            self.env[target[1]] = value
            return
        if kind == "member":
            obj = self.eval(target[1])
            if isinstance(obj, dict):
                obj[target[2]] = value
                return
            raise ScriptException(f"cannot assign member [{target[2]}]")
        if kind == "index":
            obj = self.eval(target[1])
            idx = self.eval(target[2])
            if isinstance(obj, dict):
                obj[idx] = value
                return
            if isinstance(obj, list):
                obj[int(idx)] = value
                return
        raise ScriptException("invalid assignment target")

    # -- expressions -------------------------------------------------------

    def eval(self, node) -> Any:
        kind = node[0]
        if kind == "lit":
            return node[1]
        if kind == "name":
            name = node[1]
            if name in self.env:
                return self.env[name]
            if name == "Math":
                return _MATH
            raise ScriptException(f"unknown variable [{name}]")
        if kind == "list":
            return [self.eval(x) for x in node[1]]
        if kind == "ternary":
            return self.eval(node[2]) if _truthy(self.eval(node[1])) else self.eval(node[3])
        if kind == "binop":
            op = node[1]
            if op == "&&":
                return _truthy(self.eval(node[2])) and _truthy(self.eval(node[3]))
            if op == "||":
                return _truthy(self.eval(node[2])) or _truthy(self.eval(node[3]))
            return _binop(op, self.eval(node[2]), self.eval(node[3]))
        if kind == "unary":
            v = self.eval(node[2])
            return (not _truthy(v)) if node[1] == "!" else -v
        if kind == "member":
            return self._member(self.eval(node[1]), node[2])
        if kind == "index":
            obj = self.eval(node[1])
            idx = self.eval(node[2])
            if isinstance(obj, DocView):
                return obj[str(idx)]
            if isinstance(obj, dict):
                return obj.get(idx)
            if isinstance(obj, (list, str)):
                return obj[int(idx)]
            raise ScriptException(f"cannot index [{type(obj).__name__}]")
        if kind == "call":
            obj = self.eval(node[1])
            args = [self.eval(a) for a in node[3]]
            return self._method(obj, node[2], args)
        if kind == "fncall":
            raise ScriptException(f"unknown function [{node[1]}]")
        raise ScriptException(f"unknown expression [{kind}]")

    def _member(self, obj, name: str):
        if isinstance(obj, FieldValues):
            if name in ("value", "values", "empty", "length"):
                return getattr(obj, name)
            raise ScriptException(f"unknown doc-values member [{name}]")
        if isinstance(obj, dict):
            if obj is _MATH:
                if name not in _MATH:
                    raise ScriptException(f"unknown Math member [{name}]")
                return _MATH[name]
            return obj.get(name)
        if isinstance(obj, str) and name == "length":
            return len(obj)
        if isinstance(obj, list) and name == "length":
            return len(obj)
        raise ScriptException(
            f"cannot access member [{name}] on [{type(obj).__name__}]"
        )

    def _method(self, obj, name: str, args: list):
        if hasattr(obj, "methods"):
            return obj.methods(name, args)
        if obj is _MATH or (isinstance(obj, dict) and obj is _MATH):
            fn = _MATH.get(name)
            if fn is None or not callable(fn):
                raise ScriptException(f"unknown Math function [{name}]")
            return fn(*args)
        if isinstance(obj, str):
            return _str_method(obj, name, args)
        if isinstance(obj, list):
            return _list_method(obj, name, args)
        if isinstance(obj, dict):
            return _map_method(obj, name, args)
        if isinstance(obj, (int, float)) and name in ("intValue", "longValue",
                                                      "doubleValue", "floatValue"):
            return int(obj) if name in ("intValue", "longValue") else float(obj)
        raise ScriptException(
            f"unknown method [{name}] on [{type(obj).__name__}]"
        )


def _truthy(v) -> bool:
    if v is None:
        return False
    return bool(v)


def _binop(op: str, a, b):
    try:
        if op == "+":
            if isinstance(a, str) or isinstance(b, str):
                return _to_str(a) + _to_str(b)
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            if isinstance(a, int) and isinstance(b, int):
                if b == 0:
                    raise ScriptException("/ by zero")
                return a // b if (a < 0) == (b < 0) or a % b == 0 else -((-a) // b)
            return a / b
        if op == "%":
            return a % b
        if op == "==":
            return a == b
        if op == "!=":
            return a != b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
    except ScriptException:
        raise
    except ZeroDivisionError:
        raise ScriptException("/ by zero")
    except TypeError as e:
        raise ScriptException(f"bad operands for [{op}]: {e}")
    raise ScriptException(f"unknown operator [{op}]")


def _to_str(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return f"{v:.1f}"
    return str(v)


def _str_method(s: str, name: str, args: list):
    table = {
        "length": lambda: len(s),
        "contains": lambda: str(args[0]) in s,
        "substring": lambda: s[int(args[0]):] if len(args) == 1
        else s[int(args[0]):int(args[1])],
        "toLowerCase": lambda: s.lower(),
        "toUpperCase": lambda: s.upper(),
        "startsWith": lambda: s.startswith(str(args[0])),
        "endsWith": lambda: s.endswith(str(args[0])),
        "indexOf": lambda: s.find(str(args[0])),
        "replace": lambda: s.replace(str(args[0]), str(args[1])),
        "split": lambda: re.split(str(args[0]), s),
        "trim": lambda: s.strip(),
        "equals": lambda: s == args[0],
        "equalsIgnoreCase": lambda: s.lower() == str(args[0]).lower(),
        "isEmpty": lambda: len(s) == 0,
        "charAt": lambda: s[int(args[0])],
        "toString": lambda: s,
    }
    fn = table.get(name)
    if fn is None:
        raise ScriptException(f"unknown String method [{name}]")
    return fn()


def _list_method(lst: list, name: str, args: list):
    table = {
        "size": lambda: len(lst),
        "isEmpty": lambda: len(lst) == 0,
        "contains": lambda: args[0] in lst,
        "get": lambda: lst[int(args[0])],
        "add": lambda: lst.append(args[0]),
        "remove": lambda: lst.pop(int(args[0])) if isinstance(args[0], int)
        else lst.remove(args[0]),
        "indexOf": lambda: lst.index(args[0]) if args[0] in lst else -1,
        "sort": lambda: lst.sort(),
        "toString": lambda: str(lst),
    }
    fn = table.get(name)
    if fn is None:
        raise ScriptException(f"unknown List method [{name}]")
    return fn()


def _map_method(m: dict, name: str, args: list):
    table = {
        "containsKey": lambda: args[0] in m,
        "get": lambda: m.get(args[0]),
        "getOrDefault": lambda: m.get(args[0], args[1]),
        "put": lambda: m.__setitem__(args[0], args[1]),
        "remove": lambda: m.pop(args[0], None),
        "keySet": lambda: list(m.keys()),
        "values": lambda: list(m.values()),
        "size": lambda: len(m),
        "isEmpty": lambda: len(m) == 0,
        "entrySet": lambda: [{"key": k, "value": v} for k, v in m.items()],
    }
    fn = table.get(name)
    if fn is None:
        raise ScriptException(f"unknown Map method [{name}]")
    return fn()
