"""Search templates: a from-scratch mustache subset.

The analog of the reference's lang-mustache module
(modules/lang-mustache — MustacheScriptEngine, RestSearchTemplateAction,
RestRenderSearchTemplateAction): templates are strings (or JSON trees
serialized to strings) with {{...}} placeholders, rendered against params
and parsed back to the search body.

Supported syntax (the subset the reference's own tests exercise):
- {{var}} / {{a.b}}      dotted lookups, HTML-escape-free (mustache
                         escaping is meaningless inside JSON)
- {{#toJson}}v{{/toJson}} JSON-encode a param (arrays/objects)
- {{#join}}v{{/join}}     comma-join an array param
- {{#section}}..{{/section}} render when truthy; iterate when a list
- {{^section}}..{{/section}} inverted section
- {{var}}{{^var}}default{{/var}} idiom works through the above
"""

from __future__ import annotations

import json
import re
from typing import Any

from opensearch_tpu.common.errors import IllegalArgumentException

_TAG = re.compile(r"\{\{\s*([#^/]?)\s*([^}]+?)\s*\}\}")


def _lookup(params: Any, path: str) -> Any:
    if path == ".":
        return params
    cur = params
    for part in path.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, list) and part.isdigit():
            cur = cur[int(part)] if int(part) < len(cur) else None
        else:
            return None
        if cur is None:
            return None
    return cur


def _stringify(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (dict, list)):
        return json.dumps(v)
    return str(v)


def render(template: str, params: dict | None) -> str:
    """Render a mustache template string against params."""
    params = params or {}
    out, _pos = _render_block(template, 0, None, params)
    return out


def _render_block(
    tpl: str, pos: int, until: str | None, params: Any
) -> tuple[str, int]:
    """Render until the closing tag `until` (None = end of string).
    Returns (rendered, position after the closing tag)."""
    parts: list[str] = []
    while True:
        m = _TAG.search(tpl, pos)
        if m is None:
            if until is not None:
                raise IllegalArgumentException(
                    f"unclosed mustache section [{until}]"
                )
            parts.append(tpl[pos:])
            return "".join(parts), len(tpl)
        parts.append(tpl[pos: m.start()])
        kind, name = m.group(1), m.group(2)
        pos = m.end()
        if kind == "/":
            if name != until:
                raise IllegalArgumentException(
                    f"mismatched mustache close [{name}], expected [{until}]"
                )
            return "".join(parts), pos
        if kind == "":
            parts.append(_stringify(_lookup(params, name)))
            continue
        # section start: find and render the body
        if name == "toJson":
            body, pos = _render_block(tpl, pos, name, params)
            parts.append(json.dumps(_lookup(params, body.strip())))
            continue
        if name == "join":
            body, pos = _render_block(tpl, pos, name, params)
            v = _lookup(params, body.strip())
            parts.append(",".join(_stringify(x) for x in (v or [])))
            continue
        value = _lookup(params, name)
        if kind == "#":
            if isinstance(value, list):
                # render the body once per element with the element as ctx
                body_start = pos
                rendered, pos = _render_block(tpl, body_start, name, params)
                for item in value:
                    r, _ = _render_block(tpl, body_start, name, item)
                    parts.append(r)
                # drop the params-rendered probe (only used to locate pos)
                _ = rendered
            elif value:
                ctx = value if isinstance(value, dict) else params
                rendered, pos = _render_block(tpl, pos, name, ctx)
                parts.append(rendered)
            else:
                _, pos = _render_block(tpl, pos, name, params)
        else:  # "^" inverted
            if not value or value == []:
                rendered, pos = _render_block(tpl, pos, name, params)
                parts.append(rendered)
            else:
                _, pos = _render_block(tpl, pos, name, params)


def render_search_template(source: Any, params: dict | None) -> dict:
    """Template source (string or JSON tree) -> rendered search body."""
    if isinstance(source, dict):
        source = json.dumps(source)
    rendered = render(str(source), params)
    try:
        return json.loads(rendered)
    except json.JSONDecodeError as e:
        raise IllegalArgumentException(
            f"rendered template is not valid JSON: {e}: {rendered[:200]}"
        ) from e
