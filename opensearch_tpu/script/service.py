"""ScriptService: compile cache + execution contexts.

The analog of server/.../script/ScriptService.java:82 — compile-once cache
keyed by (lang, source), per-context entry points mirroring the reference's
ScriptContext registry (score, field, update, ingest, aggs). The "painless"
language is the interpreter in painless.py; "expression" is accepted as an
alias (numeric-only scripts are a strict subset).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any

from opensearch_tpu.common.errors import IllegalArgumentException
from opensearch_tpu.script.painless import (
    DocView,
    Evaluator,
    ScriptException,
    compile_script,
)

DEFAULT_CACHE_SIZE = 3000


class ScriptService:
    def __init__(self, cache_size: int = DEFAULT_CACHE_SIZE):
        self._cache: OrderedDict[str, Any] = OrderedDict()
        self._cache_size = cache_size
        self.stats = {"compilations": 0, "cache_evictions": 0}

    def compile(self, script: dict | str):
        """script: {"source": ..., "lang": "painless", "params": {...}} or
        bare source string. Returns (ast, params)."""
        if isinstance(script, str):
            source, params = script, {}
        else:
            if "id" in script:
                raise IllegalArgumentException(
                    "stored scripts are not supported yet; use inline source"
                )
            source = script.get("source", "")
            params = script.get("params") or {}
            lang = script.get("lang", "painless")
            if lang not in ("painless", "expression"):
                raise IllegalArgumentException(f"unsupported script lang [{lang}]")
        ast = self._cache.get(source)
        if ast is None:
            ast = compile_script(source)
            self.stats["compilations"] += 1
            self._cache[source] = ast
            if len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)
                self.stats["cache_evictions"] += 1
        else:
            self._cache.move_to_end(source)
        return ast, params

    # -- contexts ----------------------------------------------------------

    def score(self, ast, params: dict, host, doc: int, mapper_service,
              score: float = 0.0) -> float:
        env = {
            "params": params,
            "doc": DocView(host, doc, mapper_service),
            "_score": score,
        }
        out = Evaluator(env).run(ast)
        if out is None:
            raise ScriptException("score script returned null")
        return float(out)

    def field(self, ast, params: dict, host, doc: int, mapper_service,
              source: dict | None = None) -> Any:
        env = {
            "params": params,
            "doc": DocView(host, doc, mapper_service),
        }
        if source is not None:
            env["_source"] = source
        return Evaluator(env).run(ast)

    def execute_update(self, ast, params: dict, ctx: dict) -> dict:
        """update-by-script: ctx = {"_source": {...}, "op": "index", ...};
        the script mutates ctx in place (UpdateHelper semantics)."""
        env = {"params": params, "ctx": ctx}
        Evaluator(env).run(ast)
        return ctx

    def execute_ingest(self, ast, params: dict, doc_source: dict) -> dict:
        """ingest script processor: ctx IS the document source."""
        env = {"params": params, "ctx": doc_source}
        Evaluator(env).run(ast)
        return doc_source


# module-level default instance (the node-singleton the reference wires in
# Node.java; a TpuNode could own one per node — scripts are stateless so a
# process-wide cache is equivalent)
default_script_service = ScriptService()
