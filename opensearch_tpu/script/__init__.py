from opensearch_tpu.script.service import ScriptService, default_script_service

__all__ = ["ScriptService", "default_script_service"]
