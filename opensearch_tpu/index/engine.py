"""The per-shard engine: write path kernel + NRT reader publication.

The analog of InternalEngine
(server/src/main/java/org/opensearch/index/engine/InternalEngine.java:152):

- index/delete ops get a sequence number and a version plan from the live
  version map (dedup + conflict detection, `LiveVersionMap`), are buffered
  in RAM and appended to the translog before being acknowledged
  (InternalEngine.index:863 → indexIntoLucene:1138 + Translog.add:606)
- `refresh` seals the RAM buffer into an immutable HostSegment, publishes
  its padded arrays to device HBM, and swaps the searcher snapshot (the NRT
  reader model); deletes republish the affected segments' live bitmaps
- `flush` = persist segments + a commit point, then roll/trim the translog
  (Lucene commit + CombinedDeletionPolicy analog)
- crash recovery = load last commit, replay translog ops with
  seq_no > commit max_seq_no (TranslogRecoveryRunner)

Searcher snapshots are immutable lists of (host, device) segment pairs —
holding one is the PIT/scroll `ReaderContext` refcount analog.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field as dc_field
from pathlib import Path

from opensearch_tpu.common.errors import (
    OpenSearchTpuException,
    VersionConflictException,
)
from opensearch_tpu.index.device import DeviceSegment, to_device
from opensearch_tpu.index.mapper import MapperService, ParsedDocument
from opensearch_tpu.index.segment import (
    HostSegment,
    SegmentBuilder,
    load_segment,
    save_segment,
)
from opensearch_tpu.index.seqno import LocalCheckpointTracker
from opensearch_tpu.index.translog import Translog


@dataclass
class OpResult:
    doc_id: str
    seq_no: int
    version: int
    created: bool = False
    found: bool = True
    result: str = "created"   # created | updated | deleted | not_found


@dataclass
class VersionEntry:
    seq_no: int
    version: int
    deleted: bool = False


@dataclass
class SearcherSnapshot:
    """Immutable point-in-time view over sealed segments + live masks."""

    segments: list[tuple[HostSegment, DeviceSegment]]
    generation: int

    @property
    def num_docs(self) -> int:
        return sum(h.live_count for h, _ in self.segments)

    @property
    def max_doc(self) -> int:
        return sum(h.n_docs for h, _ in self.segments)


_ENGINE_SEQ = 0


class Engine:
    def __init__(self, path: str | Path, mapper_service: MapperService,
                 durability: str = "request",
                 shard_label: tuple[str, int] | None = None):
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.mapper_service = mapper_service
        # (index name, shard number) for device-residency attribution:
        # every to_device publish below runs inside an upload_scope carrying
        # it, so the ledger's per-structure rows name their owner
        self.shard_label = shard_label
        self.translog = Translog(self.path / "translog")
        # "request" = fsync once per request before ack (the reference's
        # index.translog.durability=REQUEST — TransportWriteAction syncs at
        # the end of the shard bulk, NOT per op); "async" = fsync only on
        # refresh/flush (the sync_interval timer analog)
        self.durability = durability
        self.version_map: dict[str, VersionEntry] = {}
        # process-unique engine identity: cache layers (e.g. the distributed
        # serving bundles) key on it so a deleted+recreated index can never
        # alias a stale cache entry
        global _ENGINE_SEQ
        _ENGINE_SEQ += 1
        self.instance_id = _ENGINE_SEQ
        self._segment_counter = 0
        self._segments: list[tuple[HostSegment, DeviceSegment]] = []
        self._buffer: list[tuple[ParsedDocument, int] | None] = []
        self._buffer_pos: dict[str, int] = {}
        self._refresh_generation = 0
        import uuid as _uuid

        # identity that survives neither delete/recreate nor restart —
        # request-cache keys embed it so recreated indices never collide
        self.engine_uuid = _uuid.uuid4().hex
        self._searcher = SearcherSnapshot([], 0)
        self._dirty_live: set[str] = set()  # segment names needing live republish
        # gap-tracking checkpoint machinery (LocalCheckpointTracker.java):
        # on the primary ops issue+process in order; on a replica fed by a
        # real transport they arrive out of order and the checkpoint must
        # hold at the first unprocessed seq_no
        self.tracker = LocalCheckpointTracker()
        # peer-recovery retention leases (ReplicationTracker.java:104):
        # flush-time translog trimming honors the leased floor so a
        # returning replica can recover by ops replay, not segment copy
        from opensearch_tpu.index.seqno import RetentionLeases

        self.retention_leases = RetentionLeases()
        self._sync_needed = False
        self.stats = {"index_total": 0, "delete_total": 0, "refresh_total": 0,
                      "flush_total": 0, "index_time_ms": 0.0}
        self._recover()

    # -- sequence numbers --------------------------------------------------

    @property
    def max_seq_no(self) -> int:
        return self.tracker.max_seq_no

    @property
    def local_checkpoint(self) -> int:
        return self.tracker.checkpoint

    # -- durability --------------------------------------------------------

    def ensure_synced(self) -> None:
        """Fsync the translog once per REQUEST (possibly covering many ops
        — Translog.java:606 + TransportWriteAction's AsyncAfterWriteAction).
        No-op when nothing was appended since the last sync."""
        if self._sync_needed:
            self.translog.sync()
            self._sync_needed = False

    # -- write path --------------------------------------------------------

    def _check_version(self, doc_id: str, entry, version: int | None,
                       version_type: str) -> None:
        """VersionType.isVersionConflictForWrites semantics."""
        if version is None:
            return
        current = entry.version if entry is not None and not entry.deleted \
            else None
        if version_type == "external":
            if current is not None and version <= current:
                raise VersionConflictException(
                    f"[{doc_id}]: version conflict, current version "
                    f"[{current}] is higher or equal to the one provided "
                    f"[{version}]"
                )
        elif version_type == "external_gte":
            if current is not None and version < current:
                raise VersionConflictException(
                    f"[{doc_id}]: version conflict, current version "
                    f"[{current}] is higher than the one provided "
                    f"[{version}]"
                )
        else:  # internal CAS
            if current is None or current != version:
                raise VersionConflictException(
                    f"[{doc_id}]: version conflict, current version "
                    f"[{current if current is not None else -1}] is "
                    f"different than the one provided [{version}]"
                )

    def index(
        self,
        doc_id: str,
        source: dict,
        routing: str | None = None,
        if_seq_no: int | None = None,
        if_primary_term: int | None = None,
        seq_no: int | None = None,
        version: int | None = None,
        version_type: str = "internal",
    ) -> OpResult:
        """Index one document (InternalEngine.index:863). `seq_no` is set
        only on the replica/recovery replay path."""
        t0 = time.monotonic()
        entry = self.version_map.get(doc_id)
        if if_seq_no is not None:
            current_seq = entry.seq_no if entry and not entry.deleted else -1
            if current_seq != if_seq_no:
                raise VersionConflictException(
                    f"[{doc_id}]: version conflict, required seqNo [{if_seq_no}], "
                    f"current document has seqNo [{current_seq}]"
                )
        self._check_version(doc_id, entry, version, version_type)
        if seq_no is not None and entry is not None and entry.seq_no >= seq_no:
            # stale op on the replica/replay path: a newer op for this doc
            # already applied (reference: per-doc seq_no check in
            # InternalEngine.planIndexingAsNonPrimary — ops may arrive both
            # via recovery dump and concurrent replication fan-out, in
            # either order). Still marked processed: the checkpoint counts
            # seq_nos this copy has ACCOUNTED FOR, including superseded ones
            self.tracker.mark_seq_no_as_processed(seq_no)
            return OpResult(doc_id, seq_no, entry.version, created=False,
                            result="noop")
        parsed = self.mapper_service.parse_document(doc_id, source, routing)
        op_seq = seq_no if seq_no is not None else self.tracker.generate_seq_no()
        created = entry is None or entry.deleted
        if version is not None and version_type in ("external", "external_gte"):
            pass  # external versions are caller-assigned verbatim
        else:
            version = 1 if created else entry.version + 1
        self._delete_from_live_segments(doc_id)
        self._buffer_put(parsed, op_seq)
        self.version_map[doc_id] = VersionEntry(op_seq, version)
        self.translog.add(
            {"op": "index", "id": doc_id, "seq_no": op_seq, "version": version,
             "source": source, "routing": routing}
        )
        self._sync_needed = True
        self.tracker.mark_seq_no_as_processed(op_seq)
        self.stats["index_total"] += 1
        self.stats["index_time_ms"] += (time.monotonic() - t0) * 1e3
        return OpResult(doc_id, op_seq, version, created=created,
                        result="created" if created else "updated")

    def delete(self, doc_id: str, seq_no: int | None = None,
               if_seq_no: int | None = None,
               version: int | None = None,
               version_type: str = "internal") -> OpResult:
        entry = self.version_map.get(doc_id)
        found = (entry is not None and not entry.deleted) or doc_id in self._buffer_pos
        if if_seq_no is not None:
            current_seq = entry.seq_no if entry and not entry.deleted else -1
            if current_seq != if_seq_no:
                raise VersionConflictException(
                    f"[{doc_id}]: version conflict, required seqNo "
                    f"[{if_seq_no}], current document has seqNo [{current_seq}]"
                )
        self._check_version(doc_id, entry, version, version_type)
        if seq_no is not None and entry is not None and entry.seq_no >= seq_no:
            # stale op (see index()): ignore, a newer op already applied
            self.tracker.mark_seq_no_as_processed(seq_no)
            return OpResult(doc_id, seq_no, entry.version, found=False,
                            result="noop")
        op_seq = seq_no if seq_no is not None else self.tracker.generate_seq_no()
        if version is not None and version_type in ("external", "external_gte"):
            pass  # caller-assigned external version
        else:
            version = (entry.version + 1) if entry else 1
        self._buffer_remove(doc_id)
        self._delete_from_live_segments(doc_id)
        self.version_map[doc_id] = VersionEntry(op_seq, version, deleted=True)
        self.translog.add(
            {"op": "delete", "id": doc_id, "seq_no": op_seq, "version": version}
        )
        self._sync_needed = True
        self.tracker.mark_seq_no_as_processed(op_seq)
        self.stats["delete_total"] += 1
        return OpResult(doc_id, op_seq, version, found=found,
                        result="deleted" if found else "not_found")

    def _buffer_put(self, parsed: ParsedDocument, seq_no: int) -> None:
        pos = self._buffer_pos.get(parsed.doc_id)
        if pos is not None:
            self._buffer[pos] = None  # supersede older buffered version
        self._buffer_pos[parsed.doc_id] = len(self._buffer)
        self._buffer.append((parsed, seq_no))

    def _buffer_remove(self, doc_id: str) -> None:
        pos = self._buffer_pos.pop(doc_id, None)
        if pos is not None:
            self._buffer[pos] = None

    def _delete_from_live_segments(self, doc_id: str) -> None:
        for host, _dev in self._segments:
            if host.delete_doc(doc_id):
                self._dirty_live.add(host.name)

    # -- read path ---------------------------------------------------------

    def get(self, doc_id: str, realtime: bool = True) -> dict | None:
        """Realtime GET (index/get in the reference: reads through the
        version map + buffer without waiting for refresh). realtime=False
        reads only what the last refresh made searchable."""
        entry = self.version_map.get(doc_id)
        if realtime and entry is not None and entry.deleted:
            return None
        pos = self._buffer_pos.get(doc_id) if realtime else None
        if pos is not None and self._buffer[pos] is not None:
            parsed, seq = self._buffer[pos]
            return {"_source": parsed.source, "_seq_no": seq,
                    "_version": entry.version if entry else 1,
                    "_routing": parsed.routing}
        for host, _dev in self._segments:
            d = host.local_doc(doc_id)
            if d is not None:
                return {"_source": json.loads(host.sources[d]),
                        "_seq_no": entry.seq_no if entry else -1,
                        "_version": entry.version if entry else 1,
                        "_routing": host.doc_routings[d]}
        return None

    def acquire_searcher(self) -> SearcherSnapshot:
        return self._searcher

    # -- device residency ---------------------------------------------------

    def _upload_scope(self):
        """Attribution scope for every device publish this engine makes:
        the residency ledger's (index, shard, generation) columns come from
        here (see telemetry/device_ledger.upload_scope)."""
        from opensearch_tpu.telemetry.device_ledger import upload_scope

        index, shard = self.shard_label or (f"engine:{self.instance_id}", 0)
        return upload_scope(index=index, shard=shard,
                            generation=self._refresh_generation + 1)

    @staticmethod
    def _retire_devices(pairs, reason: str) -> None:
        """Free the ledger allocations of retired (host, dev) pairs. Old
        searcher snapshots (scroll/PIT) may still pin the arrays briefly —
        the ledger tracks the PUBLISHED set, which these just left."""
        for _host, dev in pairs:
            free = getattr(dev, "free_allocations", None)
            if free is not None:
                free(reason=reason)

    # -- refresh / flush ---------------------------------------------------

    def refresh(self) -> SearcherSnapshot:
        """Seal the RAM buffer into a new segment + republish live masks."""
        # async durability: the refresh cadence doubles as the fsync timer
        # (index.translog.sync_interval analog); no-op under request
        # durability where every ack already synced
        self.ensure_synced()
        live_buffer = [e for e in self._buffer if e is not None]
        if live_buffer:
            self._segment_counter += 1
            self.stats["segments_built"] = self.stats.get("segments_built", 0) + 1
            builder = SegmentBuilder(self.mapper_service, f"_{self._segment_counter}")
            for parsed, seq in live_buffer:
                builder.add(parsed, seq)
            host = builder.build()
            # stamp per-doc versions at seal time (version doc-values)
            import numpy as _np

            host.doc_versions = _np.asarray(
                [self.version_map[d].version if d in self.version_map else 1
                 for d in host.doc_ids], _np.int64,
            )
            with self._upload_scope():
                dev = to_device(host)
            self._segments.append((host, dev))
            self._buffer = []
            self._buffer_pos = {}
        if self._dirty_live:
            with self._upload_scope():
                self._segments = [
                    (h, d.with_live(h.live) if h.name in self._dirty_live
                     else d)
                    for h, d in self._segments
                ]
            self._dirty_live.clear()
        self._maybe_merge()
        self._refresh_generation += 1
        self._searcher = SearcherSnapshot(list(self._segments), self._refresh_generation)
        self.stats["refresh_total"] += 1
        return self._searcher

    # -- merging -----------------------------------------------------------
    #
    # The OpenSearchConcurrentMergeScheduler + TieredMergePolicy analog
    # (InternalEngine.java:152, CombinedDeletionPolicy). Without merging
    # every refresh adds a segment forever: per-segment device dispatch
    # overhead grows without bound and deleted docs are never reclaimed.
    # The TPU model: merges happen on HOST (rebuild packed arrays from the
    # live docs of the source segments), then the merged segment is
    # republished to device HBM and the next searcher snapshot swaps it in.
    # Old snapshots (scroll/PIT) keep their references to the merged-away
    # segments — immutability gives the IndexReader refcount semantics for
    # free; the arrays are dropped when the last snapshot dies.

    MAX_SEGMENTS_BEFORE_MERGE = 10  # segments_per_tier analog
    MERGE_FACTOR = 8                # how many smallest segments fuse per pass

    def _maybe_merge(self) -> None:
        """Background-merge policy, run synchronously at refresh time (the
        single-writer engine's scheduler): when the tier overflows, fuse the
        MERGE_FACTOR smallest segments into one."""
        if len(self._segments) <= self.MAX_SEGMENTS_BEFORE_MERGE:
            return
        by_size = sorted(self._segments, key=lambda hd: int(hd[0].live.sum()))
        self._merge_segments([h.name for h, _ in by_size[: self.MERGE_FACTOR]])

    def force_merge(self, max_num_segments: int = 1,
                    only_expunge_deletes: bool = False) -> dict:
        """POST /{index}/_forcemerge — fuse down to max_num_segments (or
        just rewrite segments carrying tombstones)."""
        self.refresh()
        if not only_expunge_deletes:
            while len(self._segments) > max(1, int(max_num_segments)):
                n_fuse = len(self._segments) - max(1, int(max_num_segments)) + 1
                by_size = sorted(self._segments,
                                 key=lambda hd: int(hd[0].live.sum()))
                self._merge_segments([h.name for h, _ in by_size[:n_fuse]])
        # a force merge always rewrites tombstone-carrying segments, even
        # at/below the target count (Lucene's forceMerge drops deletes in
        # every segment it touches)
        victims = [h.name for h, _ in self._segments
                   if int(h.live.sum()) < h.n_docs]
        if victims:
            self._merge_segments(victims)
        self._refresh_generation += 1
        self._searcher = SearcherSnapshot(list(self._segments),
                                          self._refresh_generation)
        return {"segments": len(self._segments)}

    def _merge_segments(self, names: list[str]) -> None:
        """Fuse the named segments into one new segment holding only their
        live docs. Docs are re-packed via the mapper (host-side rebuild —
        the analyze cost is the merge cost, paid off the query path);
        seal-time seq_nos/versions/routings carry over from the sources."""
        names_set = set(names)
        chosen = [(h, d) for h, d in self._segments if h.name in names_set]
        keep = [(h, d) for h, d in self._segments if h.name not in names_set]
        live_total = sum(int(h.live.sum()) for h, _ in chosen)
        if not chosen:
            return
        if live_total == 0:
            # pure-tombstone segments simply drop
            self._segments = keep
            self._retire_devices(chosen, reason="merged")
            self._dirty_live -= {h.name for h, _ in chosen}
            self.stats["merge_total"] = self.stats.get("merge_total", 0) + 1
            return
        self._segment_counter += 1
        self.stats["segments_built"] = self.stats.get("segments_built", 0) + 1
        builder = SegmentBuilder(self.mapper_service,
                                 f"_{self._segment_counter}")
        versions: list[int] = []
        for host, _dev in chosen:
            for d in range(host.n_docs):
                if not host.live[d]:
                    continue  # tombstone reclaim
                parsed = self.mapper_service.parse_document(
                    host.doc_ids[d], json.loads(host.sources[d]),
                    host.doc_routings[d] if host.doc_routings else None,
                )
                builder.add(parsed, int(host.doc_seq_nos[d]))
                versions.append(int(host.doc_versions[d]))
        merged = builder.build()
        import numpy as _np

        merged.doc_versions = _np.asarray(versions, _np.int64)
        with self._upload_scope():
            self._segments = keep + [(merged, to_device(merged))]
        self._retire_devices(chosen, reason="merged")
        self._dirty_live -= {h.name for h, _ in chosen}
        self.stats["merge_total"] = self.stats.get("merge_total", 0) + 1

    def _commit_signature(self) -> tuple:
        import hashlib

        return (
            self.tracker.max_seq_no,
            tuple(
                (h.name, hashlib.sha1(h.live.tobytes()).hexdigest())
                for h, _ in self._segments
            ),
        )

    def flush(self) -> None:
        """Commit: refresh, persist segments + commit point, roll translog.
        A no-change flush is skipped entirely (Lucene's IndexWriter.commit
        no-op) so repeated snapshots of an idle shard produce byte-identical
        files for the repository's content-addressed dedup."""
        self.refresh()
        sig = self._commit_signature()
        if sig == getattr(self, "_last_flush_sig", None) and (
            self.path / "commit.json"
        ).exists():
            return
        seg_dir = self.path / "segments"
        prev_seg_lives = dict(getattr(self, "_last_flush_sig", (None, ()))[1])
        cur_seg_lives = dict(sig[1])  # (name, live-digest) pairs from sig
        for host, _dev in self._segments:
            if (seg_dir / f"{host.name}.json").exists() and (
                prev_seg_lives.get(host.name) == cur_seg_lives[host.name]
            ):
                continue  # unchanged since last commit
            save_segment(host, seg_dir)
        commit = {
            "segments": [h.name for h, _ in self._segments],
            "max_seq_no": self.tracker.max_seq_no,
            "local_checkpoint": self.local_checkpoint,
            "segment_counter": self._segment_counter,
            "translog_generation": self.translog.current_generation + 1,
            "retention_leases": self.retention_leases.to_dict(),
            "version_map": {
                doc_id: [e.seq_no, e.version, e.deleted]
                for doc_id, e in self.version_map.items()
            },
        }
        tmp = self.path / "commit.json.tmp"
        with open(tmp, "w") as f:
            json.dump(commit, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path / "commit.json")
        # merged-away segments are no longer referenced by any commit:
        # delete their files (CombinedDeletionPolicy keeping only commits
        # the translog/snapshots still need — here: just the latest)
        current = {h.name for h, _ in self._segments}
        for f in seg_dir.glob("_*"):
            stem = f.name.split(".")[0]
            if stem not in current:
                f.unlink(missing_ok=True)
        self.translog.roll_generation()
        # flush is the periodic hook where stale leases (holder gone >12h
        # without a renewal) stop pinning history
        self.retention_leases.expire(int(time.time() * 1000))
        self.translog.trim_below(
            self.translog.current_generation,
            min_retained_seq=self.retention_leases.min_retained_seq_no(),
        )
        self._last_flush_sig = sig
        self.stats["flush_total"] += 1

    # -- segment replication (NRTReplicationEngine analog) ------------------
    #
    # In SEGMENT replication mode a replica never indexes documents: writes
    # only append to its translog (durability + promotion source), and
    # searchable state arrives as sealed immutable segment bundles published
    # by the primary after refresh (indices/replication/
    # SegmentReplicationTargetService.java:66, onNewCheckpoint:298; the
    # replica engine swap is NRTReplicationEngine's updateSegments).

    def segment_names(self) -> list[str]:
        return [h.name for h, _ in self._segments]

    def segment_sigs(self) -> dict[str, list[int]]:
        """Cheap per-segment content signature for checkpoint diffs: two
        copies may hold same-NAME segments with different content (a
        crash-restarted replica rebuilds a bootstrap segment from its
        translog); the signature distinguishes them. Equal signatures mean
        the segments cover the same ops — equivalent for serving."""
        return {
            h.name: [h.n_docs, int(h.min_seq_no), int(h.max_seq_no),
                     int(h.live.sum())]
            for h, _ in self._segments
        }

    def append_translog_op(self, op: dict) -> None:
        """Replica-side durability for a replicated write without indexing
        (segment-replication replicas)."""
        self.translog.add(op)
        self._sync_needed = True
        self.tracker.mark_seq_no_as_processed(int(op["seq_no"]))
        if op.get("op") == "index":
            self.stats["index_total"] += 1
        else:
            self.stats["delete_total"] += 1

    def install_replicated_segments(
        self, new_hosts: list, order: list[str]
    ) -> None:
        """Swap in the primary's segment set: keep local copies of
        unchanged segments, adopt the new ones, drop segments the primary
        no longer has (merged away). `order` is the primary's full segment
        name list — the replica mirrors it exactly so doc-id tie-breaks and
        segment ordering match across copies."""
        existing = {h.name: (h, d) for h, d in self._segments}
        old_devs = {id(d): (h, d) for h, d in self._segments}
        with self._upload_scope():
            for host in new_hosts:
                existing[host.name] = (host, to_device(host))
        self._segments = [existing[n] for n in order if n in existing]
        # replaced same-name copies and merged-away segments the primary
        # dropped both leave the published set: release their residency
        kept = {id(d) for _h, d in self._segments}
        self._retire_devices(
            [pair for oid, pair in old_devs.items() if oid not in kept],
            reason="replicated-install",
        )
        # seal-time doc columns refresh the version map so realtime GET and
        # seq-no stale checks see replicated docs — only the NEWLY adopted
        # hosts need scanning (kept segments were processed on first install)
        for host in new_hosts:
            for d in range(host.n_docs):
                if not host.live[d]:
                    continue
                doc_id = host.doc_ids[d]
                seq = int(host.doc_seq_nos[d])
                cur = self.version_map.get(doc_id)
                if cur is None or cur.seq_no < seq:
                    self.version_map[doc_id] = VersionEntry(
                        seq, int(host.doc_versions[d])
                    )
                self.tracker.mark_seq_no_as_processed(seq)
        # buffered ops now covered by an installed segment must not build a
        # duplicate local segment at the next refresh
        for doc_id, pos in list(self._buffer_pos.items()):
            entry = self._buffer[pos]
            if entry is None:
                self._buffer_pos.pop(doc_id, None)
                continue
            vm = self.version_map.get(doc_id)
            if vm is not None and vm.seq_no >= entry[1]:
                self._buffer[pos] = None
                self._buffer_pos.pop(doc_id, None)
        if not self._buffer_pos:
            self._buffer = []
        # keep the segment counter ahead of adopted names so a promoted
        # replica never reuses a replicated segment's name
        for name in order:
            try:
                self._segment_counter = max(
                    self._segment_counter, int(name.lstrip("_").split(".")[0])
                )
            except ValueError:
                pass
        self._refresh_generation += 1
        self._searcher = SearcherSnapshot(
            list(self._segments), self._refresh_generation
        )
        self.stats["refresh_total"] += 1

    def translog_tail_ops(self) -> list[dict]:
        """Ops since the last flush (the translog tail a recovering segrep
        replica needs for durability/promotion completeness). Syncs first:
        under async durability recently acked ops may still be unsynced,
        and read_ops truncates at the fsynced checkpoint — a recovery dump
        must never miss acked ops."""
        self.translog.sync()
        self._sync_needed = False
        return list(self.translog.read_ops())

    def history_ops_from(self, from_seq_no: int) -> list[dict] | None:
        """Retained history ops with seq_no >= from_seq_no, in order —
        or None when the translog no longer covers that point (history was
        trimmed past it; the caller must fall back to a segment copy).
        The ops-based recovery source (RecoverySourceHandler phase2-only,
        .../indices/recovery/RecoverySourceHandler.java:171)."""
        if from_seq_no > self.tracker.max_seq_no:
            return []
        if not self.retention_leases.covers(from_seq_no):
            return None
        self.translog.sync()
        ops = [op for op in self.translog.read_ops()
               if int(op.get("seq_no", -1)) >= from_seq_no]
        covered = {int(op["seq_no"]) for op in ops}
        # every needed seq_no must be present (gaps mean trimmed history)
        if any(s not in covered
               for s in range(from_seq_no, self.tracker.max_seq_no + 1)):
            return None
        return sorted(ops, key=lambda o: int(o["seq_no"]))

    def replay_translog_tail(self) -> int:
        """Promotion of a segment-replication replica: index any translog
        ops not yet reflected in the engine (the per-doc seq_no stale check
        dedups ops already covered by replicated segments)."""
        replayed = 0
        for op in self.translog.read_ops():
            if op["op"] == "index":
                r = self.index(op["id"], op["source"], op.get("routing"),
                               seq_no=op["seq_no"])
            else:
                r = self.delete(op["id"], seq_no=op["seq_no"])
            if r.result != "noop":
                replayed += 1
        return replayed

    # -- recovery ----------------------------------------------------------

    def _recover(self) -> None:
        commit_path = self.path / "commit.json"
        replay_from_seq = -1
        if commit_path.exists():
            commit = json.loads(commit_path.read_text())
            seg_dir = self.path / "segments"
            with self._upload_scope():
                for name in commit["segments"]:
                    host = load_segment(seg_dir, name)
                    self._segments.append((host, to_device(host)))
            self.tracker = LocalCheckpointTracker(
                max_seq_no=commit["max_seq_no"],
                local_checkpoint=commit["local_checkpoint"],
            )
            self._segment_counter = commit["segment_counter"]
            self.version_map = {
                doc_id: VersionEntry(seq, ver, deleted)
                for doc_id, (seq, ver, deleted) in commit["version_map"].items()
            }
            if commit.get("retention_leases"):
                from opensearch_tpu.index.seqno import RetentionLeases

                self.retention_leases = RetentionLeases.from_dict(
                    commit["retention_leases"])
            replay_from_seq = commit["max_seq_no"]
        replayed = 0
        for op in self.translog.read_ops():
            if int(op["seq_no"]) <= replay_from_seq:
                continue
            if op["op"] == "index":
                parsed = self.mapper_service.parse_document(
                    op["id"], op["source"], op.get("routing")
                )
                self.tracker.mark_seq_no_as_processed(op["seq_no"])
                self._delete_from_live_segments(op["id"])
                self._buffer_put(parsed, op["seq_no"])
                self.version_map[op["id"]] = VersionEntry(op["seq_no"], op["version"])
            else:
                self.tracker.mark_seq_no_as_processed(op["seq_no"])
                self._buffer_remove(op["id"])
                self._delete_from_live_segments(op["id"])
                self.version_map[op["id"]] = VersionEntry(
                    op["seq_no"], op["version"], deleted=True
                )
            replayed += 1
        if self._segments or replayed:
            self.refresh()
        if commit_path.exists() and replayed == 0:
            # recovered state matches the on-disk commit exactly: remember
            # its signature so the next no-change flush skips file rewrites
            # (keeps snapshot dedup byte-stable across restarts)
            self._last_flush_sig = self._commit_signature()

    # -- stats / lifecycle -------------------------------------------------

    @property
    def num_docs(self) -> int:
        buffered = len([e for e in self._buffer if e is not None])
        return buffered + sum(h.live_count for h, _ in self._segments)

    def segment_stats(self) -> dict:
        return {
            "count": len(self._segments),
            "docs": sum(h.n_docs for h, _ in self._segments),
            "live_docs": sum(h.live_count for h, _ in self._segments),
            "buffered_docs": len([e for e in self._buffer if e is not None]),
        }

    def close(self) -> None:
        self.translog.close()
        # release the published set's device-residency entries (shard
        # removal, index delete, node shutdown all land here)
        self._retire_devices(self._segments, reason="closed")
