"""Mappings: field types, document parsing, dynamic mapping.

The analog of the reference's mapper layer
(server/src/main/java/org/opensearch/index/mapper/ — MapperService,
DocumentMapper, DocumentParser.java:66, MappedFieldType subclasses): a
MapperService owns the schema for one index, parses JSON documents into typed
per-field values ("LuceneDocument fields" become typed column/posting inputs
for the segment builder), infers mappings dynamically, and validates merges.

Field value encodings chosen for the TPU segment layout:
- text      -> analyzed terms (postings + doc length norm)
- keyword   -> ordinal doc-values + exact-term postings
- long/integer/short/byte/date -> int64 doc-values column
- double/float/half_float      -> float64 doc-values column
- boolean   -> int64 column (0/1)
- dense_vector -> row in the segment's [n, dims] matrix
"""

from __future__ import annotations

import datetime as _dt
import math
from dataclasses import dataclass, field as dc_field
from typing import Any

from opensearch_tpu.common.errors import (
    IllegalArgumentException,
    MapperParsingException,
    StrictDynamicMappingException,
)
from opensearch_tpu.index.analysis import AnalysisRegistry, Analyzer

INT_TYPES = {"long", "integer", "short", "byte"}
FLOAT_TYPES = {"double", "float", "half_float"}
NUMERIC_TYPES = INT_TYPES | FLOAT_TYPES

_INT_RANGES = {
    "long": (-(2**63), 2**63 - 1),
    "integer": (-(2**31), 2**31 - 1),
    "short": (-(2**15), 2**15 - 1),
    "byte": (-(2**7), 2**7 - 1),
}


@dataclass
class FieldMapper:
    """One mapped field (a MappedFieldType + its Mapper in the reference)."""

    name: str
    type: str
    analyzer: str = "standard"
    search_analyzer: str | None = None
    index: bool = True
    doc_values: bool = True
    store: bool = False
    # dense_vector
    dims: int = 0
    similarity: str = "l2_norm"  # l2_norm | cosine | dot_product
    # ANN method config (k-NN plugin style): {"name": "ivf_pq",
    # "parameters": {"nlist": .., "m": .., "nprobe": ..}}; None = exact
    method: dict | None = None
    # original type was "completion" (stored keyword-style; the suggester
    # prefix-matches its values and object-form {input, weight} is accepted)
    completion: bool = False
    # date
    format: str = "strict_date_optional_time||epoch_millis"
    # extra sub-fields ("fields": {"raw": {"type": "keyword"}})
    fields: dict[str, "FieldMapper"] = dc_field(default_factory=dict)

    def to_dict(self) -> dict:
        out: dict[str, Any] = {
            "type": "completion" if self.completion else self.type
        }
        if self.type == "text" and self.analyzer != "standard":
            out["analyzer"] = self.analyzer
        if self.search_analyzer and self.search_analyzer != self.analyzer:
            out["search_analyzer"] = self.search_analyzer
        if self.type == "dense_vector" or self.type == "knn_vector":
            out["dims"] = self.dims
            out["similarity"] = self.similarity
            if self.method:
                out["method"] = self.method
        if not self.index:
            out["index"] = False
        if self.fields:
            out["fields"] = {n: m.to_dict() for n, m in self.fields.items()}
        return out


@dataclass
class ParsedField:
    """Typed value(s) extracted from one document field."""

    terms: list[str] | None = None        # text: analyzed term stream
    exact: list[str] | None = None        # keyword: untokenized values
    numeric: list[float] | None = None    # numeric/date/boolean column values
    vector: list[float] | None = None     # dense_vector row


@dataclass
class ParsedDocument:
    doc_id: str
    source: dict
    fields: dict[str, ParsedField]
    routing: str | None = None


# epoch range guard so dates stay in int64 millis
_MAX_MILLIS = 2**62


def parse_date_millis(value: Any) -> int:
    """strict_date_optional_time || epoch_millis, like the reference default."""
    if isinstance(value, bool):
        raise ValueError("booleans are not dates")
    if isinstance(value, (int, float)):
        v = int(value)
        if abs(v) > _MAX_MILLIS:
            raise ValueError(f"epoch_millis out of range: {value}")
        return v
    s = str(value).strip()
    if s.lstrip("-").isdigit():
        return int(s)
    # ISO-8601 family
    txt = s.replace("Z", "+00:00")
    try:
        dt = _dt.datetime.fromisoformat(txt)
    except ValueError:
        # date-only variants fromisoformat already handles in 3.11+; re-raise
        raise ValueError(f"failed to parse date field [{s}]")
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * 1000)


def _parse_boolean(value: Any) -> int:
    if isinstance(value, bool):
        return 1 if value else 0
    if isinstance(value, str):
        if value == "true":
            return 1
        if value == "false" or value == "":
            return 0
    raise ValueError(f"failed to parse boolean [{value!r}]")


class MapperService:
    """Schema owner for one index (MapperService + DocumentParser)."""

    def __init__(
        self,
        mappings: dict | None = None,
        analysis_registry: AnalysisRegistry | None = None,
    ):
        self.analysis = analysis_registry or AnalysisRegistry()
        self.mappers: dict[str, FieldMapper] = {}
        self.dynamic: str | bool = True  # True | False | "strict"
        self._source_enabled = True
        if mappings:
            self.merge(mappings)

    # -- mapping CRUD ------------------------------------------------------

    def merge(self, mappings: dict) -> None:
        """Apply a mappings dict {"properties": {...}, "dynamic": ...}."""
        if "dynamic" in mappings:
            d = mappings["dynamic"]
            if d not in (True, False, "true", "false", "strict"):
                raise MapperParsingException(f"invalid dynamic value [{d}]")
            self.dynamic = {"true": True, "false": False}.get(d, d)
        src = mappings.get("_source")
        if isinstance(src, dict) and "enabled" in src:
            self._source_enabled = bool(src["enabled"])
        for name, conf in (mappings.get("properties") or {}).items():
            self._merge_field("", name, conf)

    def _merge_field(self, prefix: str, name: str, conf: dict) -> None:
        full = f"{prefix}{name}"
        if "properties" in conf and "type" not in conf:
            # object field: flatten children with dotted names
            for child, child_conf in conf["properties"].items():
                self._merge_field(f"{full}.", child, child_conf)
            return
        ftype = conf.get("type")
        if ftype is None:
            raise MapperParsingException(f"no type specified for field [{full}]")
        if ftype == "knn_vector":  # k-NN plugin compat alias
            ftype = "dense_vector"
        known = (
            {"text", "keyword", "date", "boolean", "dense_vector",
             "match_only_text", "completion", "search_as_you_type"}
            | NUMERIC_TYPES
        )
        if ftype not in known:
            raise MapperParsingException(
                f"No handler for type [{ftype}] declared on field [{full}]"
            )
        if ftype in ("match_only_text", "search_as_you_type"):
            ftype = "text"
        is_completion = ftype == "completion"
        if is_completion:
            # completion inputs are stored whole like keywords; the suggester
            # prefix-matches over the keyword ordinals (the FST analog)
            ftype = "keyword"
        mapper = FieldMapper(
            name=full,
            type=ftype,
            completion=is_completion,
            analyzer=conf.get("analyzer", "standard"),
            search_analyzer=conf.get("search_analyzer"),
            index=conf.get("index", True),
            doc_values=conf.get("doc_values", True),
            store=conf.get("store", False),
            dims=int(conf.get("dims", conf.get("dimension", 0))),
            similarity=conf.get("similarity", conf.get("space_type", "l2_norm")),
            method=conf.get("method") if isinstance(conf.get("method"), dict) else None,
            format=conf.get("format", "strict_date_optional_time||epoch_millis"),
        )
        if ftype == "dense_vector" and mapper.dims <= 0:
            raise MapperParsingException(
                f"dense_vector field [{full}] requires positive [dims]"
            )
        existing = self.mappers.get(full)
        if existing is not None and existing.type != mapper.type:
            raise IllegalArgumentException(
                f"mapper [{full}] cannot be changed from type "
                f"[{existing.type}] to [{mapper.type}]"
            )
        # multi-fields
        for sub, sub_conf in (conf.get("fields") or {}).items():
            self._merge_field(f"{full}.", sub, sub_conf)
        self.mappers[full] = mapper

    def field_mapper(self, name: str) -> FieldMapper | None:
        return self.mappers.get(name)

    def to_dict(self) -> dict:
        props: dict[str, Any] = {}
        for name, m in sorted(self.mappers.items()):
            # re-nest dotted names into object properties
            parts = name.split(".")
            node = props
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node[parts[-1]] = m.to_dict()
        out: dict[str, Any] = {"properties": props}
        if self.dynamic is not True:
            out["dynamic"] = self.dynamic
        return out

    # -- document parsing --------------------------------------------------

    def _analyzer_for(self, mapper: FieldMapper, search: bool = False) -> Analyzer:
        name = (mapper.search_analyzer if search else None) or mapper.analyzer
        return self.analysis.get(name)

    def parse_document(
        self, doc_id: str, source: dict, routing: str | None = None
    ) -> ParsedDocument:
        """DocumentParser.parseDocument:78 — JSON → typed field values,
        applying dynamic mapping for unseen fields."""
        fields: dict[str, ParsedField] = {}
        self._parse_object(source, "", fields)
        return ParsedDocument(doc_id=doc_id, source=source, fields=fields, routing=routing)

    def _parse_object(self, obj: dict, prefix: str, out: dict[str, ParsedField]) -> None:
        for key, value in obj.items():
            full = f"{prefix}{key}"
            if isinstance(value, dict):
                mapper = self.mappers.get(full)
                if mapper is not None and mapper.type == "dense_vector":
                    raise MapperParsingException(
                        f"dense_vector field [{full}] must be an array of numbers"
                    )
                if mapper is not None and mapper.completion:
                    # completion object form: {"input": str|[str], "weight": N}
                    inputs = value.get("input")
                    if inputs is None:
                        raise MapperParsingException(
                            f"completion field [{full}] object form requires [input]"
                        )
                    if isinstance(inputs, str):
                        inputs = [inputs]
                    self._parse_value(mapper, full, inputs, out)
                    continue
                self._parse_object(value, f"{full}.", out)
                continue
            mapper = self.mappers.get(full)
            if mapper is None:
                mapper = self._dynamic_mapper(full, value)
                if mapper is None:
                    continue  # dynamic: false -> ignore; strict raises inside
                self.mappers[full] = mapper
            self._parse_value(mapper, full, value, out)

    def _dynamic_mapper(self, name: str, value: Any) -> FieldMapper | None:
        if self.dynamic == "strict":
            raise StrictDynamicMappingException(
                f"mapping set to strict, dynamic introduction of [{name}] is not allowed"
            )
        if self.dynamic is False:
            return None
        if isinstance(value, bool):
            return FieldMapper(name, "boolean")
        if isinstance(value, int):
            return FieldMapper(name, "long")
        if isinstance(value, float):
            return FieldMapper(name, "float")
        if isinstance(value, str):
            try:
                parse_date_millis(value)
                if not value.lstrip("-").isdigit():
                    return FieldMapper(name, "date")
            except ValueError:
                pass
            # dynamic strings get text + .keyword sub-field, like the reference
            kw = FieldMapper(f"{name}.keyword", "keyword")
            self.mappers[f"{name}.keyword"] = kw
            return FieldMapper(name, "text")
        if isinstance(value, list):
            if value and all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in value):
                # plain numeric array -> numeric field (NOT dense_vector: the
                # reference requires explicit mapping for vectors)
                if all(isinstance(v, int) for v in value):
                    return FieldMapper(name, "long")
                return FieldMapper(name, "float")
            for v in value:
                if v is not None:
                    return self._dynamic_mapper(name, v)
            return None
        if value is None:
            return None
        raise MapperParsingException(f"cannot infer mapping for [{name}]={value!r}")

    def _parse_value(
        self, mapper: FieldMapper, name: str, value: Any, out: dict[str, ParsedField]
    ) -> None:
        if value is None:
            return
        values = value if isinstance(value, list) else [value]
        pf = out.setdefault(name, ParsedField())
        try:
            if mapper.type == "text":
                analyzer = self._analyzer_for(mapper)
                terms: list[str] = pf.terms or []
                for v in values:
                    if v is None:
                        continue
                    terms.extend(analyzer.analyze(str(v)))
                pf.terms = terms
            elif mapper.type == "keyword":
                exact = pf.exact or []
                exact.extend(str(v) for v in values if v is not None)
                pf.exact = exact
            elif mapper.type in NUMERIC_TYPES:
                nums = pf.numeric or []
                for v in values:
                    if v is None:
                        continue
                    if isinstance(v, bool):
                        raise ValueError("booleans are not numbers")
                    x = float(v)
                    if mapper.type in INT_TYPES:
                        if not float(v).is_integer() and not isinstance(v, int):
                            # the reference rejects "3.5" for integer types
                            raise ValueError(f"[{v}] is not an integer")
                        lo, hi = _INT_RANGES[mapper.type]
                        if not (lo <= int(v) <= hi):
                            raise ValueError(f"[{v}] out of range for [{mapper.type}]")
                        x = float(int(v))
                    elif not math.isfinite(x):
                        raise ValueError(f"[{v}] is not finite")
                    nums.append(x)
                pf.numeric = nums
            elif mapper.type == "date":
                nums = pf.numeric or []
                nums.extend(float(parse_date_millis(v)) for v in values if v is not None)
                pf.numeric = nums
            elif mapper.type == "boolean":
                nums = pf.numeric or []
                nums.extend(float(_parse_boolean(v)) for v in values if v is not None)
                pf.numeric = nums
            elif mapper.type == "dense_vector":
                if pf.vector is not None:
                    raise ValueError("multiple vectors for one field")
                vec = [float(v) for v in values]
                if len(vec) != mapper.dims:
                    raise ValueError(
                        f"vector length {len(vec)} != dims {mapper.dims}"
                    )
                pf.vector = vec
            else:  # pragma: no cover
                raise ValueError(f"unhandled type [{mapper.type}]")
        except (ValueError, TypeError) as e:
            raise MapperParsingException(
                f"failed to parse field [{name}] of type [{mapper.type}]: {e}"
            ) from e

    def analyze_query_text(self, field: str, text: str) -> list[str]:
        """Analyze query text with the field's search analyzer (match query)."""
        mapper = self.mappers.get(field)
        if mapper is None or mapper.type != "text":
            return [text]
        return self._analyzer_for(mapper, search=True).analyze(str(text))
